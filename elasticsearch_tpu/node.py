"""Node — the service container and lifecycle.

Reference: core/node/Node.java:129-315 — module assembly (:161-198), ordered
start (:230-275: indices → cluster → search → discovery → gateway → http).
One Node owns: persisted cluster state (gateway), ClusterService,
IndicesService (reconciler), SearchService, and the document/bulk action
entry points (the action layer, core/action/) that the REST layer and the
Python client both call — mirroring how NodeClient and RestController share
TransportAction instances.
"""

from __future__ import annotations

import time
import uuid
from pathlib import Path

from elasticsearch_tpu.cluster.allocation import AllocationService
from elasticsearch_tpu.cluster.service import URGENT, ClusterService
from elasticsearch_tpu.cluster.state import (
    ClusterState, IndexMetadata, RoutingTable)
from elasticsearch_tpu.common.errors import DocumentMissingError
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.engine import MATCH_ANY
from elasticsearch_tpu.search.service import SearchService
from elasticsearch_tpu.transport import (
    DiscoveryNode, LocalTransport, LocalTransportHub, TransportService)


class Node:
    def __init__(self, settings: Settings | dict | None = None,
                 data_path: str | Path | None = None,
                 transport_hub: LocalTransportHub | None = None):
        if not isinstance(settings, Settings):
            settings = Settings(settings or {})
        self.settings = settings
        self.node_id = uuid.uuid4().hex[:20]
        self.node_name = settings.get("node.name", f"node-{self.node_id[:7]}")
        self.data_path = Path(data_path or settings.get("path.data", "data"))
        self.data_path.mkdir(parents=True, exist_ok=True)
        self._hub = transport_hub
        self._started = False

    # ---- lifecycle (Node.start order, core/node/Node.java:230-275) ---------

    SHARD_STARTED_ACTION = "internal:cluster/shard/started"
    SHARD_FAILED_ACTION = "internal:cluster/shard/failure"

    def start(self) -> "Node":
        hub = self._hub or LocalTransportHub()
        attrs = (("data", self.settings.get("node.data", "true")),
                 ("master", self.settings.get("node.master", "true")))
        self.transport_service = TransportService(
            LocalTransport(hub),
            lambda addr: DiscoveryNode(self.node_id, self.node_name, addr,
                                       attributes=attrs))
        self.allocation = AllocationService()
        cluster_name = self.settings.get("cluster.name", "elasticsearch-tpu")
        self.cluster_service = ClusterService(
            ClusterState(cluster_name=cluster_name), self.node_id)
        self.cluster_service.add_listener(self._persist_state)
        from elasticsearch_tpu.indices.service import IndicesService
        self.indices_service = IndicesService(self.data_path,
                                              self.cluster_service,
                                              self.node_id,
                                              self.allocation)
        self.indices_service.on_shard_started = self._on_shard_started
        self.indices_service.on_shard_failed = self._on_shard_failed
        # ShardStateAction RPC endpoints (master side)
        self.transport_service.register_request_handler(
            self.SHARD_STARTED_ACTION, self._handle_shard_started, sync=True)
        self.transport_service.register_request_handler(
            self.SHARD_FAILED_ACTION, self._handle_shard_failed, sync=True)
        self.search_service = SearchService()
        self._delayed_reroute_timer = None
        self.cluster_service.add_listener(self._schedule_delayed_reroute)
        from elasticsearch_tpu.discovery import ZenDiscovery
        self.discovery = ZenDiscovery(
            self.transport_service, self.cluster_service, self.allocation,
            seed_provider=hub.addresses, cluster_name=cluster_name,
            min_master_nodes=self.settings.get_as_int(
                "discovery.zen.minimum_master_nodes", 1),
            gateway_fn=self._gateway_recover,
            ping_timeout=self.settings.get_as_float(
                "discovery.zen.ping_timeout", 1.0),
            fd_interval=self.settings.get_as_float("fd.ping_interval", 0.5),
            fd_timeout=self.settings.get_as_float("fd.ping_timeout", 1.0),
            fd_retries=self.settings.get_as_int("fd.ping_retries", 3),
            publish_timeout=self.settings.get_as_float(
                "discovery.zen.publish_timeout", 10.0))
        self._started = True
        self.discovery.start(self.settings.get_as_float(
            "discovery.initial_state_timeout", 30.0))
        return self

    def _gateway_recover(self, state: ClusterState) -> ClusterState:
        """Gateway recovery (GatewayMetaState): merge persisted metadata
        into the state when this node becomes master of a fresh cluster."""
        raw = ClusterState.load_metadata(self.data_path / "_state")
        if not raw:
            return state
        indices = dict(state.indices)
        routing = state.routing_table
        for name, m in raw.get("indices", {}).items():
            if name in indices:
                continue
            meta = IndexMetadata.from_state_dict(name, m)
            indices[name] = meta
            routing = routing.add_index(meta)
        return state.with_(
            version=max(state.version, raw.get("version", 0)),
            indices=indices, routing_table=routing,
            templates={**raw.get("templates", {}), **state.templates},
            persistent_settings={**raw.get("persistent_settings", {}),
                                 **state.persistent_settings})

    # ---- ShardStateAction (core/cluster/action/shard/ShardStateAction.java)

    def _on_shard_started(self, shard) -> None:
        """Report to the master; locally if we are it."""
        state = self.cluster_service.state()
        if state.master_node_id == self.node_id:
            self.cluster_service.submit_state_update(
                f"shard-started [{shard.index}][{shard.shard}]",
                lambda st: self.allocation.apply_started_shards(st, [shard]),
                priority=URGENT)
            return
        master = state.master_node
        if master is None:
            self.indices_service.unreport(shard.allocation_id)
            return
        fut = self.transport_service.send_request(
            master, self.SHARD_STARTED_ACTION, {"shard": shard.to_dict()},
            timeout=10.0)
        fut.add_done_callback(
            lambda f: self._retry_shard_report(shard)
            if f.exception() is not None else None)

    def _retry_shard_report(self, shard) -> None:
        """A lost started-report must be re-sent even on a quiescent
        cluster (the reference resends on every applied state AND the
        master re-pings INITIALIZING shards)."""
        import threading
        self.indices_service.unreport(shard.allocation_id)
        t = threading.Timer(1.0, self._recheck_shards)
        t.daemon = True
        t.start()

    def _recheck_shards(self) -> None:
        if not self._started:
            return
        try:
            self.cluster_service.run_task(
                "recheck-shards",
                lambda: self.indices_service._cluster_changed(
                    self.cluster_service.state(),
                    self.cluster_service.state()))
        except RuntimeError:
            pass                                 # shutting down

    def _on_shard_failed(self, shard, details: str) -> None:
        state = self.cluster_service.state()
        if state.master_node_id == self.node_id:
            self.cluster_service.submit_state_update(
                f"shard-failed [{shard.index}][{shard.shard}]",
                lambda st: self.allocation.apply_failed_shards(
                    st, [(shard, details)]),
                priority=URGENT)
            return
        master = state.master_node
        if master is not None:
            self.transport_service.send_request(
                master, self.SHARD_FAILED_ACTION,
                {"shard": shard.to_dict(), "details": details}, timeout=10.0)

    def _handle_shard_started(self, request: dict, source) -> dict:
        from elasticsearch_tpu.cluster.state import ShardRouting
        shard = ShardRouting.from_dict(request["shard"])
        self.cluster_service.submit_state_update(
            f"shard-started [{shard.index}][{shard.shard}] (remote)",
            lambda st: self.allocation.apply_started_shards(st, [shard]),
            priority=URGENT).result(10.0)
        return {}

    def _handle_shard_failed(self, request: dict, source) -> dict:
        from elasticsearch_tpu.cluster.state import ShardRouting
        shard = ShardRouting.from_dict(request["shard"])
        details = request.get("details", "")
        self.cluster_service.submit_state_update(
            f"shard-failed [{shard.index}][{shard.shard}] (remote)",
            lambda st: self.allocation.apply_failed_shards(
                st, [(shard, details)]),
            priority=URGENT).result(10.0)
        return {}

    @property
    def is_master(self) -> bool:
        return self.cluster_service.state().master_node_id == self.node_id

    def _persist_state(self, old: ClusterState, new: ClusterState) -> None:
        new.persist(self.data_path / "_state")

    def _schedule_delayed_reroute(self, old, new) -> None:
        """RoutingService.scheduleDelayedReroute analog: when NODE_LEFT
        shards are waiting out their delayed-allocation window, arrange a
        reroute at expiry (only the master reroutes)."""
        import threading
        if new.master_node_id != self.node_id:
            return
        remaining = self.allocation.next_delayed_reroute_millis(new)
        if remaining is None:
            return
        if self._delayed_reroute_timer is not None and \
                self._delayed_reroute_timer.is_alive():
            return
        t = threading.Timer(remaining / 1000.0 + 0.05, self._delayed_reroute)
        t.daemon = True
        t.start()
        self._delayed_reroute_timer = t

    def _delayed_reroute(self) -> None:
        if not self._started:
            return
        try:
            self.cluster_service.submit_state_update(
                "delayed reroute",
                lambda st: self.allocation.reroute(st, "delay expired"),
                priority=URGENT)
        except RuntimeError:
            pass                                 # cluster service closed

    def wait_for_health(self, status: str | None = "green",
                        timeout: float = 10.0,
                        wait_for_nodes: str | int | None = None) -> dict:
        """Health wait (wait_for_status / wait_for_nodes params of the
        health API). `wait_for_nodes` accepts N, '>=N', '<=N', '>N', '<N';
        status=None waits only on the node predicate."""
        want = {"green": ("green",), "yellow": ("green", "yellow"),
                None: ("green", "yellow", "red")}[status]
        deadline = time.monotonic() + timeout
        while True:
            h = self.cluster_service.state().health(
                len(self.cluster_service.pending_tasks()))
            nodes_ok = _nodes_predicate(wait_for_nodes, h["number_of_nodes"])
            if h["status"] in want and nodes_ok and \
                    h["number_of_pending_tasks"] == 0:
                return h
            if time.monotonic() > deadline:
                h["timed_out"] = True
                return h
            time.sleep(0.01)

    def close(self) -> None:
        """Graceful shutdown: leave the cluster, then stop services."""
        if self._started:
            self._started = False
            if self._delayed_reroute_timer is not None:
                self._delayed_reroute_timer.cancel()
            self.discovery.stop()
            self.indices_service.close()
            self.cluster_service.close()
            self.transport_service.close()

    def kill(self) -> None:
        """Abrupt death — no leave notification, no flush ordering; the
        cluster must detect the loss via fault detection (test disruption
        helper, mirrors InternalTestCluster restartNode(KILL))."""
        if self._started:
            self._started = False
            if self._delayed_reroute_timer is not None:
                self._delayed_reroute_timer.cancel()
            self.transport_service.close()
            self.discovery.master_fd.stop()
            self.discovery.nodes_fd.stop()
            self.discovery._running = False
            self.cluster_service.close()
            self.indices_service.close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # ---- document action layer (core/action/{index,get,delete,update}) ----

    def index_doc(self, index: str, doc_id: str | None, source: dict,
                  routing: str | None = None, version: int | None = None,
                  op_type: str = "index", refresh: bool = False) -> dict:
        svc = self.indices_service.index(index) if \
            self.indices_service.has_index(index) else \
            self.indices_service.create_index(index)  # auto-create
        created_id = doc_id or uuid.uuid4().hex[:20]
        engine = svc.shard_for(created_id, routing)
        v, created = engine.index(
            created_id, source,
            version=MATCH_ANY if version is None else version,
            routing=routing, op_type=op_type)
        if refresh:
            engine.refresh()
        return {
            "_index": svc.name, "_type": "_doc", "_id": created_id,
            "_version": v,
            "result": "created" if created else "updated",
            "created": created,
            "_shards": {"total": 1, "successful": 1, "failed": 0},
        }

    def get_doc(self, index: str, doc_id: str,
                routing: str | None = None) -> dict:
        svc = self.indices_service.index(index)
        r = svc.shard_for(doc_id, routing).get(doc_id)
        out = {"_index": svc.name, "_type": "_doc", "_id": doc_id,
               "found": r.found}
        if r.found:
            out["_version"] = r.version
            out["_source"] = r.source
        return out

    def delete_doc(self, index: str, doc_id: str,
                   routing: str | None = None, version: int | None = None,
                   refresh: bool = False) -> dict:
        svc = self.indices_service.index(index)
        engine = svc.shard_for(doc_id, routing)
        v = engine.delete(doc_id,
                          version=MATCH_ANY if version is None else version)
        if refresh:
            engine.refresh()
        return {"_index": svc.name, "_type": "_doc", "_id": doc_id,
                "_version": v, "result": "deleted", "found": True,
                "_shards": {"total": 1, "successful": 1, "failed": 0}}

    def update_doc(self, index: str, doc_id: str, body: dict,
                   routing: str | None = None, refresh: bool = False) -> dict:
        """Get-modify-reindex on the primary (TransportUpdateAction)."""
        svc = self.indices_service.index(index)
        engine = svc.shard_for(doc_id, routing)
        current = engine.get(doc_id)
        if not current.found:
            if "upsert" in body:
                return self.index_doc(index, doc_id, body["upsert"],
                                      routing=routing, refresh=refresh)
            raise DocumentMissingError(index, doc_id)
        if "doc" in body:
            merged = _deep_merge(dict(current.source), body["doc"])
        elif "script" in body:
            merged = _apply_update_script(dict(current.source), body["script"])
        else:
            merged = dict(current.source)
        v, _ = engine.index(doc_id, merged, version=current.version,
                            routing=routing)
        if refresh:
            engine.refresh()
        return {"_index": svc.name, "_type": "_doc", "_id": doc_id,
                "_version": v, "result": "updated"}

    def mget(self, body: dict, default_index: str | None = None) -> dict:
        docs = []
        for spec in body.get("docs", []):
            idx = spec.get("_index", default_index)
            docs.append(self.get_doc(idx, spec["_id"],
                                     routing=spec.get("routing")))
        if "ids" in body and default_index:
            for did in body["ids"]:
                docs.append(self.get_doc(default_index, str(did)))
        return {"docs": docs}

    # ---- bulk (TransportBulkAction: split per shard, apply per item) -------

    def bulk(self, operations: list[tuple[str, dict, dict | None]],
             refresh: bool = False) -> dict:
        """operations: (action, metadata, source) triples, pre-parsed from
        NDJSON by the REST layer or built by the client."""
        items = []
        errors = False
        touched: set[tuple[str, int]] = set()
        for action, meta, source in operations:
            index = meta.get("_index")
            doc_id = meta.get("_id")
            routing = meta.get("routing", meta.get("_routing"))
            try:
                if action in ("index", "create"):
                    r = self.index_doc(index, doc_id, source, routing=routing,
                                       op_type="create" if action == "create"
                                       else "index")
                    status = 201 if r["created"] else 200
                elif action == "delete":
                    r = self.delete_doc(index, doc_id, routing=routing)
                    status = 200
                elif action == "update":
                    r = self.update_doc(index, doc_id, source or {},
                                        routing=routing)
                    status = 200
                else:
                    raise ValueError(f"unknown bulk action [{action}]")
                items.append({action: {**r, "status": status}})
            except Exception as e:  # per-item failure (bulk continues)
                errors = True
                from elasticsearch_tpu.common.errors import ElasticsearchTpuError
                err = e.to_xcontent() if isinstance(e, ElasticsearchTpuError) \
                    else {"type": "exception", "reason": str(e)}
                status = e.status if isinstance(e, ElasticsearchTpuError) else 500
                items.append({action: {"_index": index, "_id": doc_id,
                                       "error": err, "status": status}})
        if refresh:
            for name in {m.get("_index") for _, m, _ in operations if m}:
                if name and self.indices_service.has_index(name):
                    self.indices_service.index(name).refresh()
        return {"took": 0, "errors": errors, "items": items}

    # ---- search entry ------------------------------------------------------

    def search(self, index: str, body: dict | None = None,
               scroll: str | None = None) -> dict:
        names = self.indices_service.resolve(index)
        if len(names) == 1:
            return self.search_service.search(
                self.indices_service.index(names[0]), body, scroll=scroll)
        # multi-index search: run per index and merge (coordinator behavior)
        from elasticsearch_tpu.search.controller import merge_responses
        from elasticsearch_tpu.search.phase import parse_search_request
        req = parse_search_request(body)
        all_results, all_searchers, idx_of = [], [], []
        t0 = time.perf_counter()
        for n in names:
            svc = self.indices_service.index(n)
            searchers = self.search_service._searchers(svc)
            for s in searchers:
                all_searchers.append((n, s))
                all_results.append(s.query_phase(req))
        class _SearcherProxy:
            def __init__(self, name, s):
                self.name, self.s = name, s
            def fetch_phase(self, req, result, index_name, positions):
                return self.s.fetch_phase(req, result, self.name, positions)
        proxies = [_SearcherProxy(n, s) for n, s in all_searchers]
        return merge_responses("", req, all_results, proxies,
                               (time.perf_counter() - t0) * 1e3, req.aggs)

    def count(self, index: str, body: dict | None = None) -> dict:
        resp = self.search(index, {**(body or {}), "size": 0})
        return {"count": resp["hits"]["total"]["value"],
                "_shards": resp["_shards"]}


def _nodes_predicate(expr, actual: int) -> bool:
    if expr is None:
        return True
    s = str(expr)
    for op, fn in ((">=", lambda a, b: a >= b), ("<=", lambda a, b: a <= b),
                   (">", lambda a, b: a > b), ("<", lambda a, b: a < b)):
        if s.startswith(op):
            return fn(actual, int(s[len(op):]))
    return actual == int(s)


def _deep_merge(base: dict, patch: dict) -> dict:
    for k, v in patch.items():
        if isinstance(v, dict) and isinstance(base.get(k), dict):
            base[k] = _deep_merge(dict(base[k]), v)
        else:
            base[k] = v
    return base


def _apply_update_script(source: dict, script) -> dict:
    """Update scripts: support the common `ctx._source.field = ...` and
    `ctx._source.field += n` idioms via a restricted evaluator."""
    import re as _re
    if isinstance(script, dict):
        src = script.get("source", script.get("inline", ""))
        params = script.get("params", {})
    else:
        src, params = str(script), {}
    for stmt in src.split(";"):
        stmt = stmt.strip()
        if not stmt:
            continue
        m = _re.fullmatch(
            r"ctx\._source\.(\w+)\s*(=|\+=|-=)\s*(.+)", stmt)
        if not m:
            raise ValueError(f"unsupported update script [{stmt}]")
        fname, op, expr = m.groups()
        expr = expr.strip()
        pm = _re.fullmatch(r"params\.(\w+)", expr)
        if pm:
            value = params[pm.group(1)]
        else:
            try:
                value = float(expr) if "." in expr else int(expr)
            except ValueError:
                value = expr.strip("'\"")
        if op == "=":
            source[fname] = value
        elif op == "+=":
            source[fname] = source.get(fname, 0) + value
        elif op == "-=":
            source[fname] = source.get(fname, 0) - value
    return source
