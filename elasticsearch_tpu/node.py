"""Node — the service container and lifecycle.

Reference: core/node/Node.java:129-315 — module assembly (:161-198), ordered
start (:230-275: indices → cluster → search → discovery → gateway → http).
One Node owns: persisted cluster state (gateway), ClusterService,
IndicesService (reconciler), SearchService, and the document/bulk action
entry points (the action layer, core/action/) that the REST layer and the
Python client both call — mirroring how NodeClient and RestController share
TransportAction instances.
"""

from __future__ import annotations

import threading as _threading
import time
import uuid
from pathlib import Path

from elasticsearch_tpu.cluster.allocation import AllocationService
from elasticsearch_tpu.cluster.service import URGENT, ClusterService
from elasticsearch_tpu.cluster.state import (
    ClusterState, IndexMetadata, RoutingTable)
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.transport import (
    DiscoveryNode, LocalTransport, LocalTransportHub, TransportService)
from elasticsearch_tpu.transport.service import TransportAddress


class Node:
    def __init__(self, settings: Settings | dict | None = None,
                 data_path: str | Path | None = None,
                 transport_hub: LocalTransportHub | None = None):
        if not isinstance(settings, Settings):
            settings = Settings(settings or {})
        # plugin scan + default-settings merge happen before anything reads
        # settings (reference order: PluginsService at core/node/Node.java:145
        # precedes module assembly; plugin additionalSettings merge UNDER
        # user settings)
        from elasticsearch_tpu.plugins import PluginsService
        specs = settings.get("plugins") or []
        if isinstance(specs, str):
            specs = [s.strip() for s in specs.split(",") if s.strip()]
        self.plugins_service = PluginsService(specs)
        defaults = self.plugins_service.merged_default_settings()
        if defaults:
            settings = Settings(defaults).merge(settings)
        self.settings = settings
        self.node_id = uuid.uuid4().hex[:20]
        self.node_name = settings.get("node.name", f"node-{self.node_id[:7]}")
        self.data_path = Path(data_path or settings.get("path.data", "data"))
        self.data_path.mkdir(parents=True, exist_ok=True)
        self._hub = transport_hub
        self._started = False

    # ---- lifecycle (Node.start order, core/node/Node.java:230-275) ---------

    SHARD_STARTED_ACTION = "internal:cluster/shard/started"
    SHARD_FAILED_ACTION = "internal:cluster/shard/failure"

    def start(self) -> "Node":
        # transport selection (ref: `transport.type` setting resolved by
        # NetworkModule — NettyTransport by default, LocalTransport for
        # embedded/test use; core/node/Node.java:230-275 wiring order).
        # "tcp" boots a real socket server so multi-process / multi-host
        # clusters form over the network; "local" keeps the in-process hub.
        transport_type = self.settings.get("transport.type", "local")
        if transport_type in ("tcp", "netty"):
            from elasticsearch_tpu.transport.tcp import TcpTransport
            hub = None
            transport = TcpTransport(
                self.settings.get("transport.host", "127.0.0.1"),
                self.settings.get_as_int("transport.tcp.port", 0),
                publish_host=self.settings.get("transport.publish_host"),
                compress=self.settings.get_as_bool(
                    "transport.tcp.compress", False))
            seed_provider = self._unicast_seeds
        elif transport_type == "local":
            hub = self._hub or LocalTransportHub()
            transport = LocalTransport(hub)
            seed_provider = hub.addresses
        else:
            raise ValueError(f"unknown transport.type [{transport_type}]")
        attrs = (("data", self.settings.get("node.data", "true")),
                 ("master", self.settings.get("node.master", "true")))
        # every other `node.<key>` setting becomes a custom node attribute
        # (ref: DiscoveryNode attributes from `node.` settings,
        # core/cluster/node/DiscoveryNodeService.java)
        reserved = {"data", "master", "name", "local", "mode", "client",
                    "max_local_storage_nodes", "portsfile"}
        extra = tuple(
            (k[len("node."):], str(v))
            for k, v in sorted(self.settings.as_dict().items())
            if k.startswith("node.") and k[len("node."):] not in reserved
            and "." not in k[len("node."):])
        attrs = attrs + extra
        from elasticsearch_tpu.common.threadpool import ThreadPool
        self.thread_pool = ThreadPool(self.settings)
        from elasticsearch_tpu import __version__ as _build
        self.transport_service = TransportService(
            transport,
            lambda addr: DiscoveryNode(self.node_id, self.node_name, addr,
                                       attributes=attrs, build=_build),
            thread_pool=self.thread_pool)
        # task registry (core/tasks/TaskManager.java): every inbound RPC
        # and every locally-spawned action registers under a
        # cluster-unique "node:seq" id; wired into the transport so the
        # parent link propagates on every outgoing request
        from elasticsearch_tpu.tasks import TaskManager
        self.task_manager = TaskManager(self.node_id, self.node_name)
        self.transport_service.task_manager = self.task_manager
        self.task_manager.ban_broadcaster = self._broadcast_task_ban
        self.transport_service.register_request_handler(
            self.TASKS_LIST_ACTION, self._handle_tasks_list,
            executor="management", sync=True)
        self.transport_service.register_request_handler(
            self.TASK_CANCEL_ACTION, self._handle_task_cancel,
            executor="management", sync=True)
        # bans apply inline on the delivery thread ("same"): a cancel
        # must land even when the management pool is saturated by the
        # very work being cancelled
        self.transport_service.register_request_handler(
            self.TASK_BAN_ACTION, self._handle_task_ban,
            executor="same", sync=True)
        self.allocation = AllocationService()
        cluster_name = self.settings.get("cluster.name", "elasticsearch-tpu")
        self.cluster_service = ClusterService(
            ClusterState(cluster_name=cluster_name), self.node_id)
        self.cluster_service.add_listener(self._persist_state)
        # orphan reaping: when a node leaves the cluster, every task
        # parented on it is cancelled (its coordinator can neither
        # collect nor cancel it anymore) and its bans are dropped
        self.cluster_service.add_listener(self._reap_tasks_on_node_left)
        from elasticsearch_tpu.indices.service import IndicesService
        from elasticsearch_tpu.common.breaker import (
            HierarchyCircuitBreakerService)
        self.breaker_service = HierarchyCircuitBreakerService(self.settings)
        # SLO targets (observability.slo.* settings) — installed once so
        # the histogram seam classifies good/bad from the first event
        from elasticsearch_tpu.observability import slo as _slo
        _slo.configure(self.node_id, self.settings)
        self.indices_service = IndicesService(self.data_path,
                                              self.cluster_service,
                                              self.node_id,
                                              self.allocation)
        self.indices_service.breaker_service = self.breaker_service
        self.indices_service.merge_submit = \
            lambda fn: self.thread_pool.submit("merge", fn)
        self.indices_service.on_shard_started = self._on_shard_started
        self.indices_service.on_shard_failed = self._on_shard_failed
        # ShardStateAction RPC endpoints (master side)
        self.transport_service.register_request_handler(
            self.SHARD_STARTED_ACTION, self._handle_shard_started, sync=True)
        self.transport_service.register_request_handler(
            self.SHARD_FAILED_ACTION, self._handle_shard_failed, sync=True)
        # master-forwarding seam (TransportMasterNodeAction analog)
        self.indices_service.master_executor = self._execute_master_action
        # dangling-indices offer path (DanglingIndicesState → master
        # metadata re-import + allocation)
        self.indices_service.dangling_import = self._import_dangling
        self.transport_service.register_request_handler(
            self.MASTER_FORWARD_ACTION, self._handle_master_forward,
            executor="management", sync=True)
        # distributed action layer (core/action/)
        from elasticsearch_tpu.action import (
            BroadcastActions, DocumentActions, SearchActions)
        self.document_actions = DocumentActions(self)
        self.search_actions = SearchActions(self)
        self.broadcast_actions = BroadcastActions(self)
        # collective-plane data-layer pipelining: engine reader swaps
        # (refresh/merge) schedule the next-generation pack build off
        # the query hot path; per-index request_cache stats read the
        # node's shard request cache through the same late-bound seam
        self.indices_service.reader_swap_hook = \
            self.search_actions.schedule_plane_rebuild
        self.indices_service.request_cache = \
            self.search_actions.request_cache
        # peer recovery (core/indices/recovery/): replicas pull files + ops
        # from their active primary before reporting started
        from elasticsearch_tpu.indices.recovery import PeerRecoveryService
        self.recovery_service = PeerRecoveryService(self)
        self.indices_service.prepare_shard = \
            self.recovery_service.recover_shard
        # snapshot/restore (core/snapshots/)
        from elasticsearch_tpu.snapshots import SnapshotsService
        self.snapshots_service = SnapshotsService(self)
        # live disk-usage sampling feeding the DiskThresholdDecider
        # (InternalClusterInfoService) — constructed here, started at the
        # end of start() so a failed boot never leaks the timer
        from elasticsearch_tpu.cluster.info import ClusterInfoService
        from elasticsearch_tpu.common.settings import parse_time_value \
            as _ptv
        self.cluster_info_service = ClusterInfoService(
            self, interval_s=_ptv(
                self.settings.get("cluster.info.update.interval", "30s"),
                "cluster.info.update.interval"))
        # node-level monitoring fan-out (core/action/admin/cluster/node/)
        self.transport_service.register_request_handler(
            self.NODE_STATS_ACTION, self._handle_node_stats,
            executor="management", sync=True)
        self.transport_service.register_request_handler(
            self.HOT_THREADS_ACTION, self._handle_hot_threads,
            executor="management", sync=True)
        self.transport_service.register_request_handler(
            self.TRACE_COLLECT_ACTION, self._handle_trace_collect,
            executor="management", sync=True)
        self._delayed_reroute_timer = None
        self.cluster_service.add_listener(self._schedule_delayed_reroute)
        # TTL purger (IndicesTTLService): periodic sweep deleting expired
        # _ttl docs through the normal replicated delete path
        from elasticsearch_tpu.common.settings import parse_time_value
        self._ttl_interval = parse_time_value(
            self.settings.get("indices.ttl.interval", "60s"), "ttl.interval")
        self._ttl_timer = None
        self._schedule_ttl_sweep()
        # IndexingMemoryController (core/indices/memory/
        # IndexingMemoryController.java:48): a node-wide budget for
        # uncommitted write buffers; when the sum exceeds
        # indices.memory.index_buffer_size, the largest buffers refresh
        # (turning them into searchable segments frees the RAM)
        self._index_buffer_budget = self._parse_buffer_size(
            self.settings.get("indices.memory.index_buffer_size", "10%"))
        self._imc_timer = None
        self._schedule_imc()
        # file scripts hot-reload (ResourceWatcherService + the
        # ScriptService file-script listener)
        from elasticsearch_tpu.watcher import ResourceWatcherService
        scripts_dir = Path(self.settings.get(
            "path.scripts", self.data_path / "config" / "scripts"))
        self.resource_watcher = ResourceWatcherService(
            scripts_dir,
            interval_s=parse_time_value(
                self.settings.get("resource.reload.interval", "5s"),
                "resource.reload.interval")).start()
        # plugin ZenPing providers compose with the transport's own seed
        # source (DiscoveryModule.addZenPing — how discovery-multicast
        # rides beside unicast); collected BEFORE ZenDiscovery starts so
        # plugin seeds feed the initial election round
        try:
            extra_pings = self.plugins_service.collect_zen_pings(self)
            if extra_pings:
                base_seeds = seed_provider

                def seed_provider():
                    seeds = list(base_seeds())
                    seen = set(seeds)
                    for fn in extra_pings:
                        # plugin seeds are best-effort ADDITIONS: one
                        # failing probe must not cost the round its
                        # unicast seeds
                        try:
                            extra = fn()
                        except Exception:    # noqa: BLE001 — next round
                            continue
                        for a in extra:
                            if a not in seen:
                                seen.add(a)
                                seeds.append(a)
                    return seeds
            from elasticsearch_tpu.discovery import ZenDiscovery
            self.discovery = ZenDiscovery(
                self.transport_service, self.cluster_service,
                self.allocation,
                seed_provider=seed_provider, cluster_name=cluster_name,
                min_master_nodes=self.settings.get_as_int(
                    "discovery.zen.minimum_master_nodes", 1),
                gateway_fn=self._gateway_recover,
                ping_timeout=self.settings.get_as_float(
                    "discovery.zen.ping_timeout", 1.0),
                fd_interval=self.settings.get_as_float(
                    "fd.ping_interval", 0.5),
                fd_timeout=self.settings.get_as_float(
                    "fd.ping_timeout", 1.0),
                fd_retries=self.settings.get_as_int("fd.ping_retries", 3),
                publish_timeout=self.settings.get_as_float(
                    "discovery.zen.publish_timeout", 10.0))
        except Exception:
            # a failed boot must not leak plugin ping responders (same
            # invariant cluster_info_service keeps: constructed here,
            # started only once start() cannot fail before _started)
            self.plugins_service.abort_zen_pings(self)
            raise
        self._started = True
        self.discovery.start(self.settings.get_as_float(
            "discovery.initial_state_timeout", 30.0))
        self.cluster_info_service.start()
        # plugin service wiring once the node is fully up (the analog of
        # nodeServices()/onModule hooks firing at injector-creation time)
        self.plugins_service.apply_node_start(self)
        return self

    def _unicast_seeds(self) -> list[TransportAddress]:
        """Unicast discovery seeds for TCP clusters (ref: UnicastZenPing,
        `discovery.zen.ping.unicast.hosts` — a list or comma string of
        host:port pairs). The local bound address is implicit; zen skips it
        when pinging."""
        raw = self.settings.get("discovery.zen.ping.unicast.hosts") or []
        if isinstance(raw, str):
            raw = [h.strip() for h in raw.split(",") if h.strip()]
        seeds = []
        for entry in raw:
            entry = str(entry)
            if entry.startswith("["):
                # bracketed IPv6: [::1] or [::1]:9300
                host, _, rest = entry[1:].partition("]")
                port = rest.lstrip(":") or "9300"
            elif entry.count(":") > 1:
                # raw IPv6 literal, no port syntax possible
                host, port = entry, "9300"
            else:
                host, sep, port = entry.rpartition(":")
                if not sep or not port:
                    # bare host: default to the standard transport port
                    # (the reference appends :9300 to host-only entries)
                    host, port = entry.rstrip(":"), "9300"
            seeds.append(TransportAddress(host or "127.0.0.1", int(port)))
        return seeds

    def _gateway_recover(self, state: ClusterState) -> ClusterState:
        """Gateway recovery (GatewayMetaState): merge persisted metadata
        into the state when this node becomes master of a fresh cluster."""
        raw = ClusterState.load_metadata(self.data_path / "_state")
        if not raw:
            return state
        indices = dict(state.indices)
        routing = state.routing_table
        for name, m in raw.get("indices", {}).items():
            if name in indices:
                continue
            meta = IndexMetadata.from_state_dict(name, m)
            indices[name] = meta
            routing = routing.add_index(meta)
        from elasticsearch_tpu.indices.service import IndicesService
        tombs = list(raw.get("tombstones", []))
        for t in state.customs.get("index_tombstones", []):
            if t not in tombs:
                tombs.append(t)
        customs = dict(state.customs)
        if tombs:
            customs["index_tombstones"] = \
                tombs[-IndicesService.TOMBSTONE_CAP:]
        return state.with_(
            version=max(state.version, raw.get("version", 0)),
            indices=indices, routing_table=routing,
            templates={**raw.get("templates", {}), **state.templates},
            persistent_settings={**raw.get("persistent_settings", {}),
                                 **state.persistent_settings},
            customs=customs)

    # ---- master forwarding (TransportMasterNodeAction.java:50) -------------

    MASTER_FORWARD_ACTION = "cluster:admin/forward"

    def _execute_master_action(self, action: str, request: dict, local):
        """Run a metadata op on the elected master: locally when we are it,
        else forward over the transport and wait for the ack (the published
        state reaches us before the master responds, because publish acks
        gate the response — PublishClusterStateAction two-phase commit)."""
        from elasticsearch_tpu.action.replication import unwrap_remote
        from elasticsearch_tpu.common.errors import MasterNotDiscoveredError
        from elasticsearch_tpu.transport.service import (
            RemoteTransportError, TransportException)
        deadline = time.monotonic() + 30.0
        while True:
            state = self.cluster_service.state()
            if state.master_node_id == self.node_id or \
                    state.master_node is None and not self._started:
                return local()
            master = state.master_node
            if master is None:
                if time.monotonic() > deadline:
                    raise MasterNotDiscoveredError(
                        f"no master to forward [{action}] to")
                time.sleep(0.05)
                continue
            try:
                self.transport_service.send_request(
                    master, self.MASTER_FORWARD_ACTION,
                    {"action": action, "request": request},
                    timeout=30.0).result(35.0)
                return None
            except Exception as e:               # noqa: BLE001 — unwrap
                if isinstance(e, TransportException) and \
                        not isinstance(e, RemoteTransportError):
                    # master died mid-request: retry across elections
                    if time.monotonic() > deadline:
                        raise MasterNotDiscoveredError(
                            f"[{action}] failed: {e}") from None
                    time.sleep(0.1)
                    continue
                raise unwrap_remote(e) from None

    def put_stored_script(self, lang: str, sid: str, source) -> bool:
        """Indexed/stored scripts live in cluster state (the reference's
        hidden .scripts index; metadata storage gives the same durability
        — cf. search/templates.py's reasoning for stored templates).
        → created (False = overwrote), decided inside the MASTER's
        single-writer update so concurrent puts and applied-state lag on
        the coordinating node can't misreport it."""
        out = self.indices_service._master_op(
            "put-script", {"lang": lang, "id": sid, "source": source},
            lambda: self._put_script_on_master(lang, sid, source))
        return bool(out.get("created", True)) if isinstance(out, dict) \
            else True

    def delete_stored_script(self, lang: str, sid: str) -> None:
        self.indices_service._master_op(
            "delete-script", {"lang": lang, "id": sid},
            lambda: self._delete_script_on_master(lang, sid))

    def _put_script_on_master(self, lang: str, sid: str, source) -> dict:
        created = [True]
        version = [1]

        def update(state):
            key = f"{lang}\x00{sid}"
            existing = state.customs.get("stored_scripts", {})
            created[0] = key not in existing
            versions = dict(state.customs.get("stored_script_versions", {}))
            version[0] = versions.get(key, 0) + 1
            versions[key] = version[0]
            scripts = {**existing, key: source}
            return state.with_(customs={
                **state.customs, "stored_scripts": scripts,
                "stored_script_versions": versions})
        self.cluster_service.submit_and_wait(f"put-script [{sid}]", update)
        return {"created": created[0], "version": version[0]}

    def _delete_script_on_master(self, lang: str, sid: str) -> None:
        def update(state):
            key = f"{lang}\x00{sid}"
            scripts = {k: v for k, v in
                       state.customs.get("stored_scripts", {}).items()
                       if k != key}
            # deletion bumps the version like a document delete would
            # (the reference's .scripts index semantics)
            versions = dict(state.customs.get("stored_script_versions", {}))
            versions[key] = versions.get(key, 0) + 1
            return state.with_(customs={
                **state.customs, "stored_scripts": scripts,
                "stored_script_versions": versions})
        self.cluster_service.submit_and_wait(f"delete-script [{sid}]",
                                             update)

    def stored_script(self, sid: str, lang: str = "mustache"):
        src = self.cluster_service.state().customs.get(
            "stored_scripts", {}).get(f"{lang}\x00{sid}")
        if src is None and getattr(self, "resource_watcher", None):
            # file scripts resolve after indexed ones (ScriptService
            # lookup order: inline > indexed > file)
            src = self.resource_watcher.get(sid, lang)
        return src

    def stored_script_version(self, sid: str, lang: str) -> int:
        return self.cluster_service.state().customs.get(
            "stored_script_versions", {}).get(f"{lang}\x00{sid}", 0)

    def cluster_reroute(self, commands: list[dict],
                        dry_run: bool = False) -> dict:
        """POST /_cluster/reroute (ref: TransportClusterRerouteAction +
        allocation commands): explicit shard placement commands applied
        through the master's single-writer queue; dry_run validates and
        computes without publishing."""
        if dry_run:
            state = self.cluster_service.state()
            new = self.allocation.execute_commands(state, commands)
            return {"acknowledged": True,
                    "state": {"routing_table": new.routing_table.to_dict()
                              if hasattr(new.routing_table, "to_dict")
                              else {}}}
        self.indices_service._master_op(
            "cluster-reroute", {"commands": commands},
            lambda: self._reroute_on_master(commands))
        return {"acknowledged": True}

    def _reroute_on_master(self, commands: list[dict]) -> None:
        from elasticsearch_tpu.cluster.service import URGENT
        errors: list[Exception] = []

        def update(state):
            try:
                return self.allocation.execute_commands(state, commands)
            except Exception as e:           # noqa: BLE001 — surface below
                errors.append(e)
                return state
        self.cluster_service.submit_and_wait("cluster-reroute", update,
                                             priority=URGENT)
        if errors:
            raise errors[0]

    def _handle_master_forward(self, request: dict, source) -> dict:
        isvc = self.indices_service
        action, req = request["action"], request["request"]
        dispatch = {
            "create-index": lambda: isvc.create_index(req["name"],
                                                      req["body"]),
            "delete-index": lambda: isvc.delete_index(req["name"]),
            "put-mapping": lambda: isvc.put_mapping(req["name"], req["type"],
                                                    req["mapping"]),
            "update-settings": lambda: isvc.update_settings(req["name"],
                                                            req["settings"]),
            "put-alias": lambda: isvc.put_alias(req["index"], req["alias"],
                                                req.get("body")),
            "delete-alias": lambda: isvc.delete_alias(req["index"],
                                                      req["alias"]),
            "index-state": lambda: isvc.set_index_state(req["index"],
                                                        req["state"]),
            "put-warmer": lambda: isvc.put_warmer(req["index"], req["name"],
                                                  req["body"]),
            "delete-warmer": lambda: isvc.delete_warmers(
                req["index"], set(req["names"])),
            "put-template": lambda: self.put_template(req["name"],
                                                      req["body"]),
            "delete-template": lambda: self.delete_template(req["name"]),
            "cluster-settings": lambda: self.update_cluster_settings(
                req["body"]),
            "put-percolator": lambda: isvc.put_percolator(
                req["index"], req["id"], req["body"]),
            "delete-percolator": lambda: isvc.delete_percolator(
                req["index"], req["id"]),
            "put-repository": lambda: self.snapshots_service.put_repository(
                req["name"], req["body"]),
            "delete-repository": lambda:
                self.snapshots_service.delete_repository(req["name"]),
            "create-snapshot": lambda:
                self.snapshots_service._create_on_master(
                    req["repo"], req["snapshot"], req["body"]),
            "delete-snapshot": lambda:
                self.snapshots_service.delete_snapshot(req["repo"],
                                                       req["snapshot"]),
            "restore-snapshot": lambda:
                self.snapshots_service._restore_on_master(
                    req["repo"], req["snapshot"], req["body"]),
            "cluster-reroute": lambda: self._reroute_on_master(
                req.get("commands") or []),
            "put-script": lambda: self._put_script_on_master(
                req["lang"], req["id"], req["source"]),
            "delete-script": lambda: self._delete_script_on_master(
                req["lang"], req["id"]),
            "import-dangling": lambda: self._import_dangling_on_master(
                req["name"], req["meta"]),
        }
        fn = dispatch.get(action)
        if fn is None:
            raise ValueError(f"unknown master action [{action}]")
        out = fn()
        if isinstance(out, dict):        # e.g. put-script's created flag
            return {"acknowledged": True, **out}
        return {"acknowledged": True}

    # ---- dangling-indices import (core/gateway/DanglingIndicesState.java) --

    def _import_dangling(self, name: str, meta_dict: dict) -> None:
        """Offer an orphaned on-disk index to the elected master (local
        when we are it); the master re-imports the metadata and allocates
        — unless a tombstone or a racing re-create made the offer stale."""
        self.indices_service._master_op(
            "import-dangling", {"name": name, "meta": meta_dict},
            lambda: self._import_dangling_on_master(name, meta_dict))

    def _import_dangling_on_master(self, name: str,
                                   meta_dict: dict) -> None:
        def update(state: ClusterState) -> ClusterState:
            if name in state.indices:
                return state                     # re-created meanwhile
            tombs = state.customs.get("index_tombstones", [])
            uuid_ = meta_dict.get("uuid", "")
            for t in tombs:
                if t.get("index") == name or \
                        (uuid_ and t.get("uuid") == uuid_):
                    return state                 # deleted: stays dead
            meta = IndexMetadata.from_state_dict(name, meta_dict)
            return self.allocation.reroute(
                state.with_(
                    indices={**state.indices, name: meta},
                    routing_table=state.routing_table.add_index(meta)),
                f"dangling index imported [{name}]")
        self.cluster_service.submit_and_wait(
            f"import-dangling [{name}]", update)

    # ---- cluster-level metadata (master ops) -------------------------------

    def put_template(self, name: str, body: dict) -> None:
        self.indices_service._master_op(
            "put-template", {"name": name, "body": body},
            lambda: self.cluster_service.submit_and_wait(
                f"put-template [{name}]",
                lambda st: st.with_(templates={**st.templates, name: body})))

    def delete_template(self, name: str) -> None:
        self.indices_service._master_op(
            "delete-template", {"name": name},
            lambda: self.cluster_service.submit_and_wait(
                f"delete-template [{name}]",
                lambda st: st.with_(templates={
                    k: v for k, v in st.templates.items() if k != name})))

    def update_cluster_settings(self, body: dict) -> None:
        """PUT /_cluster/settings — persistent + transient scopes stored in
        cluster state (DynamicSettings / NodeSettingsService analog)."""
        def local():
            def update(st: ClusterState) -> ClusterState:
                persistent = {**st.persistent_settings,
                              **Settings(body.get("persistent",
                                                  {})).as_dict()}
                transient = {**st.transient_settings,
                             **Settings(body.get("transient", {})).as_dict()}
                return st.with_(persistent_settings=persistent,
                                transient_settings=transient)
            self.cluster_service.submit_and_wait("cluster-settings", update)
        self.indices_service._master_op("cluster-settings", {"body": body},
                                        local)

    # ---- ShardStateAction (core/cluster/action/shard/ShardStateAction.java)

    def _on_shard_started(self, shard) -> None:
        """Report to the master; locally if we are it."""
        state = self.cluster_service.state()
        if state.master_node_id == self.node_id:
            self.cluster_service.submit_state_update(
                f"shard-started [{shard.index}][{shard.shard}]",
                lambda st: self.allocation.apply_started_shards(st, [shard]),
                priority=URGENT)
            return
        master = state.master_node
        if master is None:
            self.indices_service.unreport(shard.allocation_id)
            return
        fut = self.transport_service.send_request(
            master, self.SHARD_STARTED_ACTION, {"shard": shard.to_dict()},
            timeout=10.0)
        fut.add_done_callback(
            lambda f: self._retry_shard_report(shard)
            if f.exception() is not None else None)

    def _retry_shard_report(self, shard) -> None:
        """A lost started-report must be re-sent even on a quiescent
        cluster (the reference resends on every applied state AND the
        master re-pings INITIALIZING shards)."""
        import threading
        self.indices_service.unreport(shard.allocation_id)
        t = threading.Timer(1.0, self._recheck_shards)
        t.daemon = True
        t.start()

    def _recheck_shards(self) -> None:
        if not self._started:
            return
        try:
            self.cluster_service.run_task(
                "recheck-shards",
                lambda: self.indices_service._cluster_changed(
                    self.cluster_service.state(),
                    self.cluster_service.state()))
        except RuntimeError:
            pass                                 # shutting down

    def _on_shard_failed(self, shard, details: str) -> None:
        state = self.cluster_service.state()
        if state.master_node_id == self.node_id:
            self.cluster_service.submit_state_update(
                f"shard-failed [{shard.index}][{shard.shard}]",
                lambda st: self.allocation.apply_failed_shards(
                    st, [(shard, details)]),
                priority=URGENT)
            return
        master = state.master_node
        if master is None:
            self._retry_shard_failed(shard, details)
            return
        fut = self.transport_service.send_request(
            master, self.SHARD_FAILED_ACTION,
            {"shard": shard.to_dict(), "details": details}, timeout=10.0)
        fut.add_done_callback(
            lambda f: self._retry_shard_failed(shard, details)
            if f.exception() is not None else None)

    def _retry_shard_failed(self, shard, details: str) -> None:
        """A failed-shard report lost to a dying/absent master MUST be
        re-sent: until some master applies it, the cluster state keeps
        advertising a copy that missed writes as active — reads served
        from it silently lose acked documents (a chaos-matrix find:
        replica fan-out failure racing a master kill)."""
        import threading
        t = threading.Timer(1.0, self._resend_shard_failed,
                            (shard, details))
        t.daemon = True
        t.start()

    def _resend_shard_failed(self, shard, details: str) -> None:
        if not self._started:
            return
        st = self.cluster_service.state()
        cur = [s for s in st.routing_table.shard_copies(shard.index,
                                                        shard.shard)
               if s.allocation_id == shard.allocation_id]
        if not cur or not cur[0].assigned:
            return                               # already applied
        self._on_shard_failed(shard, details)

    def _handle_shard_started(self, request: dict, source) -> dict:
        from elasticsearch_tpu.cluster.state import ShardRouting
        shard = ShardRouting.from_dict(request["shard"])
        self.cluster_service.submit_state_update(
            f"shard-started [{shard.index}][{shard.shard}] (remote)",
            lambda st: self.allocation.apply_started_shards(st, [shard]),
            priority=URGENT).result(10.0)
        return {}

    def _handle_shard_failed(self, request: dict, source) -> dict:
        from elasticsearch_tpu.cluster.state import ShardRouting
        shard = ShardRouting.from_dict(request["shard"])
        details = request.get("details", "")
        self.cluster_service.submit_state_update(
            f"shard-failed [{shard.index}][{shard.shard}] (remote)",
            lambda st: self.allocation.apply_failed_shards(
                st, [(shard, details)]),
            priority=URGENT).result(10.0)
        return {}

    # ---- task management (core/tasks/, TransportListTasksAction etc.) ------

    TASKS_LIST_ACTION = "cluster:monitor/tasks/lists[n]"
    TASK_CANCEL_ACTION = "cluster:admin/tasks/cancel"
    TASK_BAN_ACTION = "internal:admin/tasks/ban"

    def _handle_tasks_list(self, request: dict, source) -> dict:
        request = request or {}
        return {
            "name": self.node_name,
            "transport_address":
                str(self.transport_service.local_node.address),
            "tasks": self.task_manager.list_tasks(
                actions=request.get("actions"),
                parent_task_id=request.get("parent_task_id"),
                detailed=request.get("detailed", True))}

    def collect_tasks(self, actions: list[str] | None = None,
                      parent_task_id: str | None = None,
                      nodes: list[str] | None = None,
                      detailed: bool = True) -> dict:
        """GET /_tasks — every node's matching tasks, collected over the
        transport (TransportListTasksAction fan-out)."""
        per_node = self._fan_out_nodes(
            self.TASKS_LIST_ACTION,
            {"actions": actions, "parent_task_id": parent_task_id,
             "detailed": detailed})
        if nodes:
            wanted = set(nodes)
            per_node = {nid: doc for nid, doc in per_node.items()
                        if nid in wanted or doc.get("name") in wanted}
        return {"nodes": per_node}

    def cancel_task(self, task_id: str,
                    reason: str = "by user request") -> dict:
        """POST /_tasks/{id}/_cancel — routed to the task's OWNER node
        (the id's node part); the owner marks the task and its local
        descendants cancelled and broadcasts a ban on the id so children
        on every other node — current and future — cancel too."""
        owner, _, _ = str(task_id).rpartition(":")
        if owner == self.node_id or not owner:
            return self._cancel_local_task(task_id, reason)
        state = self.cluster_service.state()
        target = state.node(owner)
        if target is None:
            return {"found": False, "task_id": task_id}
        from elasticsearch_tpu.action.replication import unwrap_remote
        try:
            return self.transport_service.send_request(
                target, self.TASK_CANCEL_ACTION,
                {"task_id": task_id, "reason": reason},
                timeout=10.0).result(15.0)
        except Exception as e:               # noqa: BLE001 — unwrap
            raise unwrap_remote(e) from None

    def _cancel_local_task(self, task_id: str, reason: str) -> dict:
        tm = self.task_manager
        task = tm.get(task_id)
        if task is None:
            return {"found": False, "task_id": task_id}
        tm.cancel(task, reason)
        # ban the id cluster-wide; the flag makes unregister lift it
        task.ban_sent = True
        self._broadcast_task_ban(task.task_id, True, reason)
        return {"found": True, "task_id": task_id,
                "task": task.to_dict()}

    def _broadcast_task_ban(self, parent_task_id: str, ban: bool,
                            reason: str) -> None:
        """Fire-and-forget ban (or ban removal) to every other node —
        TaskManager.setBan propagation. Best-effort: a node that misses
        the ban still reaps the children when the parent node leaves."""
        state = self.cluster_service.state()
        for nid, n in state.nodes.items():
            if nid == self.node_id:
                continue
            try:
                self.transport_service.send_request(
                    n, self.TASK_BAN_ACTION,
                    {"parent": parent_task_id, "ban": ban,
                     "reason": reason}, timeout=5.0)
            except Exception:                # noqa: BLE001 — best effort
                continue

    def _handle_task_cancel(self, request: dict, source) -> dict:
        return self._cancel_local_task(
            request["task_id"], request.get("reason", "by user request"))

    def _handle_task_ban(self, request: dict, source) -> dict:
        if request.get("ban", True):
            n = self.task_manager.set_ban(
                request["parent"], request.get("reason", "parent banned"))
            return {"cancelled": n}
        self.task_manager.remove_ban(request["parent"])
        return {"cancelled": 0}

    def _reap_tasks_on_node_left(self, old, new) -> None:
        for nid in set(old.nodes) - set(new.nodes):
            self.task_manager.reap_node_left(nid)

    # ---- node-level monitoring (nodes stats / hot threads fan-out) ---------

    NODE_STATS_ACTION = "cluster:monitor/nodes/stats[n]"
    HOT_THREADS_ACTION = "cluster:monitor/nodes/hot_threads[n]"
    TRACE_COLLECT_ACTION = "cluster:monitor/nodes/trace[n]"

    def local_node_stats(self) -> dict:
        """This node's stats document (core/action/admin/cluster/node/stats
        — indices rollup, breakers, thread pools, process/os probes)."""
        from elasticsearch_tpu.monitor import os_stats, process_stats
        indices_total = {"docs": {"count": 0},
                         "store": {"size_in_bytes": 0,
                                   "throttle_time_in_millis": 0},
                         "segments": {"count": 0, "memory_in_bytes": 0},
                         "indexing": {"index_total": 0,
                                      "index_time_in_millis": 0}}
        # collective-plane admission rollup across this node's indices
        # (per-index detail lives in _stats; the flip to default-on is
        # observable here: served / fallback-by-reason), plus the
        # plane breaker (state, trip count, consecutive errors, last
        # error, probes) and which indices are plane-degraded —
        # the degraded-mode-serving dashboard
        from elasticsearch_tpu.search import jit_exec as _jx_breaker
        plane_total: dict = {"served": 0, "fallback": {},
                             "data_layer": {},
                             "breaker": _jx_breaker.plane_breaker.stats(),
                             "degraded_indices": sorted(
                                 name for name, svc in
                                 self.indices_service.indices.items()
                                 if svc.plane_stats.get("degraded"))}
        # percolate rollup: ops/time/registered queries summed across this
        # node's indices plus the registry program-cache counters (the
        # compiled-percolation analog of the collective_plane rollup)
        perc_total: dict = {"total": 0, "time_in_millis": 0, "current": 0,
                            "queries": 0}
        for svc in list(self.indices_service.indices.values()):
            plane_total["served"] += svc.plane_stats["served"]
            for reason, n in svc.plane_stats["fallback"].items():
                plane_total["fallback"][reason] = \
                    plane_total["fallback"].get(reason, 0) + n
            for k, v in svc.plane_stats.get("data_layer", {}).items():
                plane_total["data_layer"][k] = \
                    plane_total["data_layer"].get(k, 0) + v
            ps_idx = svc._percolate_stats()
            perc_total["total"] += ps_idx["total"]
            perc_total["time_in_millis"] += ps_idx["time_in_millis"]
            perc_total["queries"] += ps_idx["queries"]
            s = svc.stats()
            indices_total["docs"]["count"] += s["docs"]["count"]
            indices_total["store"]["size_in_bytes"] += \
                s.get("store", {}).get("size_in_bytes", 0)
            indices_total["segments"]["count"] += s["segments"]["count"]
            indices_total["segments"]["memory_in_bytes"] += \
                s["segments"]["memory_in_bytes"]
            indices_total["indexing"]["index_total"] += \
                s["indexing"]["index_total"]
            indices_total["indexing"]["index_time_in_millis"] += \
                s["indexing"]["index_time_in_millis"]
        pools = self.thread_pool.stats()
        recovery = getattr(self, "recovery_service", None)
        indices_total["request_cache"] = \
            self.search_actions.request_cache.stats_dict()
        indices_total["collective_plane"] = plane_total
        indices_total["percolate"] = perc_total
        # compiled-path counters: per-segment program cache plus the
        # plane's shape-keyed program layer (mesh_program_{hits,misses})
        # and fallback reasons — the trace/compile budget, observable.
        # `node_local` is THIS node's attributed slice of the shared
        # module-level rollup (in-process nodes share one device, so the
        # top-level numbers are process-wide; the slice is what isolates
        # one node's activity in multi-node stats)
        from elasticsearch_tpu.search import jit_exec as _jit_exec
        indices_total["jit"] = {
            **_jit_exec.cache_stats(),
            "node_local": _jit_exec.cache_stats(self.node_id)}
        ps = process_stats()
        osx = os_stats()
        heap = ps["mem"]["resident_in_bytes"]
        total_mem = osx.get("mem", {}).get("total_in_bytes", heap or 1)
        from elasticsearch_tpu.observability import costs as _costs
        from elasticsearch_tpu.observability import flightrec as _flight
        from elasticsearch_tpu.observability import histograms as _hist
        from elasticsearch_tpu.observability import slo as _slo
        from elasticsearch_tpu.observability import timeseries as _ts
        from elasticsearch_tpu.observability import tracing as _tracing
        # every stats read advances the telemetry ring (throttled), so
        # the windowed sections below always reflect this scrape
        self.telemetry_tick()
        rates_doc = _ts.rates(self.node_id)
        rates_doc["slo_burn"] = _slo.windowed_burn(self.node_id,
                                                   rates_doc)
        return {
            "name": self.node_name,
            "timestamp": int(time.time() * 1000),
            "indices": indices_total,
            "breakers": self.breaker_service.stats(),
            # the device-memory ledger: every HBM reservation on this
            # node keyed (index, engine, component), reconciling with
            # breakers.fielddata.estimated_size_in_bytes
            "device_memory": self.breaker_service.device_ledger.snapshot(
                resolve_index=self.resolve_engine_index),
            # rolling-window rates + windowed percentiles (1m/5m/15m)
            # from the telemetry ring, plus per-window SLO burn rates
            "rates": rates_doc,
            # SLO burn accounting: objective, per-lane good/bad totals,
            # cumulative burn rate
            "slo": _slo.stats(self.node_id),
            "thread_pool": pools,
            "tasks": self.task_manager.stats(),
            # adaptive replica selection: per-target-node C3 ranks/EWMAs
            # this coordinator observed, plus the hedged-request counters
            # (hedges_launched == hedges_won + hedges_cancelled +
            # in_flight at every instant)
            "adaptive_selection":
                self.search_actions.replica_stats.stats_dict(),
            # continuous-batching scheduler: queue depths, batches
            # launched/in-flight/drained, shed counts by reason, and the
            # sample-time reconciliation verdict (submitted == queued +
            # in_flight + delivered + declined + shed)
            "scheduler": self.search_actions.scheduler.stats(),
            # dispatch watchdog: live in-flight device waits (with the
            # oldest wait's age — the stall liveness gauge), the
            # escalation tallies (stalls/abandoned/quarantines/
            # probe_reopens), and the envelope config
            "watchdog": self.search_actions.watchdog.stats(),
            # program cost observatory: per-lane rollups over the
            # resident compiled programs (XLA static cost + live
            # dispatch stats, predicted vs measured) and the top
            # programs by device time; table accounting reconciles
            # (inserted == resident + evicted + dropped)
            "programs": _costs.stats_doc(self.node_id),
            # anomaly flight recorder occupancy (full ring via
            # GET /_nodes/diagnostics)
            "flight_recorder": _flight.stats(self.node_id),
            # per-lane latency distributions (fixed-bucket histograms,
            # always on) + this node's span-store accounting
            "latency": _hist.summaries(self.node_id),
            "tracing": _tracing.store_stats(self.node_id),
            "process": ps,
            "os": osx,
            # process-level memory reported under the reference's jvm
            # section name (there is no JVM; RSS plays the heap role)
            "jvm": {"timestamp": ps["timestamp"],
                    "uptime_in_millis": ps["uptime_in_millis"],
                    "mem": {"heap_used_in_bytes": heap,
                            "heap_used_percent":
                                int(100 * heap / max(total_mem, 1)),
                            "heap_max_in_bytes": total_mem},
                    "threads": {"count": _threading.active_count(),
                                "peak_count": _threading.active_count()},
                    "gc": {"collectors": {}},
                    "buffer_pools": {
                        "direct": {"count": 0, "used_in_bytes": 0,
                                   "total_capacity_in_bytes": 0},
                        "mapped": {"count": 0, "used_in_bytes": 0,
                                   "total_capacity_in_bytes": 0}}},
            "transport": {"server_open": 0, "rx_count": 0,
                          "rx_size_in_bytes": 0, "tx_count": 0,
                          "tx_size_in_bytes": 0},
            "fs": self._fs_stats(ps["timestamp"]),
            "http": {"current_open": 0, "total_opened": 0},
            "recovery": dict(recovery.stats) if recovery else {},
        }

    def _fs_stats(self, ts: int) -> dict:
        import shutil as _sh
        try:
            du = _sh.disk_usage(str(self.data_path))
            entry = {"path": str(self.data_path), "type": "local",
                     "total_in_bytes": du.total,
                     "free_in_bytes": du.free,
                     "available_in_bytes": du.free}
        except OSError:
            entry = {"path": str(self.data_path), "type": "local",
                     "total_in_bytes": 0,
                     "free_in_bytes": 0, "available_in_bytes": 0}
        total = {k: v for k, v in entry.items()
                 if k not in ("path", "type")}
        return {"timestamp": ts, "total": total, "data": [entry]}

    @staticmethod
    def _parse_buffer_size(raw) -> int:
        """'10%' of total memory, or an absolute byte size ('512mb')."""
        s = str(raw).strip().lower()
        if s.endswith("%"):
            try:
                import os as _os
                total = _os.sysconf("SC_PHYS_PAGES") * \
                    _os.sysconf("SC_PAGE_SIZE")
            except (OSError, ValueError):
                total = 1 << 32
            return int(total * float(s[:-1]) / 100.0)
        units = {"kb": 1 << 10, "mb": 1 << 20, "gb": 1 << 30, "b": 1}
        for suffix, mult in units.items():
            if s.endswith(suffix):
                return int(float(s[: -len(suffix)]) * mult)
        return int(float(s))

    def _schedule_imc(self) -> None:
        t = _threading.Timer(
            self.settings.get_as_float(
                "indices.memory.interval_s", 5.0), self._imc_tick)
        t.daemon = True
        self._imc_timer = t
        t.start()

    def _imc_tick(self) -> None:
        try:
            self.indexing_memory_check()
        except Exception:                # noqa: BLE001 — keep governing
            pass
        if self._started:
            self._schedule_imc()

    def indexing_memory_check(self) -> int:
        """One governor pass: refresh the largest write buffers until the
        node-wide total fits the budget. → buffers refreshed."""
        sized = []
        for name, svc in list(self.indices_service.indices.items()):
            for sid, engine in list(svc.engines.items()):
                try:
                    sized.append((engine.buffer_memory_bytes(), engine))
                except Exception:        # noqa: BLE001 — engine closing
                    continue
        total = sum(b for b, _ in sized)
        refreshed = 0
        if total <= self._index_buffer_budget:
            return 0
        for nbytes, engine in sorted(sized, key=lambda x: -x[0]):
            if total <= self._index_buffer_budget or nbytes == 0:
                break
            try:
                engine.refresh()
                refreshed += 1
                total -= nbytes
            except Exception:            # noqa: BLE001 — engine closing
                continue
        return refreshed

    def _schedule_ttl_sweep(self) -> None:
        t = _threading.Timer(self._ttl_interval, self._ttl_tick)
        t.daemon = True
        self._ttl_timer = t
        t.start()

    def _ttl_tick(self) -> None:
        try:
            self.ttl_sweep_once()
        except Exception:                # noqa: BLE001 — keep sweeping
            pass
        if self._started:
            self._schedule_ttl_sweep()

    def ttl_sweep_once(self) -> int:
        """One TTL purge pass (IndicesTTLService.PurgerThread): find
        expired docs per local shard, delete them through the replicated
        path (routing-aware via the doc's stored _routing)."""
        now_ms = int(time.time() * 1000)
        purged = 0
        state = self.cluster_service.state()
        for name, svc in list(self.indices_service.indices.items()):
            # only primaries sweep (IndicesTTLService purges on primary
            # shards; replicas receive the replicated deletes)
            primaries = {s.shard for s in
                         state.routing_table.index_shards(name)
                         if s.primary and s.node_id == self.node_id}
            for sid, engine in list(svc.engines.items()):
                if sid not in primaries:
                    continue
                for did in engine.expired_docs(now_ms):
                    try:
                        got = engine.get(did)
                        routing = (got.meta or {}).get("_routing")
                        self.document_actions.delete_doc(name, did,
                                                         routing=routing)
                        purged += 1
                    except Exception:    # noqa: BLE001 — racing writes
                        continue
        return purged

    def _handle_node_stats(self, request: dict, source) -> dict:
        return self.local_node_stats()

    def _handle_hot_threads(self, request: dict, source) -> dict:
        from elasticsearch_tpu.monitor import hot_threads
        return {"text": hot_threads(
            snapshots=int(request.get("snapshots", 10)),
            interval=float(request.get("interval", 0.05)),
            threads=int(request.get("threads", 3)))}

    def _fan_out_nodes(self, action: str, request: dict) -> dict:
        """Collect one payload per cluster node (TransportNodesAction)."""
        state = self.cluster_service.state()
        out = {}
        futures = []
        for nid, n in state.nodes.items():
            if nid == self.node_id:
                continue
            futures.append((nid, self.transport_service.send_request(
                n, action, request, timeout=15.0)))
        handler = {self.NODE_STATS_ACTION: self._handle_node_stats,
                   self.HOT_THREADS_ACTION: self._handle_hot_threads,
                   self.TASKS_LIST_ACTION: self._handle_tasks_list,
                   self.TRACE_COLLECT_ACTION:
                       self._handle_trace_collect}[action]
        out[self.node_id] = handler(request, None)
        for nid, fut in futures:
            try:
                out[nid] = fut.result(20.0)
            except Exception:                    # noqa: BLE001 — node gone
                continue
        return out

    def collect_nodes_stats(self) -> dict:
        return {"cluster_name": self.cluster_service.state().cluster_name,
                "nodes": self._fan_out_nodes(self.NODE_STATS_ACTION, {})}

    # ---- span tracing (observability/tracing.py) ---------------------------

    def _handle_trace_collect(self, request: dict, source) -> dict:
        """One node's span records — for one trace id, or everything in
        the store (the Chrome-trace dump)."""
        from elasticsearch_tpu.observability import tracing
        request = request or {}
        trace_id = request.get("trace_id")
        spans = tracing.spans_for(self.node_id, trace_id) if trace_id \
            else tracing.all_spans(self.node_id)
        from elasticsearch_tpu.observability import timeseries
        return {"name": self.node_name, "spans": spans,
                "stats": tracing.store_stats(self.node_id),
                # the telemetry ring's samples ride along so the Chrome
                # export can draw per-node counter tracks (ledger bytes,
                # lane counts) under the span timeline
                "counters": timeseries.ring_samples(self.node_id)}

    def collect_trace(self, trace_id: str) -> dict:
        """GET /_tasks/{id}/trace — gather one trace's spans from every
        node and reassemble the cross-node tree under the coordinating
        task id (span parent links survive the wire, so remote shard
        subtrees nest under the coordinator's fan-out spans)."""
        from elasticsearch_tpu.observability import tracing
        per_node = self._fan_out_nodes(self.TRACE_COLLECT_ACTION,
                                       {"trace_id": trace_id})
        spans = [s for doc in per_node.values() for s in doc["spans"]]
        return {
            "trace_id": trace_id,
            "span_count": len(spans),
            "nodes": sorted({s["node"] for s in spans}),
            "open_spans": sum(doc["stats"]["open_spans"]
                              for doc in per_node.values()),
            "tree": tracing.build_tree(spans),
        }

    def collect_chrome_trace(self, trace_id: str | None = None) -> dict:
        """GET /_nodes/trace — every node's stored spans (optionally one
        trace) as a Chrome Trace Event Format document for offline
        viewing in chrome://tracing / Perfetto."""
        from elasticsearch_tpu.observability import chrome
        self.telemetry_tick()            # the export's final sample
        per_node = self._fan_out_nodes(
            self.TRACE_COLLECT_ACTION,
            {"trace_id": trace_id} if trace_id else {})
        spans = [s for doc in per_node.values() for s in doc["spans"]]
        spans.sort(key=lambda s: s["start_us"])
        counters = {nid: doc.get("counters") or []
                    for nid, doc in per_node.items()}
        return chrome.chrome_trace(spans, counters=counters)

    # ---- live telemetry plane (observability/{ledger,timeseries}) ---------

    def resolve_engine_index(self, engine_uuid: str) -> str | None:
        """engine uuid → index name, for ledger rows whose charge site
        didn't know the index (the block cache keys by engine only)."""
        for name, svc in self.indices_service.indices.items():
            for engine in svc.engines.values():
                if engine.engine_uuid == engine_uuid:
                    return name
        return None

    def telemetry_tick(self, force: bool = False) -> bool:
        """Snapshot this node's cumulative counters into the timeseries
        ring (scrape-driven and throttled: search hot paths never pay
        for windowing). Hedge counters ride as extra series next to the
        lane/jit/slo/ledger sample."""
        from elasticsearch_tpu.observability import timeseries
        extra = {}
        try:
            for k, v in self.search_actions.replica_stats.hedge_stats() \
                    .items():
                if isinstance(v, (int, float)):
                    extra[f"hedge.{k}"] = v
        except Exception:                # noqa: BLE001 — pre-start tick
            pass
        return timeseries.tick(
            self.node_id, extra=extra,
            ledger=self.breaker_service.device_ledger, force=force)

    def collect_diagnostics(self, top: int = 25) -> dict:
        """GET /_nodes/diagnostics — the anomaly flight recorder's ring
        plus every book an operator needs next to it to diagnose a
        blown SLO after the fact, as ONE bundle: the program cost table
        (top programs + per-lane rollups), the device-memory ledger,
        windowed rates + SLO burn, scheduler depths, dispatch-watchdog
        stall state, and breaker states (plane + byte breakers)."""
        from elasticsearch_tpu.observability import costs as _costs
        from elasticsearch_tpu.observability import flightrec as _flight
        from elasticsearch_tpu.observability import slo as _slo
        from elasticsearch_tpu.observability import timeseries as _ts
        from elasticsearch_tpu.search import jit_exec as _jit_exec
        self.telemetry_tick()
        rates_doc = _ts.rates(self.node_id)
        rates_doc["slo_burn"] = _slo.windowed_burn(self.node_id,
                                                   rates_doc)
        return {
            "name": self.node_name,
            "timestamp": int(time.time() * 1000),
            "flight_recorder": {
                **_flight.stats(self.node_id),
                "events": _flight.events(self.node_id),
            },
            "programs": _costs.stats_doc(self.node_id, top=top),
            "device_memory": self.breaker_service.device_ledger.snapshot(
                resolve_index=self.resolve_engine_index),
            "rates": rates_doc,
            "slo": _slo.stats(self.node_id),
            "scheduler": self.search_actions.scheduler.stats(),
            # the hang half of the fault model next to the raise half
            # (breakers below): stalls, abandoned waits, quarantine
            # state, and the oldest in-flight wait's age
            "watchdog": self.search_actions.watchdog.stats(),
            "breakers": {
                "plane": _jit_exec.plane_breaker.stats(),
                "bytes": self.breaker_service.stats(),
            },
        }

    def collect_hot_threads(self, **params) -> str:
        per_node = self._fan_out_nodes(self.HOT_THREADS_ACTION, params)
        return "\n".join(f"::: node [{nid[:8]}]\n{p['text']}"
                         for nid, p in per_node.items())

    @property
    def is_master(self) -> bool:
        return self.cluster_service.state().master_node_id == self.node_id

    def _persist_state(self, old: ClusterState, new: ClusterState) -> None:
        new.persist(self.data_path / "_state")

    def _schedule_delayed_reroute(self, old, new) -> None:
        """RoutingService.scheduleDelayedReroute analog: when NODE_LEFT
        shards are waiting out their delayed-allocation window, arrange a
        reroute at expiry (only the master reroutes)."""
        import threading
        if new.master_node_id != self.node_id:
            return
        remaining = self.allocation.next_delayed_reroute_millis(new)
        if remaining is None:
            return
        if self._delayed_reroute_timer is not None and \
                self._delayed_reroute_timer.is_alive():
            return
        t = threading.Timer(remaining / 1000.0 + 0.05, self._delayed_reroute)
        t.daemon = True
        t.start()
        self._delayed_reroute_timer = t

    def _delayed_reroute(self) -> None:
        if not self._started:
            return
        try:
            self.cluster_service.submit_state_update(
                "delayed reroute",
                lambda st: self.allocation.reroute(st, "delay expired"),
                priority=URGENT)
        except RuntimeError:
            pass                                 # cluster service closed

    def wait_for_health(self, status: str | None = "green",
                        timeout: float = 10.0,
                        wait_for_nodes: str | int | None = None) -> dict:
        """Health wait (wait_for_status / wait_for_nodes params of the
        health API). `wait_for_nodes` accepts N, '>=N', '<=N', '>N', '<N';
        status=None waits only on the node predicate."""
        want = {"green": ("green",), "yellow": ("green", "yellow"),
                None: ("green", "yellow", "red")}[status]
        deadline = time.monotonic() + timeout
        while True:
            h = self.cluster_service.state().health(
                len(self.cluster_service.pending_tasks()))
            nodes_ok = _nodes_predicate(wait_for_nodes, h["number_of_nodes"])
            if h["status"] in want and nodes_ok and \
                    h["number_of_pending_tasks"] == 0:
                return h
            if time.monotonic() > deadline:
                h["timed_out"] = True
                return h
            time.sleep(0.01)

    def close(self) -> None:
        """Graceful shutdown: leave the cluster, then stop services."""
        if self._started:
            self._started = False
            self.plugins_service.apply_node_stop(self)
            if self._delayed_reroute_timer is not None:
                self._delayed_reroute_timer.cancel()
            if self._ttl_timer is not None:
                self._ttl_timer.cancel()
            if getattr(self, "_imc_timer", None) is not None:
                self._imc_timer.cancel()
            if getattr(self, "resource_watcher", None):
                self.resource_watcher.stop()
            if getattr(self, "cluster_info_service", None):
                self.cluster_info_service.stop()
            self.search_actions.close()
            self.discovery.stop()
            self.indices_service.close()
            self.cluster_service.close()
            self.transport_service.close()
            self.thread_pool.shutdown()

    def kill(self) -> None:
        """Abrupt death — no leave notification, no flush ordering; the
        cluster must detect the loss via fault detection (test disruption
        helper, mirrors InternalTestCluster restartNode(KILL))."""
        if self._started:
            self._started = False
            if self._delayed_reroute_timer is not None:
                self._delayed_reroute_timer.cancel()
            if getattr(self, "cluster_info_service", None):
                self.cluster_info_service.stop()
            self.transport_service.close()
            self.discovery.master_fd.stop()
            self.discovery.nodes_fd.stop()
            self.discovery._running = False
            self.cluster_service.close()
            self.indices_service.close()
            self.thread_pool.shutdown()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # ---- document action layer (core/action/{index,get,delete,update}) ----

    def index_doc(self, index: str, doc_id: str | None, source: dict,
                  routing: str | None = None, version: int | None = None,
                  op_type: str = "index", refresh: bool = False,
                  version_type: str = "internal",
                  meta: dict | None = None) -> dict:
        return self.document_actions.index_doc(
            index, doc_id, source, routing=routing, version=version,
            op_type=op_type, refresh=refresh, version_type=version_type,
            meta=meta)

    def get_doc(self, index: str, doc_id: str,
                routing: str | None = None, realtime: bool = True,
                refresh: bool = False) -> dict:
        return self.document_actions.get_doc(index, doc_id, routing=routing,
                                             realtime=realtime,
                                             refresh=refresh)

    def delete_doc(self, index: str, doc_id: str,
                   routing: str | None = None, version: int | None = None,
                   refresh: bool = False,
                   version_type: str = "internal") -> dict:
        return self.document_actions.delete_doc(
            index, doc_id, routing=routing, version=version, refresh=refresh,
            version_type=version_type)

    def update_doc(self, index: str, doc_id: str, body: dict,
                   routing: str | None = None, refresh: bool = False,
                   version: int | None = None,
                   meta: dict | None = None) -> dict:
        return self.document_actions.update_doc(
            index, doc_id, body, routing=routing, refresh=refresh,
            version=version, meta=meta)

    def mget(self, body: dict, default_index: str | None = None,
             realtime: bool = True, refresh: bool = False) -> dict:
        return self.document_actions.mget(body, default_index,
                                          realtime=realtime,
                                          refresh=refresh)

    def bulk(self, operations: list[tuple[str, dict, dict | None]],
             refresh: bool = False) -> dict:
        """operations: (action, metadata, source) triples, pre-parsed from
        NDJSON by the REST layer or built by the client."""
        return self.document_actions.bulk(operations, refresh=refresh)

    # ---- search entry ------------------------------------------------------

    def search(self, index: str, body: dict | None = None,
               scroll: str | None = None,
               search_type: str | None = None,
               routing: str | None = None,
               preference: str | None = None) -> dict:
        return self.search_actions.search(index, body, scroll=scroll,
                                          search_type=search_type,
                                          routing=routing,
                                          preference=preference)

    def count(self, index: str, body: dict | None = None,
              routing: str | None = None,
              preference: str | None = None) -> dict:
        return self.search_actions.count(index, body, routing=routing,
                                         preference=preference)


def _nodes_predicate(expr, actual: int) -> bool:
    if expr is None:
        return True
    s = str(expr)
    for op, fn in ((">=", lambda a, b: a >= b), ("<=", lambda a, b: a <= b),
                   (">", lambda a, b: a > b), ("<", lambda a, b: a < b)):
        if s.startswith(op):
            return fn(actual, int(s[len(op):]))
    return actual == int(s)


def _deep_merge(base: dict, patch: dict) -> dict:
    for k, v in patch.items():
        if isinstance(v, dict) and isinstance(base.get(k), dict):
            base[k] = _deep_merge(dict(base[k]), v)
        else:
            base[k] = v
    return base


def _apply_update_script(source: dict, script,
                         meta: dict | None = None
                         ) -> tuple[dict, str, dict]:
    """Run an update script against the document (UpdateHelper.prepare):
    the script sees `ctx` with a mutable `_source` plus `op`/`_ttl`/
    `_timestamp`/`_id` and `params`; → (new source, op, meta_updates)
    where op is "index" (reindex), "none" (noop) or "delete" (remove the
    doc) and meta_updates carries any _ttl/_timestamp the script set.
    Interpreted by GroovyLite (scriptlang.py), the lang-groovy analog —
    conditionals, loops and collection mutation all work."""
    from elasticsearch_tpu.search.script_engines import resolve_engine
    lang = None
    if isinstance(script, dict):
        src = script.get("source", script.get("inline", ""))
        params = script.get("params", {})
        lang = script.get("lang")
    else:
        src, params = str(script), {}
    compile_fn = resolve_engine(lang)
    ctx = {"_source": source, "op": "index", **(meta or {})}
    before = {k: ctx.get(k) for k in ("_ttl", "_timestamp")}
    compile_fn(src).run({"ctx": ctx, "params": params})
    op = ctx.get("op", "index")
    if op not in ("index", "none", "noop", "delete"):
        raise ValueError(f"invalid ctx.op [{op}]")
    # scripts may restamp ttl/timestamp (UpdateHelper reads ctx._ttl /
    # ctx._timestamp after the script runs)
    meta_updates = {k: ctx[k] for k in ("_ttl", "_timestamp")
                    if ctx.get(k) is not None and ctx.get(k) != before[k]}
    return ctx["_source"], "none" if op == "noop" else op, meta_updates
