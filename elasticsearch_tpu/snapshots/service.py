"""SnapshotsService — create/get/delete/restore snapshots.

Reference call shape (core/snapshots/SnapshotsService.java): the master
records the snapshot in a cluster-state custom (visibility + concurrency
gate, ``SnapshotsInProgress``), fans shard uploads out to the nodes
holding each primary (SnapshotShardsService analog — here a transport
action per shard), then finalizes global metadata in the repository.
Restore (RestoreService): indices are re-created from the snapshot's
metadata with an ``index.restore.*`` marker; each primary's recovery then
pulls files from the repository instead of a peer (the reference's
restore recovery source), and replicas peer-recover from the restored
primary as usual.

Repository registrations live in the ``repositories`` cluster-state
custom ({name → {type, settings}}), the analog of the reference's
RepositoriesMetaData persisted in MetaData customs.
"""

from __future__ import annotations

import time

from elasticsearch_tpu import __version__
from elasticsearch_tpu.repositories import (
    RepositoryMissingError, repository_for)

SNAPSHOT_SHARD_ACTION = "internal:snapshot/shard"


class SnapshotsService:
    def __init__(self, node):
        self.node = node
        node.transport_service.register_request_handler(
            SNAPSHOT_SHARD_ACTION, self._handle_snapshot_shard,
            executor="snapshot", sync=True)

    # ---- repository registry ----------------------------------------------

    def _repos(self) -> dict:
        return self.node.cluster_service.state().customs.get(
            "repositories", {})

    def repository(self, name: str):
        spec = self._repos().get(name)
        if spec is None:
            raise RepositoryMissingError(f"[{name}] missing")
        return repository_for(name, spec)

    def put_repository(self, name: str, body: dict) -> None:
        # relative fs locations resolve under the node's data path (the
        # reference requires them inside path.repo; resolving against the
        # process CWD would litter it with repository directories)
        settings = dict(body.get("settings") or {})
        loc = settings.get("location")
        if loc is not None and not str(loc).startswith("/"):
            settings["location"] = str(self.node.data_path / "repos" / loc)
            body = {**body, "settings": settings}
        repository_for(name, body).verify()      # fail fast on bad config

        def local():
            def update(st):
                repos = {**st.customs.get("repositories", {}), name: body}
                return st.with_(customs={**st.customs,
                                         "repositories": repos})
            self.node.cluster_service.submit_and_wait(
                f"put-repository [{name}]", update)
        self.node.indices_service._master_op(
            "put-repository", {"name": name, "body": body}, local)

    def delete_repository(self, name: str) -> None:
        def local():
            def update(st):
                repos = {k: v for k, v in
                         st.customs.get("repositories", {}).items()
                         if k != name}
                return st.with_(customs={**st.customs,
                                         "repositories": repos})
            self.node.cluster_service.submit_and_wait(
                f"delete-repository [{name}]", update)
        self.node.indices_service._master_op(
            "delete-repository", {"name": name}, local)

    def get_repositories(self, name: str | None = None) -> dict:
        repos = self._repos()
        if name and name not in ("_all", "*"):
            if name not in repos:
                raise RepositoryMissingError(f"[{name}] missing")
            return {name: repos[name]}
        return dict(repos)

    # ---- create ------------------------------------------------------------

    def create_snapshot(self, repo: str, snapshot: str,
                        body: dict | None = None) -> dict:
        body = body or {}
        request = {"repo": repo, "snapshot": snapshot, "body": body}
        out: dict = {}

        def local():
            out.update(self._create_on_master(repo, snapshot, body))
        self.node.indices_service._master_op("create-snapshot", request,
                                             local)
        if not out:                              # ran remotely on master
            out.update(self.repository(repo).read_snapshot(snapshot))
        return {"snapshot": out}

    def _create_on_master(self, repo: str, snapshot: str,
                          body: dict) -> dict:
        repository = self.repository(repo)
        repository.begin_snapshot(snapshot)
        state = self.node.cluster_service.state()
        expr = ",".join(body.get("indices", ["_all"])) \
            if isinstance(body.get("indices", "_all"), list) \
            else body.get("indices", "_all")
        names = [n for n in self.node.indices_service._resolve(state, expr)
                 if state.indices[n].state == "open"]
        t0 = time.time()                # wall-clock ok: start_time epoch
        # visibility + concurrency gate (SnapshotsInProgress custom)
        self._set_in_progress({"repository": repo, "snapshot": snapshot,
                               "state": "STARTED", "indices": names})
        shards_ok = shards_failed = 0
        failures: list[dict] = []
        indices_meta: dict = {}
        try:
            for name in names:
                meta = state.indices[name]
                indices_meta[name] = {
                    "shards": meta.number_of_shards,
                    "settings": dict(meta.settings),
                    "mappings": meta.mappings or {},
                }
                for shard in range(meta.number_of_shards):
                    try:
                        self._snapshot_one_shard(state, repo, snapshot,
                                                 name, shard)
                        shards_ok += 1
                    except Exception as e:       # noqa: BLE001 — partial
                        shards_failed += 1
                        failures.append({"index": name, "shard_id": shard,
                                         "reason": str(e)})
        finally:
            self._set_in_progress(None)
        meta_out = {
            "snapshot": snapshot,
            "repository": repo,
            "version": __version__,
            "version_id": 2040099,
            "indices": indices_meta,
            "state": "SUCCESS" if not shards_failed else "PARTIAL",
            "start_time_in_millis": int(t0 * 1000),
            "end_time_in_millis": int(time.time() * 1000),  # wall-clock ok
            "shards": {"total": shards_ok + shards_failed,
                       "successful": shards_ok, "failed": shards_failed},
            "failures": failures,
        }
        repository.finalize_snapshot(snapshot, meta_out)
        return meta_out

    def _snapshot_one_shard(self, state, repo: str, snapshot: str,
                            name: str, shard: int) -> dict:
        pr = state.routing_table.primary(name, shard)
        if pr is None or not pr.active:
            raise RuntimeError(f"primary [{name}][{shard}] not active")
        request = {"repo": repo, "snapshot": snapshot,
                   "index": name, "shard": shard}
        if pr.node_id == self.node.node_id:
            return self._handle_snapshot_shard(request, None)
        target = state.node(pr.node_id)
        return self.node.transport_service.submit_request(
            target, SNAPSHOT_SHARD_ACTION, request, timeout=120.0)

    def _handle_snapshot_shard(self, request: dict, source) -> dict:
        svc = self.node.indices_service.indices.get(request["index"])
        engine = svc.engines.get(request["shard"]) if svc else None
        if engine is None:
            raise RuntimeError(
                f"[{request['index']}][{request['shard']}] not on this node")
        repository = self.repository(request["repo"])
        return repository.snapshot_shard(engine, request["index"],
                                         request["shard"],
                                         request["snapshot"])

    def _set_in_progress(self, entry: dict | None) -> None:
        def update(st):
            customs = dict(st.customs)
            if entry is None:
                customs.pop("snapshots_in_progress", None)
            else:
                if customs.get("snapshots_in_progress"):
                    raise RuntimeError(
                        "a snapshot is already running")
                if customs.get("snapshot_deletions_in_progress"):
                    raise RuntimeError(
                        "a snapshot deletion is in progress")
                customs["snapshots_in_progress"] = entry
            return st.with_(customs=customs)
        self.node.cluster_service.submit_and_wait("update-snapshot-state",
                                                  update)

    def _set_deletion_in_progress(self, entry: dict | None) -> None:
        """Mutual-exclusion gate between deletes and running creates — the
        reference's SnapshotsService likewise rejects deletes while a
        snapshot is STARTED (SnapshotsInProgress check in deleteSnapshot).
        Both markers flow through the master's single-writer queue, so
        create/delete (and their index.json read-modify-writes, which only
        happen while the corresponding marker is held) are serialized."""
        def update(st):
            customs = dict(st.customs)
            if entry is None:
                customs.pop("snapshot_deletions_in_progress", None)
            else:
                if customs.get("snapshots_in_progress"):
                    raise RuntimeError(
                        "cannot delete snapshot while a snapshot is running")
                if customs.get("snapshot_deletions_in_progress"):
                    raise RuntimeError(
                        "a snapshot deletion is already in progress")
                customs["snapshot_deletions_in_progress"] = entry
            return st.with_(customs=customs)
        self.node.cluster_service.submit_and_wait("update-snapshot-deletion",
                                                  update)

    # ---- read / delete -----------------------------------------------------

    def get_snapshots(self, repo: str, which: str = "_all") -> dict:
        repository = self.repository(repo)
        if which in ("_all", "*"):
            names = repository.snapshot_names()
        else:
            names = which.split(",")
        return {"snapshots": [repository.read_snapshot(n) for n in names]}

    def snapshot_status(self) -> dict:
        entry = self.node.cluster_service.state().customs.get(
            "snapshots_in_progress")
        return {"snapshots": [entry] if entry else []}

    def delete_snapshot(self, repo: str, snapshot: str) -> None:
        def local():
            self._set_deletion_in_progress(
                {"repository": repo, "snapshot": snapshot})
            try:
                self.repository(repo).delete_snapshot(snapshot)
            finally:
                self._set_deletion_in_progress(None)
        self.node.indices_service._master_op(
            "delete-snapshot", {"repo": repo, "snapshot": snapshot}, local)

    # ---- restore -----------------------------------------------------------

    def restore_snapshot(self, repo: str, snapshot: str,
                         body: dict | None = None) -> dict:
        body = body or {}
        request = {"repo": repo, "snapshot": snapshot, "body": body}
        out: dict = {}

        def local():
            out.update(self._restore_on_master(repo, snapshot, body))
        self.node.indices_service._master_op("restore-snapshot", request,
                                             local)
        return out or {"accepted": True}

    def _restore_on_master(self, repo: str, snapshot: str,
                           body: dict) -> dict:
        meta = self.repository(repo).read_snapshot(snapshot)
        want = body.get("indices")
        if isinstance(want, str):
            want = [s.strip() for s in want.split(",")]
        rename_pat = body.get("rename_pattern")
        rename_rep = body.get("rename_replacement", "")
        restored = []
        for name, imeta in meta["indices"].items():
            if want and name not in want:
                continue
            target = name
            if rename_pat:
                import re
                target = re.sub(rename_pat, rename_rep, name)
            settings = dict(imeta["settings"])
            settings.update(body.get("index_settings", {}))
            # the restore marker routes primary recovery to the repository
            # (the reference's restore recovery source on IndexMetaData)
            settings["index.restore.repository"] = repo
            settings["index.restore.snapshot"] = snapshot
            settings["index.restore.source_index"] = name
            state = self.node.cluster_service.state()
            existing = state.indices.get(target)
            if existing is not None:
                # restoring over an existing index requires it closed
                # (RestoreService.validateExistingIndex); the restore
                # replaces it
                if existing.state != "close":
                    from elasticsearch_tpu.common.errors import (
                        IllegalArgumentError)
                    raise IllegalArgumentError(
                        f"cannot restore index [{target}] because it's "
                        f"open")
                self.node.indices_service.delete_index(target)
            self.node.indices_service.create_index(
                target, {"settings": settings,
                         "mappings": imeta["mappings"]})
            restored.append(target)
        return {"snapshot": {"snapshot": snapshot,
                             "indices": restored,
                             "shards": meta.get("shards", {})}}
