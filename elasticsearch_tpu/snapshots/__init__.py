"""Snapshot/restore — master-coordinated backup of indices to a
repository and recovery back out of it.

Reference: core/snapshots/ — SnapshotsService (master-side coordination,
progress tracked in the SnapshotsInProgress cluster-state custom),
SnapshotShardsService (data nodes upload their primary shards),
RestoreService (indices re-created from snapshot metadata, shards
recovered from the repository instead of a peer).
"""

from elasticsearch_tpu.snapshots.service import SnapshotsService

__all__ = ["SnapshotsService"]
