"""CLI entry point (`estpu`).

Reference: core/bootstrap/Elasticsearch.java:33 → Bootstrap.setup/start —
CLI parse, environment prep, node start, HTTP ingress last, then wait.
(The reference's mlockall/seccomp hardening is JVM-era host glue; the
analogous concerns here — device memory pinning and sandboxing — belong to
the TPU runtime/XLA.)
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.server import RestServer


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="estpu", description="elasticsearch-tpu node")
    parser.add_argument("--data", default="data", help="data directory")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=9200)
    parser.add_argument("--cpu", action="store_true",
                        help="force JAX CPU platform (no TPU)")
    parser.add_argument("-E", action="append", default=[], metavar="K=V",
                        help="setting override (repeatable)")
    parser.add_argument("--portsfile", default=None,
                        help="write 'http=<port>\\ntransport=<port>' here "
                             "once bound (test orchestration; ref: the "
                             "--portsfile node flag)")
    args = parser.parse_args(argv)

    if args.cpu:
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")

    overrides = {}
    for kv in args.E:
        k, _, v = kv.partition("=")
        overrides[k] = v
    settings = Settings({"path.data": args.data, **overrides})

    node = Node(settings, data_path=args.data).start()
    server = RestServer(node, host=args.host, port=args.port).start()
    taddr = node.transport_service.transport.bound_address()
    print(f"[estpu] node [{node.node_name}] started, "
          f"http on {server.host}:{server.port}, transport on {taddr}",
          flush=True)
    if args.portsfile:
        from pathlib import Path
        Path(args.portsfile).write_text(
            f"http={server.port}\ntransport={taddr.port}\n")

    stop = threading.Event()

    def handle(sig, frame):
        stop.set()

    signal.signal(signal.SIGINT, handle)
    signal.signal(signal.SIGTERM, handle)
    stop.wait()
    print("[estpu] stopping", flush=True)
    server.stop()
    node.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
