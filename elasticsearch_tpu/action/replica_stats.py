"""Adaptive replica selection + hedged-request accounting.

Reference: the reference engine's adaptive replica selection
(``AdaptiveSelectionStats`` / ``ResponseCollectorService``), itself an
implementation of the C3 replica-ranking function (Suresh et al.,
NSDI'15): each coordinating node keeps, per target node, an EWMA of the
response time it observed, an EWMA of the SERVICE time the data node
reports for the work itself, the data node's search-pool queue depth
(piggybacked on every shard payload the way the reference ships queue
stats on the QuerySearchResult), and the number of requests currently
outstanding. Copy try-order ranks ascending by

    Ψ(s) = R̄(s) − µ̄(s) + q̂(s)³ · µ̄(s),   q̂ = 1 + outstanding + queue

— C3's cubic queue penalty: R̄ − µ̄ isolates the network/transit share,
and the q̂³·µ̄ term makes a loaded (or browned-out) copy's rank explode
long before its EWMA alone would sink it. Unobserved nodes rank 0.0, so
cold copies are explored first and acquire real ranks after one
response.

The table also owns the HEDGING side of tail tolerance ("The Tail at
Scale", Dean & Barroso): per shard group, a fixed-bucket latency
histogram of observed response times whose p-quantile (floor/ceiling
bounded) is the adaptive hedge delay, and the
``hedges_launched / hedges_won / hedges_cancelled`` counters the
acceptance gate reconciles (``launched == won + cancelled + in_flight``
at every instant).
"""

from __future__ import annotations

import threading

from elasticsearch_tpu.observability.histograms import LatencyHistogram


class ReplicaStatsTable:
    """Per-coordinating-node replica health table (one per
    SearchActions). All methods are thread-safe — the fan-out pool
    feeds it concurrently."""

    def __init__(self, alpha: float = 0.3):
        #: EWMA smoothing factor (``search.ars.alpha``): weight of the
        #: NEWEST observation; the reference uses the same one-knob EWMA
        self.alpha = min(max(float(alpha), 0.0), 1.0)
        self._lock = threading.Lock()
        self._nodes: dict[str, dict] = {}
        #: (index, shard) → latency histogram of observed response times
        #: — the per-shard-group distribution the hedge delay quantile
        #: reads (fixed √2-spaced buckets, O(1) record)
        self._group_hist: dict[tuple, LatencyHistogram] = {}
        self.hedges_launched = 0
        self.hedges_won = 0
        self.hedges_cancelled = 0

    # ---- per-node health ---------------------------------------------------

    def _node(self, node_id: str) -> dict:
        st = self._nodes.get(node_id)
        if st is None:
            st = self._nodes[node_id] = {
                "ewma_response_ms": None, "ewma_service_ms": None,
                "queue": 0, "outstanding": 0, "observations": 0}
        return st

    def begin(self, node_id: str) -> None:
        """A request to ``node_id`` is now in flight."""
        with self._lock:
            self._node(node_id)["outstanding"] += 1

    def end(self, node_id: str) -> None:
        with self._lock:
            st = self._node(node_id)
            st["outstanding"] = max(st["outstanding"] - 1, 0)

    def observe(self, node_id: str, response_ms: float,
                service_ms: float | None = None,
                queue: int | None = None) -> None:
        """Fold one observed response into the node's EWMAs.
        ``service_ms``/``queue`` come from the payload's piggybacked
        ``_ars`` block (absent on failures and latency-floor samples)."""
        with self._lock:
            st = self._node(node_id)
            st["observations"] += 1
            for key, val in (("ewma_response_ms", response_ms),
                             ("ewma_service_ms", service_ms)):
                if val is None:
                    continue
                cur = st[key]
                st[key] = float(val) if cur is None else \
                    (1.0 - self.alpha) * cur + self.alpha * float(val)
            if queue is not None:
                st["queue"] = int(queue)

    def _rank_locked(self, node_id: str) -> float:
        st = self._nodes.get(node_id)
        if st is None or not st["observations"]:
            return 0.0                    # unobserved: explore first
        r = st["ewma_response_ms"] or 0.0
        mu = st["ewma_service_ms"] if st["ewma_service_ms"] is not None \
            else r
        q_hat = 1.0 + st["outstanding"] + st["queue"]
        return r - mu + (q_hat ** 3) * mu

    def rank(self, node_id: str) -> float:
        with self._lock:
            return self._rank_locked(node_id)

    def order(self, copies: list) -> list:
        """Re-rank a copy try-order by ascending C3 score. The sort is
        STABLE, so ties (and the all-unobserved cold start) keep the
        caller's baseline order — local-first rotation under the default
        preference."""
        with self._lock:
            return sorted(copies,
                          key=lambda c: self._rank_locked(c.node_id))

    # ---- hedge delay -------------------------------------------------------

    def observe_group(self, group_key: tuple, response_ms: float) -> None:
        with self._lock:
            h = self._group_hist.get(group_key)
            if h is None:
                h = self._group_hist[group_key] = LatencyHistogram()
        h.observe(response_ms)            # histogram has its own lock

    def hedge_delay_ms(self, group_key: tuple, quantile: float,
                       floor_ms: float, ceiling_ms: float) -> float:
        """Adaptive hedge delay for one shard group: the observed
        latency distribution's p-quantile, bounded below (don't hedge
        into ordinary jitter) and above (a pathological history must
        not disable hedging). No history yet → the ceiling, so a cold
        coordinator never hedge-storms."""
        with self._lock:
            h = self._group_hist.get(group_key)
        if h is None or h.count == 0:
            return float(ceiling_ms)
        return min(max(h.percentile(quantile), float(floor_ms)),
                   float(ceiling_ms))

    # ---- hedge counters ----------------------------------------------------

    def note_hedge_launched(self) -> None:
        with self._lock:
            self.hedges_launched += 1

    def note_hedge_won(self) -> None:
        with self._lock:
            self.hedges_won += 1

    def note_hedge_cancelled(self) -> None:
        with self._lock:
            self.hedges_cancelled += 1

    def hedge_stats(self) -> dict:
        with self._lock:
            return {
                "hedges_launched": self.hedges_launched,
                "hedges_won": self.hedges_won,
                "hedges_cancelled": self.hedges_cancelled,
                # reconciliation invariant: launched == won + cancelled
                # + in_flight — every launched hedge terminally either
                # wins or is cancelled
                "hedges_in_flight": self.hedges_launched
                - self.hedges_won - self.hedges_cancelled,
            }

    # ---- stats surface (_nodes/stats.adaptive_selection) -------------------

    def stats_dict(self) -> dict:
        with self._lock:
            nodes = {}
            for nid, st in sorted(self._nodes.items()):
                nodes[nid] = {
                    "rank": round(self._rank_locked(nid), 3),
                    "ewma_response_ms":
                        round(st["ewma_response_ms"], 3)
                        if st["ewma_response_ms"] is not None else None,
                    "ewma_service_ms":
                        round(st["ewma_service_ms"], 3)
                        if st["ewma_service_ms"] is not None else None,
                    "queue": st["queue"],
                    "outstanding": st["outstanding"],
                    "observations": st["observations"],
                }
        return {"nodes": nodes, "hedging": self.hedge_stats()}
