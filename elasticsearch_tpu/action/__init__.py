"""Action layer — the distributed RPC verbs over the transport.

Reference: core/action/ (~72k LoC). The reusable bases map as:

* :class:`~elasticsearch_tpu.action.replication.DocumentActions` —
  TransportReplicationAction (core/action/support/replication/
  TransportReplicationAction.java:81): reroute → primary → replicas.
* :class:`~elasticsearch_tpu.action.replication.BroadcastActions` —
  TransportBroadcastAction (core/action/support/broadcast/
  TransportBroadcastAction.java:48): one copy of every shard.
* :class:`~elasticsearch_tpu.action.search_action.SearchActions` —
  TransportSearchTypeAction (core/action/search/type/
  TransportSearchTypeAction.java:87): scatter query/fetch + reduce.
* Master forwarding lives on the Node (`_execute_master_action`) —
  TransportMasterNodeAction (core/action/support/master/
  TransportMasterNodeAction.java:50).
"""

from elasticsearch_tpu.action.replication import (
    BroadcastActions, DocumentActions)
from elasticsearch_tpu.action.search_action import SearchActions

__all__ = ["DocumentActions", "BroadcastActions", "SearchActions"]
