"""Write replication + routed reads over the transport.

Reference: core/action/support/replication/TransportReplicationAction.java:81
— ReroutePhase resolves the primary's node from cluster state and forwards
(:366), PrimaryPhase applies the op locally (:346,578), ReplicationPhase
fans the op out to every assigned copy (:689,828-864) and reports failed
replicas to the master; core/action/bulk/TransportShardBulkAction.java:116
(primary loop) / :448 (replica); core/action/support/single/shard/
TransportSingleShardAction.java:53 (routed get with copy failover);
core/action/support/broadcast/TransportBroadcastAction.java:48.
"""

from __future__ import annotations

import time
import uuid

from elasticsearch_tpu.cluster.routing import OperationRouting
from elasticsearch_tpu.cluster.state import NO_MASTER_BLOCK, ShardRouting
from elasticsearch_tpu.common.errors import (
    ClusterBlockError, DocumentMissingError, ElasticsearchTpuError,
    IllegalArgumentError, IndexAlreadyExistsError, UnavailableShardsError,
    reconstruct_error)
from elasticsearch_tpu.index.engine import MATCH_ANY
from elasticsearch_tpu.transport.service import (
    NodeDisconnectedError, RemoteTransportError, TransportException)


def update_get_section(source: dict | None, version,
                       wanted) -> dict:
    """The update API's `fields` → "get" section, built from the source
    the update just APPLIED (UpdateHelper.extractGetResult — no re-get,
    so a concurrent write can't leak into the response)."""
    from elasticsearch_tpu.common.settings import source_from_path as _sfp
    if isinstance(wanted, str):
        wanted = wanted.split(",")
    section: dict = {"found": True, "_version": version}
    fvals = {}
    for f in wanted or []:
        if f == "_source":
            section["_source"] = source
            continue
        v = _sfp(source or {}, f)
        if v is not None:
            fvals[f] = v if isinstance(v, list) else [v]
    if fvals:
        section["fields"] = fvals
    return section


def unwrap_remote(e: Exception) -> Exception:
    """RemoteTransportException.unwrapCause analog."""
    if isinstance(e, RemoteTransportError):
        return reconstruct_error(e.error_type, e.reason)
    return e


#: remote failures that mean "the routing I used was stale, not that the
#: operation is invalid" — retry against fresh state instead of failing
#: the request (the reference's TransportReplicationAction.retryPrimary
#: exceptions: shard not started / engine closed / not serving here)
RETRYABLE_REMOTE = ("ShardNotLocalError", "EngineClosedError",
                    "UnavailableShardsError", "IndexShardClosedError",
                    "DelayRecoveryError")


def _is_retryable(e: Exception) -> bool:
    return isinstance(e, RemoteTransportError) and \
        e.error_type in RETRYABLE_REMOTE


class DocumentActions:
    """Document CRUD + bulk with primary→replica synchronous replication."""

    INDEX_P = "indices:data/write/index[p]"
    INDEX_R = "indices:data/write/index[r]"
    DELETE_P = "indices:data/write/delete[p]"
    DELETE_R = "indices:data/write/delete[r]"
    UPDATE_P = "indices:data/write/update"
    BULK_P = "indices:data/write/bulk[s][p]"
    BULK_R = "indices:data/write/bulk[s][r]"
    GET_S = "indices:data/read/get[s]"
    EXPLAIN_S = "indices:data/read/explain[s]"
    TERMVECTORS_S = "indices:data/read/tv[s]"

    #: how long the reroute phase waits for an active primary (the
    #: reference's default index timeout is 1m; tests want seconds).
    #: REPLICA_TIMEOUT bounds how long a primary waits on one replica
    #: ack: in-process replica applies are ms-scale, so 8 s is already
    #: 3+ orders of magnitude of slack — and under injected message
    #: drops it is the difference between "replica failed, reallocate"
    #: in seconds and a half-minute write stall per lost frame
    PRIMARY_TIMEOUT = 15.0
    REPLICA_TIMEOUT = 8.0
    BLOCK_RETRY_TIMEOUT = 5.0

    def __init__(self, node):
        self.node = node
        ts = node.transport_service
        # Primary-phase handlers block waiting for replica acks, so they
        # run on the "index" pool while replica appliers run on "replica" —
        # distinct pools per workload class (ThreadPool.java:70-129), which
        # is what prevents a cross-node write-write thread-pool deadlock.
        ts.register_request_handler(self.INDEX_P, self._handle_index_p,
                                    executor="index", sync=True)
        ts.register_request_handler(self.INDEX_R, self._handle_index_r,
                                    executor="replica", sync=True)
        ts.register_request_handler(self.DELETE_P, self._handle_delete_p,
                                    executor="index", sync=True)
        ts.register_request_handler(self.DELETE_R, self._handle_delete_r,
                                    executor="replica", sync=True)
        ts.register_request_handler(self.UPDATE_P, self._handle_update,
                                    executor="index", sync=True)
        ts.register_request_handler(self.BULK_P, self._handle_bulk_p,
                                    executor="bulk", sync=True)
        ts.register_request_handler(self.BULK_R, self._handle_bulk_r,
                                    executor="replica", sync=True)
        ts.register_request_handler(self.GET_S, self._handle_get,
                                    executor="get", sync=True)
        ts.register_request_handler(self.EXPLAIN_S, self._handle_explain,
                                    executor="get", sync=True)
        ts.register_request_handler(self.TERMVECTORS_S,
                                    self._handle_termvectors,
                                    executor="get", sync=True)

    # ---- routing helpers ---------------------------------------------------

    def _state(self):
        return self.node.cluster_service.state()

    def _resolve_write_index(self, index: str, auto_create: bool = True) -> str:
        isvc = self.node.indices_service
        if auto_create and not isvc.has_index(index):
            try:
                isvc.create_index(index, {})
            except IndexAlreadyExistsError:
                pass                             # concurrent auto-create race
        names = isvc.resolve(index)
        return names[0]

    def _shard_id(self, name: str, doc_id: str, routing: str | None) -> int:
        meta = self._state().indices[name]
        return OperationRouting.shard_id(doc_id, meta.number_of_shards,
                                         routing)

    def _resolve_single(self, index: str) -> str:
        """Single-doc ops target exactly one concrete index (the reference
        rejects multi-index aliases for doc CRUD)."""
        names = self.node.indices_service.resolve(index)
        if len(names) != 1:
            raise IllegalArgumentError(
                f"[{index}] resolves to {len(names)} indices; single-"
                "document operations need exactly one")
        return names[0]

    def _await_primary(self, name: str, shard: int) -> ShardRouting:
        """ReroutePhase: observe cluster state until the primary is active
        (TransportReplicationAction.java:366 retryBecauseUnavailable)."""
        deadline = time.monotonic() + self.PRIMARY_TIMEOUT
        while True:
            state = self._state()
            pr = state.routing_table.primary(name, shard)
            if pr is not None and pr.active and \
                    state.node(pr.node_id) is not None:
                return pr
            if time.monotonic() > deadline:
                raise UnavailableShardsError(
                    f"[{name}][{shard}] primary shard is not active "
                    f"(timeout [{self.PRIMARY_TIMEOUT}s])", index=name,
                    shard=shard)
            time.sleep(0.05)

    def _on_primary(self, name: str, shard: int, request: dict, action: str,
                    local_fn) -> dict:
        """Route a primary-phase op: execute locally if the primary shard
        lives here, otherwise forward; retry once per routing change when
        the target turns out stale."""
        from elasticsearch_tpu.indices.service import ShardNotLocalError
        from elasticsearch_tpu.tasks import raise_if_cancelled
        deadline = time.monotonic() + self.PRIMARY_TIMEOUT
        last: Exception | None = None
        while time.monotonic() < deadline:
            # cooperative cancellation checkpoint BEFORE the primary
            # applies: once the op lands on the primary it must also
            # reach the replicas (cancelling between would silently
            # diverge copies), so the shed point is the attempt boundary
            raise_if_cancelled()
            pr = self._await_primary(name, shard)
            if pr.node_id == self.node.node_id:
                try:
                    return local_fn(request)
                except ShardNotLocalError as e:
                    # ownership moved DURING the local execution (the
                    # post-op recheck tripped — e.g. a relocation handoff
                    # landed mid-op): re-resolve and retry on the new
                    # primary, same as the remote retry path
                    last = e
                    time.sleep(0.05)
                    continue
            target = self._state().node(pr.node_id)
            # per-ATTEMPT timeout well below the overall deadline: a
            # single dropped frame must cost one retry round, not the
            # whole budget (a chaos-matrix lesson — with attempt ==
            # deadline, one lost RPC turned into UnavailableShards)
            attempt_timeout = min(
                5.0, max(deadline - time.monotonic(), 0.5))
            try:
                return self.node.transport_service.send_request(
                    target, action, request,
                    timeout=attempt_timeout).result(attempt_timeout + 5)
            except RemoteTransportError as e:
                if _is_retryable(e):             # stale routing at the
                    last = e                     # target (primary moved) →
                    time.sleep(0.1)              # wait for new state, retry
                    continue
                raise unwrap_remote(e) from None  # real application error
            except TransportException as e:
                last = e                         # node left →
                time.sleep(0.1)                  # wait for new state, retry
            except Exception as e:               # noqa: BLE001 — remote error
                raise unwrap_remote(e) from None
        raise UnavailableShardsError(
            f"[{name}][{shard}] primary op failed: {last}", index=name,
            shard=shard)

    # ---- replication fan-out (ReplicationPhase :689) -----------------------

    def _replicas_of(self, name: str, shard: int) -> list[ShardRouting]:
        """Every assigned copy except the primary — including INITIALIZING
        ones so recovering shards don't miss concurrent ops (the reference
        replicates to initializing/relocating copies too)."""
        state = self._state()
        return [c for c in state.routing_table.shard_copies(name, shard)
                if c.assigned and not c.primary]

    def _replicate(self, name: str, shard: int, action: str,
                   payload: dict) -> tuple[int, int, list[dict], set]:
        """→ (total_copies, successful, failures, delivered_node_ids).
        Failed replicas are reported shard-failed to the master
        (onReplicaFailure :864-900); the delivered set feeds the post-op
        ownership recheck."""
        copies = self._replicas_of(name, shard)
        futures = []
        state = self._state()
        delivered: set[str] = set()
        ok, failures = 1, []                     # primary already succeeded
        for c in copies:
            target = state.node(c.node_id)
            if target is None:
                # assigned copy whose node just dropped out of the state:
                # it is MISSING this op — it must be failed, not silently
                # skipped, or a later promotion serves stale data
                failures.append({"shard": shard, "index": name,
                                 "node": c.node_id, "status": "INTERNAL",
                                 "reason": "node holding copy left cluster"})
                self.node._on_shard_failed(
                    c, "replication target node left cluster")
                continue
            fut = self.node.transport_service.send_request(
                target, action, payload, timeout=self.REPLICA_TIMEOUT)
            futures.append((c, fut))
        for c, fut in futures:
            try:
                fut.result(self.REPLICA_TIMEOUT + 5)
                ok += 1
                delivered.add(c.node_id)
            except Exception as e:               # noqa: BLE001 — report it
                if self.node.transport_service._closed:
                    # the "replica failure" is an artifact of THIS node
                    # dying (its close failed the in-flight fan-out). A
                    # dying primary must not ack-with-failed-replica:
                    # the ack could still escape while the failure
                    # report dies with the node, and the promoted
                    # replica would silently miss an acked write
                    raise NodeDisconnectedError(
                        "node is shutting down mid-replication") from e
                failures.append({"shard": shard, "index": name,
                                 "node": c.node_id, "status": "INTERNAL",
                                 "reason": str(unwrap_remote(e))})
                self.node._on_shard_failed(
                    c, f"replication op failed: {unwrap_remote(e)}")
        return 1 + len(copies), ok, failures, delivered

    def _shards_header(self, total: int, ok: int,
                       failures: list[dict]) -> dict:
        out = {"total": total, "successful": ok, "failed": len(failures)}
        if failures:
            out["failures"] = failures
        return out

    def _engine(self, name: str, shard: int, wait: float = 2.0):
        """Local engine for a shard, waiting briefly for the reconciler to
        catch up with a state the sender already saw."""
        deadline = time.monotonic() + wait
        while True:
            try:
                return self.node.indices_service.index(name).engine(shard)
            except Exception:                    # noqa: BLE001 — state lag
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)

    def _check_write_block(self) -> None:
        """Reject writes while the no-master block is in force (reference:
        `discovery.zen.no_master_block` defaults to `write` — a node on the
        minority side of a partition must not accept writes it can never
        durably replicate; reads stay allowed). The block is RETRYABLE
        (TransportReplicationAction.ReroutePhase waits on retryable
        cluster blocks): a re-election lasts well under a second, and
        failing writes instantly through it turns every transient master
        blip into caller-visible errors."""
        if NO_MASTER_BLOCK not in self._state().blocks:
            return
        # a few seconds covers any re-election; a real quorum loss still
        # surfaces as the block error, just not instantly
        deadline = time.monotonic() + self.BLOCK_RETRY_TIMEOUT
        while time.monotonic() < deadline:
            if NO_MASTER_BLOCK not in self._state().blocks:
                return
            time.sleep(0.05)
        raise ClusterBlockError(
            "blocked by: [SERVICE_UNAVAILABLE/2/no master];")

    # ---- index -------------------------------------------------------------

    def index_doc(self, index: str, doc_id: str | None, source: dict,
                  routing: str | None = None, version: int | None = None,
                  op_type: str = "index", refresh: bool = False,
                  version_type: str = "internal",
                  meta: dict | None = None) -> dict:
        self._check_write_block()
        name = self._resolve_write_index(index)
        doc_id = doc_id or uuid.uuid4().hex[:20]
        # a child doc routes by its parent id so the family shares a shard
        # (ref: TransportIndexAction resolveRequest — routing defaults to
        # parent)
        if routing is None and meta and meta.get("_parent") is not None:
            routing = str(meta["_parent"])
        if routing is not None:
            meta = {**(meta or {}), "_routing": routing}
        shard = self._shard_id(name, doc_id, routing)
        request = {"index": name, "shard": shard, "id": doc_id,
                   "source": source, "routing": routing,
                   "version": version, "op_type": op_type,
                   "version_type": version_type,
                   "refresh": refresh, "meta": meta}
        return self._on_primary(name, shard, request, self.INDEX_P,
                                self._handle_index_p_local)

    def _assert_primary_here(self, name: str, shard: int) -> None:
        """IndexShard's RELOCATED guard: a primary-phase op forwarded on
        STALE routing must not execute on a node that no longer owns the
        primary — its replication fan-out (computed from the new state)
        would reach nobody, acking a write that dies with the retired
        engine. Raising the retryable ShardNotLocalError sends the
        coordinator back through _on_primary's routing re-resolution."""
        from elasticsearch_tpu.indices.service import ShardNotLocalError
        pr = self._state().routing_table.primary(name, shard)
        if pr is None or pr.node_id != self.node.node_id:
            raise ShardNotLocalError(name, shard)

    def _recheck_primary_after_op(self, name: str, shard: int,
                                  delivered: set) -> None:
        """Post-op half of the lost-write guard: after apply+fan-out, if
        ownership moved, the ack stands ONLY when the op provably reached
        the node now holding the primary (the relocation target was in
        the pre-handoff fan-out); otherwise raise retryable so the op
        re-executes where the data actually lives. Re-execution cannot
        double-apply: it happens only when the new primary never received
        the op."""
        from elasticsearch_tpu.indices.service import ShardNotLocalError
        pr = self._state().routing_table.primary(name, shard)
        if pr is not None and (pr.node_id == self.node.node_id
                               or pr.node_id in delivered):
            return
        raise ShardNotLocalError(name, shard)

    def _handle_index_p(self, request: dict, source) -> dict:
        self._assert_primary_here(request["index"], request["shard"])
        return self._handle_index_p_local(request)

    def _handle_index_p_local(self, request: dict) -> dict:
        name, shard = request["index"], request["shard"]
        engine = self._engine(name, shard)
        t = (request.get("meta") or {}).get("_type")
        if t:
            svc = self.node.indices_service.indices.get(name)
            if svc is not None:
                svc.indexing_types[t] = svc.indexing_types.get(t, 0) + 1
        version = request.get("version")
        v, created = engine.index(
            request["id"], request["source"],
            version=MATCH_ANY if version is None else version,
            routing=request.get("routing"),
            op_type=request.get("op_type", "index"),
            version_type=request.get("version_type", "internal"),
            meta=request.get("meta"))
        if request.get("refresh"):
            engine.refresh()
        total, ok, failures, delivered = self._replicate(
            name, shard, self.INDEX_R,
            {"index": name, "shard": shard, "id": request["id"],
             "source": request["source"], "routing": request.get("routing"),
             "version": v, "refresh": bool(request.get("refresh")),
             "meta": request.get("meta")})
        # post-op ownership recheck (the relocation-handoff lost-write
        # guard): state application is monotonic per node, so if the
        # fan-out above computed its copies from a POST-handoff state
        # (reaching nobody) this check also sees that state and turns
        # the ack into a retry against the new primary; if the fan-out
        # saw the PRE-handoff state it DELIVERED to the relocation
        # target, the ack stands, and no spurious retry can double-apply
        # the op. Reference: IndexShard RELOCATED verification before
        # the response turnaround.
        self._recheck_primary_after_op(name, shard, delivered)
        return {"_index": name, "_type": "_doc", "_id": request["id"],
                "_version": v,
                "result": "created" if created else "updated",
                "created": created,
                "_shards": self._shards_header(total, ok, failures)}

    def _handle_index_r(self, request: dict, source) -> dict:
        engine = self._engine(request["index"], request["shard"])
        engine.index_replica(request["id"], request["source"],
                             request["version"],
                             routing=request.get("routing"),
                             meta=request.get("meta"))
        if request.get("refresh"):
            engine.refresh()
        return {}

    # ---- delete ------------------------------------------------------------

    def delete_doc(self, index: str, doc_id: str,
                   routing: str | None = None, version: int | None = None,
                   refresh: bool = False,
                   version_type: str = "internal") -> dict:
        self._check_write_block()
        name = self._resolve_single(index)
        shard = self._shard_id(name, doc_id, routing)
        request = {"index": name, "shard": shard, "id": doc_id,
                   "version": version, "version_type": version_type,
                   "refresh": refresh}
        return self._on_primary(name, shard, request, self.DELETE_P,
                                self._handle_delete_p_local)

    def _handle_delete_p(self, request: dict, source) -> dict:
        self._assert_primary_here(request["index"], request["shard"])
        return self._handle_delete_p_local(request)

    def _handle_delete_p_local(self, request: dict) -> dict:
        name, shard = request["index"], request["shard"]
        engine = self._engine(name, shard)
        version = request.get("version")
        v = engine.delete(request["id"],
                          version=MATCH_ANY if version is None else version,
                          version_type=request.get("version_type",
                                                   "internal"))
        if request.get("refresh"):
            engine.refresh()
        total, ok, failures, delivered = self._replicate(
            name, shard, self.DELETE_R,
            {"index": name, "shard": shard, "id": request["id"],
             "version": v, "refresh": bool(request.get("refresh"))})
        # post-op ownership recheck (see _handle_index_p_local)
        self._recheck_primary_after_op(name, shard, delivered)
        return {"_index": name, "_type": "_doc", "_id": request["id"],
                "_version": v, "result": "deleted", "found": True,
                "_shards": self._shards_header(total, ok, failures)}

    def _handle_delete_r(self, request: dict, source) -> dict:
        engine = self._engine(request["index"], request["shard"])
        engine.delete_replica(request["id"], request["version"])
        if request.get("refresh"):
            engine.refresh()
        return {}

    # ---- update (get-modify-reindex ON the primary's node,
    # core/action/update/TransportUpdateAction.java) -------------------------

    def update_doc(self, index: str, doc_id: str, body: dict,
                   routing: str | None = None, refresh: bool = False,
                   version: int | None = None,
                   meta: dict | None = None) -> dict:
        self._check_write_block()
        if version is not None and ("upsert" in body
                                    or body.get("doc_as_upsert")):
            # the reference rejects this combination up front: a versioned
            # update must never CREATE the doc
            raise IllegalArgumentError(
                "Validation Failed: can't provide version in upsert request")
        # upserts auto-create the index like an index op (TransportUpdateAction
        # routes through the same auto-create path)
        name = self._resolve_write_index(index) \
            if ("upsert" in body or body.get("doc_as_upsert")) \
            else self._resolve_single(index)
        if routing is None and meta and meta.get("_parent") is not None:
            routing = str(meta["_parent"])
        if routing is not None:
            meta = {**(meta or {}), "_routing": routing}
        shard = self._shard_id(name, doc_id, routing)
        request = {"index": name, "shard": shard, "id": doc_id, "body": body,
                   "routing": routing, "refresh": refresh,
                   "req_version": version, "meta": meta}
        return self._on_primary(name, shard, request, self.UPDATE_P,
                                self._handle_update_local)

    def _handle_update(self, request: dict, source) -> dict:
        self._assert_primary_here(request["index"], request["shard"])
        return self._handle_update_local(request)

    def _handle_update_local(self, request: dict) -> dict:
        from elasticsearch_tpu.node import _apply_update_script, _deep_merge
        name, shard = request["index"], request["shard"]
        body = request["body"]
        engine = self._engine(name, shard)
        current = engine.get(request["id"])
        if not current.found:
            if "upsert" in body or body.get("doc_as_upsert"):
                # doc_as_upsert: the partial doc IS the upsert document
                # (UpdateHelper.prepare, TransportUpdateAction)
                upsert_src = body["upsert"] if "upsert" in body \
                    else body.get("doc", {})
                out = self._handle_index_p_local(
                    {"index": name, "shard": shard, "id": request["id"],
                     "source": upsert_src,
                     "routing": request.get("routing"), "version": None,
                     "op_type": "index",
                     "refresh": bool(request.get("refresh")),
                     "meta": request.get("meta")})
                out["_update_source"] = upsert_src
                return out
            raise DocumentMissingError(name, request["id"])
        if request.get("req_version") is not None and \
                current.version != request["req_version"]:
            from elasticsearch_tpu.common.errors import VersionConflictError
            raise VersionConflictError(name, request["id"], current.version,
                                       request["req_version"])
        script_meta_updates: dict = {}
        if "doc" in body:
            merged = _deep_merge(dict(current.source), body["doc"])
        elif "script" in body:
            now_ms = int(time.time() * 1000)
            script_meta = {k: v for k, v in (current.meta or {}).items()
                           if k in ("_ttl", "_timestamp", "_routing",
                                    "_parent")}
            if "_ttl" in script_meta:
                # scripts see/set ttl as REMAINING millis (TTLFieldMapper
                # ctx._ttl semantics); storage keeps the absolute expiry
                script_meta["_ttl"] = int(script_meta["_ttl"]) - now_ms
            import copy as _copy
            # DEEP copy: GroovyLite mutates nested lists/maps in place,
            # and engine.get returns the live stored source — a script
            # that touches nested state then aborts (ctx.op = none)
            # must not leave unversioned edits behind
            merged, op, script_meta_updates = _apply_update_script(
                _copy.deepcopy(current.source), body["script"],
                meta={"_id": request["id"], **script_meta})
            if "_ttl" in script_meta_updates:
                script_meta_updates["_ttl"] = \
                    now_ms + int(script_meta_updates["_ttl"])
            if op == "none":
                # noop result (UpdateHelper: ctx.op = "none")
                return {"_index": name, "_type": "_doc",
                        "_id": request["id"],
                        "_version": current.version, "result": "noop",
                        "_shards": {"total": 0, "successful": 0,
                                    "failed": 0},
                        "_update_source": dict(current.source)}
            if op == "delete":
                # keep the optimistic check: a write landing between the
                # get and this delete must conflict, not vanish
                out = self._handle_delete_p_local(
                    {"index": name, "shard": shard, "id": request["id"],
                     "version": current.version,
                     "refresh": bool(request.get("refresh"))})
                out["result"] = "deleted"
                out["_update_source"] = dict(current.source)
                return out
        else:
            merged = dict(current.source)
        # carry existing metadata forward, overridden by the request's
        # (a fresh ttl/timestamp restamps; parent/type persist), then by
        # anything the update script set on ctx (_ttl/_timestamp)
        new_meta = dict(current.meta or {})
        new_meta.update(request.get("meta") or {})
        new_meta.update(script_meta_updates)
        out = self._handle_index_p_local(
            {"index": name, "shard": shard, "id": request["id"],
             "source": merged, "routing": request.get("routing"),
             "version": current.version, "op_type": "index",
             "refresh": bool(request.get("refresh")),
             "meta": new_meta or None})
        out["result"] = "updated"
        # the applied source rides along so callers can answer `fields`
        # without a racy re-get (UpdateHelper.extractGetResult)
        out["_update_source"] = merged
        return out

    # ---- get (TransportSingleShardAction: one copy, failover) --------------

    def _single_shard_read(self, name: str, shard: int, action: str,
                           request: dict, local_handler) -> dict:
        """TransportSingleShardAction: try one copy after another — local
        first (preference=_local default), then primary, then replicas."""
        state = self._state()
        copies = [c for c in state.routing_table.shard_copies(name, shard)
                  if c.active]
        copies.sort(key=lambda c: (c.node_id != self.node.node_id,
                                   not c.primary))
        if not copies:
            raise UnavailableShardsError(
                f"[{name}][{shard}] no active copy", index=name, shard=shard)
        last: Exception | None = None
        for c in copies:
            if c.node_id == self.node.node_id:
                try:
                    return local_handler(request, None)
                except ElasticsearchTpuError:
                    raise
                except Exception as e:           # noqa: BLE001 — failover
                    last = e
                    continue
            target = state.node(c.node_id)
            if target is None:
                continue
            try:
                return self.node.transport_service.send_request(
                    target, action, request, timeout=10.0).result(15.0)
            except RemoteTransportError as e:
                if _is_retryable(e):
                    last = e                     # stale copy → next copy
                    continue
                raise unwrap_remote(e) from None  # real application error
            except TransportException as e:
                last = e                         # node gone → next copy
            except Exception as e:               # noqa: BLE001 — remote error
                raise unwrap_remote(e) from None
        raise UnavailableShardsError(
            f"[{name}][{shard}] read failed on all copies: {last}",
            index=name, shard=shard)

    def get_doc(self, index: str, doc_id: str,
                routing: str | None = None, realtime: bool = True,
                refresh: bool = False) -> dict:
        name = self._resolve_single(index)
        shard = self._shard_id(name, doc_id, routing)
        return self._single_shard_read(
            name, shard, self.GET_S,
            {"index": name, "shard": shard, "id": doc_id,
             "realtime": realtime, "refresh": refresh},
            self._handle_get)

    # ---- explain (core/action/explain/TransportExplainAction.java) ---------

    def explain_doc(self, index: str, doc_id: str, body: dict,
                    routing: str | None = None) -> dict:
        name = self._resolve_single(index)
        shard = self._shard_id(name, doc_id, routing)
        return self._single_shard_read(
            name, shard, self.EXPLAIN_S,
            {"index": name, "shard": shard, "id": doc_id, "body": body},
            self._handle_explain)

    def _doc_location(self, engine, doc_id: str, realtime: bool = True):
        """→ (reader, global doc id) of a committed doc, refreshing if the
        doc still sits in the write buffer; None when absent/deleted.
        With realtime=False only already-refreshed docs resolve (the
        searcher-visible set, like the reference's non-realtime path)."""
        from elasticsearch_tpu.index.device_reader import device_reader_for
        entry = engine._versions.get(doc_id)
        if entry is None or entry.deleted:
            return None
        if entry.seg_id == -1:
            if not realtime:
                return None
            engine.refresh()                     # buffered → make visible
            entry = engine._versions.get(doc_id)
            if entry is None or entry.deleted or entry.seg_id < 0:
                return None
        reader = device_reader_for(engine)
        for s in reader.segments:
            if s.seg.seg_id == entry.seg_id:
                return reader, s.doc_base + entry.local_doc
        return None

    def _handle_explain(self, request: dict, source) -> dict:
        from elasticsearch_tpu.search.explain import (
            explain_query, strip_matched)
        from elasticsearch_tpu.search.phase import ShardSearcher
        from elasticsearch_tpu.search.query_dsl import parse_query
        name = request["index"]
        base = {"_index": name, "_type": "_doc", "_id": request["id"]}
        engine = self._engine(name, request["shard"])
        loc = self._doc_location(engine, request["id"])
        if loc is None:
            return {**base, "matched": False, "explanation": {
                "value": 0.0, "description": "no matching document",
                "details": []}}
        reader, gdoc = loc
        svc = self.node.indices_service.index(name)
        searcher = ShardSearcher(request["shard"], reader,
                                 svc.mapper_service, index_name=name)
        query = parse_query((request.get("body") or {}).get("query"))
        tree = explain_query(searcher, query, gdoc)
        return {**base, "matched": tree["matched"],
                "explanation": strip_matched(tree)}

    # ---- termvectors (core/index/termvectors/ShardTermVectorsService) ------

    def termvectors(self, index: str, doc_id: str,
                    body: dict | None = None,
                    routing: str | None = None) -> dict:
        name = self._resolve_single(index)
        shard = self._shard_id(name, doc_id, routing)
        return self._single_shard_read(
            name, shard, self.TERMVECTORS_S,
            {"index": name, "shard": shard, "id": doc_id,
             "body": body or {}},
            self._handle_termvectors)

    def _handle_termvectors(self, request: dict, source) -> dict:
        import numpy as np
        name = request["index"]
        base = {"_index": name, "_type": "_doc", "_id": request["id"]}
        engine = self._engine(name, request["shard"])
        body = request.get("body") or {}
        loc = self._doc_location(engine, request["id"],
                                 realtime=body.get("realtime", True)
                                 not in (False, "false"))
        if loc is None:
            return {**base, "found": False}
        reader, gdoc = loc
        seg, local = reader.resolve(gdoc)
        want = body.get("fields")
        term_stats = bool(body.get("term_statistics"))
        src = seg.seg.sources[local] if local < len(seg.seg.sources) \
            else {}
        out_fields: dict = {}
        for fname, col in seg.seg.text_fields.items():
            if want and fname not in want:
                continue
            uterms = np.asarray(col.uterms[local])
            utf = np.asarray(col.utf[local])
            terms = {}
            for tid, tf in zip(uterms, utf):
                if tid < 0:
                    continue
                term = col.terms[int(tid)]
                # shard-wide doc freq, not just this doc's segment —
                # otherwise the same request returns different numbers
                # across refreshes/merges
                terms[term] = {"term_freq": int(tf),
                               "doc_freq": int(reader.df(fname, term))}
                if term_stats:
                    ttf = 0
                    for s2 in reader.segments:
                        c2 = s2.seg.text_fields.get(fname)
                        if c2 is None:
                            continue
                        t2 = c2.tid(term)
                        if t2 >= 0:
                            ttf += int(np.asarray(
                                c2.utf * (c2.uterms == t2)).sum())
                    terms[term]["ttf"] = ttf
            if not terms:
                continue
            # per-occurrence tokens (position + char offsets) come from
            # re-analyzing the stored _source with the field's analyzer —
            # the reference does the same when term vectors aren't stored
            # (TermVectorsService.generateTermVectors)
            want_positions = body.get("positions", True) \
                not in (False, "false")
            want_offsets = body.get("offsets", True) \
                not in (False, "false")
            raw = src.get(fname) if isinstance(src, dict) else None
            if raw is not None and (want_positions or want_offsets):
                svc2 = self.node.indices_service.indices.get(name)
                fm = svc2.mapper_service.field_mapper(fname) \
                    if svc2 else None
                analyzer = getattr(fm, "analyzer", None)
                if analyzer is not None:
                    values = raw if isinstance(raw, list) else [raw]
                    for v in values:
                        for tok in analyzer.analyze(str(v)):
                            t = terms.get(tok.term)
                            if t is None:
                                continue
                            entry = {}
                            if want_positions:
                                entry["position"] = tok.position
                            if want_offsets:
                                entry["start_offset"] = tok.start_offset
                                entry["end_offset"] = tok.end_offset
                            t.setdefault("tokens", []).append(entry)
            sum_df = doc_count = sum_ttf = 0
            for s2 in reader.segments:
                c2 = s2.seg.text_fields.get(fname)
                if c2 is None:
                    continue
                sum_df += int(np.asarray(c2.df).sum())
                doc_count += int(s2.seg.num_docs)
                sum_ttf += int(c2.total_tokens)
            out_fields[fname] = {
                "field_statistics": {
                    "sum_doc_freq": sum_df,
                    "doc_count": doc_count,
                    "sum_ttf": sum_ttf},
                "terms": dict(sorted(terms.items()))}
        return {**base, "found": True, "took": 0,
                "term_vectors": out_fields}

    def _handle_get(self, request: dict, source) -> dict:
        name = request["index"]
        engine = self._engine(name, request["shard"])
        if request.get("refresh"):
            engine.refresh()
        r = engine.get(request["id"],
                       realtime=request.get("realtime", True))
        out = {"_index": name, "_type": "_doc", "_id": request["id"],
               "found": r.found}
        if r.found:
            out["_version"] = r.version
            out["_source"] = r.source
            for key, value in (r.meta or {}).items():
                if key == "_type":
                    out["_type"] = value
                elif key == "_ttl":
                    # _ttl reads back as REMAINING millis (TTLFieldMapper)
                    out["_ttl"] = int(value) - int(time.time() * 1000)
                else:
                    out[key] = value
        return out

    def mget(self, body: dict, default_index: str | None = None,
             realtime: bool = True, refresh: bool = False) -> dict:
        docs = []
        for spec in body.get("docs", []):
            idx = spec.get("_index", default_index)
            did = str(spec["_id"])
            routing = spec.get("routing",
                               spec.get("_routing",
                                        spec.get("parent",
                                                 spec.get("_parent"))))
            try:
                docs.append(self.get_doc(
                    idx, did,
                    routing=None if routing is None else str(routing),
                    realtime=realtime, refresh=refresh))
            except ElasticsearchTpuError as e:
                docs.append({"_index": idx, "_id": did, "found": False,
                             "error": e.to_xcontent()})
        if "ids" in body and default_index:
            for did in body["ids"]:
                try:
                    docs.append(self.get_doc(default_index, str(did),
                                             realtime=realtime,
                                             refresh=refresh))
                except ElasticsearchTpuError as e:
                    docs.append({"_index": default_index, "_id": str(did),
                                 "found": False,
                                 "error": e.to_xcontent()})
        return {"docs": docs}

    # ---- bulk (TransportBulkAction → one BULK_P per target shard) ----------

    def bulk(self, operations: list[tuple[str, dict, dict | None]],
             refresh: bool = False) -> dict:
        self._check_write_block()
        t0 = time.perf_counter()
        # auto-create every target index up front (TransportBulkAction does
        # a create round-trip per missing index before splitting)
        resolved: dict[str, str] = {}
        items: list[dict | None] = [None] * len(operations)
        errors = False
        by_shard: dict[tuple[str, int], list[tuple[int, tuple]]] = {}
        for pos, (action, meta, source) in enumerate(operations):
            index = meta.get("_index")
            err = meta.get("_meta_error")
            if err is not None:
                errors = True
                items[pos] = {action: {"_index": index,
                                       "_id": meta.get("_id"),
                                       "error": err["error"],
                                       "status": err["status"]}}
                continue
            try:
                if index not in resolved:
                    resolved[index] = self._resolve_write_index(index)
                name = resolved[index]
                doc_id = meta.get("_id") or uuid.uuid4().hex[:20]
                routing = meta.get("routing", meta.get("_routing"))
                doc_meta = meta.get("_meta_fields")
                if routing is None and doc_meta and \
                        doc_meta.get("_parent") is not None:
                    routing = str(doc_meta["_parent"])
                if routing is not None:
                    doc_meta = {**(doc_meta or {}),
                                "_routing": str(routing)}
                shard = self._shard_id(name, doc_id, routing)
                by_shard.setdefault((name, shard), []).append(
                    (pos, (action, doc_id, routing, source, doc_meta)))
            except Exception as e:               # noqa: BLE001 — per item
                errors = True
                items[pos] = self._bulk_error_item(action, index,
                                                   meta.get("_id"), e)
        for (name, shard), group in by_shard.items():
            request = {"index": name, "shard": shard, "refresh": refresh,
                       "items": [
                           {"action": a, "id": d, "routing": r, "source": s,
                            "meta": m}
                           for _, (a, d, r, s, m) in group]}
            try:
                resp = self._on_primary(name, shard, request, self.BULK_P,
                                        self._handle_bulk_p_local)
                for (pos, (action, *_)), item in zip(group, resp["items"]):
                    items[pos] = item
                    act = next(iter(item))
                    if "error" in item[act]:
                        errors = True
            except Exception as e:               # noqa: BLE001 — whole shard
                errors = True
                for pos, (action, doc_id, _r, _s, _m) in group:
                    items[pos] = self._bulk_error_item(action, name, doc_id, e)
        took_ms = (time.perf_counter() - t0) * 1e3
        from elasticsearch_tpu.observability import histograms
        histograms.observe_lane("bulk", took_ms)
        return {"took": int(took_ms), "errors": errors, "items": items}

    def _bulk_error_item(self, action: str, index, doc_id, e) -> dict:
        e = unwrap_remote(e)
        err = e.to_xcontent() if isinstance(e, ElasticsearchTpuError) \
            else {"type": "exception", "reason": str(e)}
        status = e.status if isinstance(e, ElasticsearchTpuError) else 500
        return {action: {"_index": index, "_id": doc_id, "error": err,
                         "status": status}}

    def _handle_bulk_p(self, request: dict, source) -> dict:
        self._assert_primary_here(request["index"], request["shard"])
        return self._handle_bulk_p_local(request)

    def _handle_bulk_p_local(self, request: dict) -> dict:
        """Primary bulk loop (TransportShardBulkAction.java:116): apply each
        item, collect per-item results, then replicate the resolved ops in
        one replica request."""
        name, shard = request["index"], request["shard"]
        engine = self._engine(name, shard)
        items_out: list[dict] = []
        replica_ops: list[dict] = []
        for item in request["items"]:
            action = item["action"]
            try:
                if action in ("index", "create"):
                    ti = (item.get("meta") or {}).get("_type")
                    if ti:
                        svc2 = self.node.indices_service.indices.get(name)
                        if svc2 is not None:
                            svc2.indexing_types[ti] = \
                                svc2.indexing_types.get(ti, 0) + 1
                    v, created = engine.index(
                        item["id"], item["source"],
                        routing=item.get("routing"),
                        op_type="create" if action == "create" else "index",
                        meta=item.get("meta"), sync=False)
                    replica_ops.append({"op": "index", "id": item["id"],
                                        "source": item["source"],
                                        "routing": item.get("routing"),
                                        "version": v,
                                        "meta": item.get("meta")})
                    r = {"_index": name, "_type": "_doc", "_id": item["id"],
                         "_version": v,
                         "result": "created" if created else "updated",
                         "created": created,
                         "status": 201 if created else 200}
                elif action == "delete":
                    v = engine.delete(item["id"], sync=False)
                    replica_ops.append({"op": "delete", "id": item["id"],
                                        "version": v})
                    r = {"_index": name, "_type": "_doc", "_id": item["id"],
                         "_version": v, "result": "deleted", "found": True,
                         "status": 200}
                elif action == "update":
                    ubody = item.get("source") or {}
                    r = {**self._handle_update_local(
                        {"index": name, "shard": shard, "id": item["id"],
                         "body": ubody,
                         "routing": item.get("routing"),
                         "refresh": bool(request.get("refresh")),
                         "meta": item.get("meta")}),
                        "status": 200}
                    # update replicates itself via _handle_index_p_local
                    src = r.pop("_update_source", None)
                    wanted = ubody.get("fields")
                    if wanted:
                        r["get"] = update_get_section(
                            src, r.get("_version"), wanted)
                else:
                    raise ValueError(f"unknown bulk action [{action}]")
                items_out.append({action: r})
            except Exception as e:               # noqa: BLE001 — per item
                items_out.append(self._bulk_error_item(action, name,
                                                       item["id"], e))
        # per-REQUEST durability: ONE translog fsync per shard bulk, after
        # the item loop and before acking (IndexShard.sync in
        # TransportShardBulkAction) — not one per op; an IO error here
        # self-fails the engine (retryable upstream) instead of acking
        engine.translog_sync()
        if request.get("refresh"):
            engine.refresh()
        delivered: set = set()
        if replica_ops:
            _, _, _, delivered = self._replicate(
                name, shard, self.BULK_R,
                {"index": name, "shard": shard, "ops": replica_ops,
                 "refresh": bool(request.get("refresh"))})
        # post-op ownership recheck (see _handle_index_p_local)
        self._recheck_primary_after_op(name, shard, delivered)
        return {"items": items_out}

    def _handle_bulk_r(self, request: dict, source) -> dict:
        engine = self._engine(request["index"], request["shard"])
        for op in request["ops"]:
            if op["op"] == "index":
                engine.index_replica(op["id"], op["source"], op["version"],
                                     routing=op.get("routing"),
                                     meta=op.get("meta"), sync=False)
            else:
                engine.delete_replica(op["id"], op["version"], sync=False)
        engine.translog_sync()          # per-request durability (see
        if request.get("refresh"):      # the primary loop above)
            engine.refresh()
        return {}


class BroadcastActions:
    """Shard-broadcast admin verbs: refresh / flush / force-merge hit one
    node per index copy-holder (TransportBroadcastAction.java:48 — here
    per-node grouping since the op applies to all local shards at once)."""

    ACTION = "indices:admin/broadcast[n]"

    def __init__(self, node):
        self.node = node
        node.transport_service.register_request_handler(
            self.ACTION, self._handle, executor="management", sync=True)

    def _fan_out(self, index_expr: str, op: str, **kw) -> dict:
        names = self.node.indices_service.resolve(index_expr)
        state = self.node.cluster_service.state()
        shards_per_node: dict[str, int] = {}
        for name in names:
            for s in state.routing_table.index_shards(name):
                if s.assigned:
                    shards_per_node[s.node_id] = \
                        shards_per_node.get(s.node_id, 0) + 1
        total_shards = sum(shards_per_node.values())
        futures = []
        ok = failed = 0
        for nid, nshards in shards_per_node.items():
            request = {"indices": names, "op": op, **kw}
            if nid == self.node.node_id:
                try:
                    resp = self._handle(request, None) or {}
                    f = int(resp.get("failed", 0))
                    ok += nshards - min(f, nshards)
                    failed += min(f, nshards)
                except Exception:                # noqa: BLE001 — count it
                    failed += nshards
                continue
            target = state.node(nid)
            if target is None:
                failed += nshards
                continue
            futures.append((nshards, self.node.transport_service.send_request(
                target, self.ACTION, request, timeout=30.0)))
        for nshards, fut in futures:
            try:
                resp = fut.result(35.0) or {}
                f = int(resp.get("failed", 0))
                ok += nshards - min(f, nshards)
                failed += min(f, nshards)
            except Exception:                    # noqa: BLE001 — count it
                failed += nshards
        return {"_shards": {"total": total_shards, "successful": ok,
                            "failed": failed}}

    def _handle(self, request: dict, source) -> dict:
        isvc = self.node.indices_service
        pinned = 0
        for name in request["indices"]:
            svc = isvc.indices.get(name)
            if svc is None:
                continue
            if request["op"] == "refresh":
                svc.refresh()
            elif request["op"] == "flush":
                svc.flush()
            elif request["op"] == "force_merge":
                svc.force_merge(request.get("max_num_segments", 1))
            elif request["op"] == "synced_flush":
                # ALL copies stamp the COORDINATOR's sync_id — a shared id
                # is the whole point (SyncedFlushService.java:60); a
                # pinned commit (snapshot/recovery in flight) cannot be
                # stamped and counts as failed, not silently successful
                for e in svc.shard_engines:
                    if e.synced_flush(sync_id=request["sync_id"]) is None:
                        pinned += 1
        return {"failed": pinned}

    def refresh(self, index_expr: str) -> dict:
        return self._fan_out(index_expr, "refresh")

    def flush(self, index_expr: str) -> dict:
        return self._fan_out(index_expr, "flush")

    def synced_flush(self, index_expr: str) -> dict:
        import uuid as _uuid
        return self._fan_out(index_expr, "synced_flush",
                             sync_id=_uuid.uuid4().hex)

    def force_merge(self, index_expr: str,
                    max_num_segments: int = 1) -> dict:
        return self._fan_out(index_expr, "force_merge",
                             max_num_segments=max_num_segments)
