"""Distributed search: scatter query+fetch per shard, reduce at the
coordinator.

Reference: core/action/search/type/TransportSearchTypeAction.java:87-247 —
`start` (:137) fans one request per shard group to the next copy
(`performFirstPhase` :156), failed shards retry the next copy (:205-247),
and `SearchPhaseController` merges (sortDocs :165, merge :300). Each shard
executes query AND fetch of its own top `from+size` hits in one round
(QUERY_AND_FETCH semantics, SearchType.java:29 — correct for any
single-round request and chosen here because fetch-phase hits are small
columnar reads on the TPU host, so the second fan-out round of
QUERY_THEN_FETCH buys nothing); the coordinator reduce then keeps the
global [from, from+size) slice, which is identical to what
query_then_fetch returns.

Scroll pairs a coordinator-side cursor (search_after continuation
re-running the scatter) with data-node reader PINS: the first page pins
each shard's point-in-time SearcherView under the scroll's ctx_uid
(ScrollContext semantics, SearchService.java:533-558), so later pages
never see writes that landed mid-scroll; pins expire with the keep-alive
and die on clear_scroll.
"""

from __future__ import annotations

import base64
import contextlib
import itertools
import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from elasticsearch_tpu.common.errors import (
    ElasticsearchTpuError, QueryParsingError, SearchContextMissingError,
    TaskCancelledError)
from elasticsearch_tpu.action.replica_stats import ReplicaStatsTable
from elasticsearch_tpu.common.settings import parse_time_value
from elasticsearch_tpu.index.device_reader import device_reader_for
from elasticsearch_tpu.observability import attribution
from elasticsearch_tpu.observability import histograms as obs_hist
from elasticsearch_tpu.observability import tracing as obs_trace
from elasticsearch_tpu.search.controller import merge_shard_payloads
from elasticsearch_tpu.search.phase import ShardSearcher, parse_search_request
from elasticsearch_tpu.tasks import manager as tasks


def wire_safe(obj):
    """Make agg partials transport-serializable (sets → lists, numpy →
    python) without changing what reduce_aggs consumes."""
    if isinstance(obj, dict):
        return {k: wire_safe(v) for k, v in obj.items()}
    if isinstance(obj, (set, frozenset)):
        return sorted(str(x) for x in obj)
    if isinstance(obj, (list, tuple)):
        return [wire_safe(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, np.generic):
        return obj.item()
    return obj


class _ScrollContext:
    def __init__(self, index_expr: str, body: dict, keep_alive_s: float,
                 search_type: str | None = None,
                 ctx_uid: str | None = None):
        import uuid as _uuid
        # stable id carried in every page's shard requests: data nodes pin
        # their point-in-time reader views under it (SearchService
        # activeContexts analog — scroll pages must NOT see later writes)
        self.ctx_uid = ctx_uid or _uuid.uuid4().hex
        self.index_expr = index_expr
        self.body = dict(body)
        self.search_type = search_type
        # a routed scroll stays routed on EVERY page, not just page one
        self.routing: str | None = None
        self.preference: str | None = None
        self.dfs_cache: dict = {}
        self.keep_alive_s = keep_alive_s
        self.expires_at = time.monotonic() + keep_alive_s
        self.last_sort_key: list | None = None
        self.finished = False

    def touch(self, keep_alive_s: float | None = None):
        if keep_alive_s is not None:
            self.keep_alive_s = keep_alive_s
        self.expires_at = time.monotonic() + self.keep_alive_s


def rewrite_mlt_likes(node, body: dict, default_index: str = "_all") -> dict:
    """Coordinator-side request rewrites that need cluster access before
    the per-shard fan-out:

    * more_like_this liked DOCUMENTS are fetched here (routing-aware GET,
      any shard/node) and turned into like-texts + `_exclude_ids`, so
      every shard scores them — a shard-local source scan would silently
      match nothing on shards not hosting the liked doc (the reference
      fetches liked docs before query construction too).
    * stored-script references ({"script": {"id": ...}} in script_score /
      function_score, {"id": ...} template queries) resolve against the
      cluster-state script registry (core/script/ScriptService indexed
      scripts) into inline sources shards can execute.

    Returns a rewritten copy (the input body is not mutated); bodies
    without such references pass through unchanged."""
    def walk(obj):
        if isinstance(obj, list):
            return [walk(v) for v in obj]
        if not isinstance(obj, dict):
            return obj
        out = {}
        for key, val in obj.items():
            if key in ("more_like_this", "mlt") and isinstance(val, dict) \
                    and _mlt_has_docs(val):
                out[key] = _fetch_mlt_likes(node, val, default_index)
            elif key == "script" and isinstance(val, dict) \
                    and "id" in val and "source" not in val \
                    and "inline" not in val:
                src = _stored_script_any(node, str(val["id"]),
                                         val.get("lang"))
                if src is None:
                    out[key] = walk(val)
                else:
                    out[key] = {**{k: walk(v) for k, v in val.items()
                                   if k != "id"}, "inline": src}
            elif key == "template" and isinstance(val, dict) \
                    and "id" in val and not any(
                        k in val for k in ("query", "inline", "source")):
                src = _stored_script_any(node, str(val["id"]), "mustache")
                if src is None:
                    out[key] = walk(val)
                else:
                    out[key] = {**{k: walk(v) for k, v in val.items()
                                   if k != "id"}, "inline": src}
            else:
                out[key] = walk(val)
        return out
    return walk(body)


def _stored_script_any(node, sid: str, lang: str | None):
    """Stored-script lookup; without a lang, any registered lang matches
    (the 2.x indexed-script API keys by (lang, id))."""
    if lang:
        return node.stored_script(sid, lang)
    scripts = node.cluster_service.state().customs.get("stored_scripts", {})
    for key, src in scripts.items():
        if key.split("\x00", 1)[1] == sid:
            return src
    return None


def _mlt_has_docs(spec: dict) -> bool:
    raw = spec.get("like", spec.get("like_text"))
    likes = raw if isinstance(raw, list) else [raw] if raw is not None else []
    return any(isinstance(x, dict) for x in likes) or \
        bool(spec.get("ids") or spec.get("docs"))


def _fetch_mlt_likes(node, spec: dict, default_index: str) -> dict:
    spec = dict(spec)
    raw_like = spec.pop("like", None)
    raw_like_text = spec.pop("like_text", None)
    raw = raw_like if raw_like is not None else raw_like_text
    # copy: appending ids/docs below must not mutate the caller's list (a
    # scroll context re-rewrites its stored body every page)
    likes = list(raw) if isinstance(raw, list) \
        else [raw] if raw is not None else []
    raw_ids = spec.pop("ids", None) or []
    raw_docs = spec.pop("docs", None) or []
    for did in list(raw_ids) + list(raw_docs):
        likes.append(did if isinstance(did, dict) else {"_id": did})
    raw_unlike = spec.get("unlike")
    unlikes = list(raw_unlike) if isinstance(raw_unlike, list) \
        else [raw_unlike] if raw_unlike is not None else []
    texts: list = []
    exclude = list(spec.get("_exclude_ids", []))
    fields = spec.get("fields") or []
    unlike_out: list = []
    for item in unlikes:
        if not isinstance(item, dict):
            unlike_out.append(str(item))
            continue
        if "doc" in item:
            unlike_out.extend(str(v) for v in item["doc"].values()
                              if isinstance(v, str))
            continue
        did = item.get("_id")
        if did is None:
            continue
        try:
            got = node.document_actions.get_doc(
                item.get("_index", default_index), str(did),
                routing=item.get("_routing", item.get("routing")))
        except Exception:                  # noqa: BLE001 — missing doc
            continue
        if got.get("found"):
            unlike_out.extend(v for v in (got.get("_source") or
                                          {}).values()
                              if isinstance(v, str))
    if unlike_out:
        spec["unlike"] = unlike_out
    for item in likes:
        if not isinstance(item, dict):
            texts.append(item)
            continue
        if "doc" in item:
            texts.extend(str(v) for v in item["doc"].values()
                         if isinstance(v, str))
            continue
        did = item.get("_id")
        if did is None:
            continue
        index = item.get("_index", default_index)
        routing = item.get("_routing", item.get("routing"))
        try:
            got = node.document_actions.get_doc(index, str(did),
                                                routing=routing)
        except Exception:                  # noqa: BLE001 — missing doc/index
            continue
        if not got.get("found"):
            continue
        src = got.get("_source") or {}
        for f in (fields or [k for k, v in src.items()
                             if isinstance(v, str)]):
            v = src.get(f)
            if isinstance(v, str):
                texts.append(v)
        exclude.append(str(did))
    spec["like"] = texts
    if exclude:
        spec["_exclude_ids"] = exclude
    return spec


class ShardRequestCache:
    """Shard request cache (ref:
    core/indices/cache/request/IndicesRequestCache.java:78): caches whole
    per-shard query+fetch payloads for hits-free requests (size 0 — the
    count/agg shapes the reference caches), keyed by (index, shard, reader
    generation, canonical request bytes). A refresh bumps the generation,
    so stale entries simply stop being hit and age out of the LRU."""

    def __init__(self, cap: int = 256):
        from collections import OrderedDict
        self.cap = cap
        self._lru: "OrderedDict[tuple, dict]" = OrderedDict()
        self._lock = threading.Lock()
        self.stats = {"hits": 0, "misses": 0, "evictions": 0}
        # per-engine-incarnation counters (key[0] is the engine uuid):
        # the per-index request_cache section of _stats reads these, so
        # hits/misses/evictions attribute to the index that earned them
        # instead of the node-wide rollup reporting for everyone
        self._by_uuid: dict[str, dict] = {}
        self._sizes: dict[tuple, int] = {}

    def _uuid_stats(self, uuid: str) -> dict:
        return self._by_uuid.setdefault(
            uuid, {"hits": 0, "misses": 0, "evictions": 0})

    def key(self, engine_uuid: str, generation: int, body: dict,
            dfs: dict | None):
        # engine_uuid (an incarnation id) rather than (index, shard):
        # delete+recreate of the same index restarts generations, and a
        # name-keyed entry could otherwise serve the OLD index's results
        return (engine_uuid, generation,
                json.dumps(body, sort_keys=True),
                json.dumps(dfs, sort_keys=True) if dfs else None)

    def get(self, key) -> dict | None:
        with self._lock:
            out = self._lru.get(key)
            bucket = self._uuid_stats(key[0])
            if out is not None:
                self._lru.move_to_end(key)
                self.stats["hits"] += 1
                bucket["hits"] += 1
            else:
                self.stats["misses"] += 1
                bucket["misses"] += 1
            return out

    @staticmethod
    def _approx_bytes(key, payload: dict) -> int:
        """Best-effort resident size of one entry (the payloads are the
        wire-safe size-0 shard responses, so json measures them)."""
        try:
            return len(key[2]) + len(json.dumps(payload, default=str))
        except (TypeError, ValueError):
            return 1024

    def put(self, key, payload: dict) -> None:
        with self._lock:
            self._lru[key] = payload
            self._lru.move_to_end(key)
            self._sizes[key] = self._approx_bytes(key, payload)
            while len(self._lru) > self.cap:
                old_key, _ = self._lru.popitem(last=False)
                self._sizes.pop(old_key, None)
                self.stats["evictions"] += 1
                self._uuid_stats(old_key[0])["evictions"] += 1

    def clear(self, engine_uuids: set | None = None) -> None:
        """Drop everything, or only entries belonging to the given engine
        incarnations (index-scoped /_cache/clear). Cumulative counters
        survive — reference cache stats never reset on a clear."""
        with self._lock:
            if engine_uuids is None:
                self._lru.clear()
                self._sizes.clear()
            else:
                for key in [k for k in self._lru
                            if k[0] in engine_uuids]:
                    del self._lru[key]
                    self._sizes.pop(key, None)

    def stats_dict(self) -> dict:
        with self._lock:
            return {**self.stats, "entries": len(self._lru),
                    "memory_size_in_bytes": sum(self._sizes.values())}

    def stats_for(self, engine_uuids) -> dict:
        """Per-index request_cache section (reference shape): cumulative
        hit/miss/eviction counts plus the resident bytes of the given
        engine incarnations' live entries."""
        uuids = set(engine_uuids)
        with self._lock:
            out = {"hit_count": 0, "miss_count": 0, "evictions": 0,
                   "memory_size_in_bytes": 0}
            for uuid in uuids:
                b = self._by_uuid.get(uuid)
                if b is not None:
                    out["hit_count"] += b["hits"]
                    out["miss_count"] += b["misses"]
                    out["evictions"] += b["evictions"]
            out["memory_size_in_bytes"] = sum(
                n for k, n in self._sizes.items() if k[0] in uuids)
            return out


# One-shot fielddata reservation for a collective-plane mesh pack:
# released exactly once — by supersession (refresh rebuild), cache
# eviction, index close, or any backing engine's close listener —
# whichever comes first. (The per-segment device BLOCKS beneath the pack
# carry their own OneShotCharges inside mesh_engine's block cache.)
from elasticsearch_tpu.common.breaker import OneShotCharge as _PackCharge


class SearchActions:
    QUERY_FETCH = "indices:data/read/search[phase/query+fetch]"
    QUERY_ID = "indices:data/read/search[phase/query]"
    FETCH_ID = "indices:data/read/search[phase/fetch/id]"
    FREE_CONTEXT = "indices:data/read/search[free_context]"
    MSEARCH_SHARD = "indices:data/read/msearch[shard]"
    DFS = "indices:data/read/search[phase/dfs]"
    FIELD_STATS = "indices:data/read/field_stats[s]"

    # fetch amplification break-even: below this window the extra fetch
    # round trip of query_then_fetch costs more than the surplus _source
    # bytes query_and_fetch ships (see `search` docstring)
    QTF_WINDOW_THRESHOLD = 100

    #: coordinator-side wrapper task one hedged copy attempt runs under:
    #: cancelling THIS task (ban machinery) cancels exactly that
    #: attempt's shard work, nothing else in the fan-out
    HEDGE_ACTION = "indices:data/read/search[hedge]"

    #: extra seconds the deadline-bounded collector waits past the
    #: request deadline before abandoning a shard group: shards received
    #: the REMAINING budget at dispatch, so in-budget partials need only
    #: transit time to land — anything slower is the tail the partial
    #: response exists to cut off
    PARTIAL_GRACE_S = 0.1

    #: stall ceiling on coordinator shard-future waits with NO request
    #: deadline: a wedged shard (hung device dispatch) becomes a typed
    #: shard failure after this long, never a hung request — the
    #: deadline-less analog of the PARTIAL_GRACE_S bounded collect
    SHARD_WAIT_CEILING_S = 60.0

    def __init__(self, node):
        self.node = node
        self._pool = ThreadPoolExecutor(max_workers=16,
                                        thread_name_prefix="search")
        # test seam: hold shard execution at a cancellation checkpoint
        # for this many seconds (chaos tests keep a shard task RUNNING
        # while they cancel it / kill its coordinator)
        self.shard_query_delay: float | None = None
        self._rotation = itertools.count()
        # multi-index collective-plane packs: names-tuple → (gens,
        # MeshEngineSearcher, breaker bytes, index identity); single-index
        # packs cache on the index object itself (and die with it)
        from collections import OrderedDict
        self._mesh_multi: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._mesh_multi_lock = threading.Lock()
        # double-buffered plane refresh: engine reader swaps schedule the
        # next-generation data-layer pack here (coalesced per index), so
        # the incremental compose runs OFF the query hot path and the
        # first search after a refresh finds the pack already swapped in
        # (or waits only for the in-flight build, never starts it cold)
        self._plane_warm_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="plane-warm")
        self._plane_warm_pending: set[str] = set()
        self._plane_warm_lock = threading.Lock()
        self._contexts: dict[str, _ScrollContext] = {}
        self._ctx_ids = itertools.count(1)
        # data-node side scroll pins: (ctx_uid, index, shard) →
        # (SearcherView, DeviceReader, expires_at_monotonic)
        self._pinned: dict[tuple, tuple] = {}
        self._lock = threading.Lock()
        node.transport_service.register_request_handler(
            self.QUERY_FETCH, self._handle_shard_query, executor="search",
            sync=True)
        node.transport_service.register_request_handler(
            self.MSEARCH_SHARD, self._handle_shard_msearch,
            executor="search", sync=True)
        node.transport_service.register_request_handler(
            self.DFS, self._handle_shard_dfs, executor="search", sync=True)
        node.transport_service.register_request_handler(
            self.QUERY_ID, self._handle_shard_query_only,
            executor="search", sync=True)
        node.transport_service.register_request_handler(
            self.FETCH_ID, self._handle_shard_fetch,
            executor="search", sync=True)
        node.transport_service.register_request_handler(
            self.FREE_CONTEXT, self._handle_free_context,
            executor="same", sync=True)
        self.request_cache = ShardRequestCache(
            cap=int(node.settings.get("indices.requests.cache.entries", 256))
            if hasattr(node, "settings") else 256)
        # plane-breaker knobs (per-node — one process, one device): an
        # explicit setting reconfigures the jit_exec module breaker
        if hasattr(node, "settings"):
            from elasticsearch_tpu.search import jit_exec
            jit_exec.plane_breaker.configure(
                threshold=node.settings.get(
                    "search.plane_breaker.threshold"),
                backoff_s=node.settings.get(
                    "search.plane_breaker.backoff_seconds"),
                max_backoff_s=node.settings.get(
                    "search.plane_breaker.max_backoff_seconds"))
        # ---- tail-tolerance layer (ARS + hedging + partial results) ----
        # adaptive replica selection: per-node EWMAs + C3 ranks feeding
        # _copy_try_order; hedged requests: per-shard-group latency
        # histograms + the hedge counters (replica_stats.py)
        get = node.settings.get if hasattr(node, "settings") \
            else (lambda *a: None)

        def _flag(key: str, default: bool) -> bool:
            val = get(key)
            return default if val is None \
                else str(val).lower() not in ("false", "0")
        self.ars_enabled = _flag("search.ars.enabled", True)
        self.replica_stats = ReplicaStatsTable(
            alpha=float(get("search.ars.alpha") or 0.3))
        self.hedge_enabled = _flag("search.hedge.enabled", True)
        self.hedge_quantile = float(get("search.hedge.quantile") or 0.9)
        self.hedge_floor_ms = float(get("search.hedge.floor_ms") or 50.0)
        self.hedge_ceiling_ms = float(
            get("search.hedge.ceiling_ms") or 1000.0)
        # deadline-bounded partial results: request param
        # allow_partial_search_results overrides this node default
        self.default_allow_partial = _flag(
            "search.default_allow_partial_results", True)
        # ---- continuous-batching scheduler (ROADMAP item 6) ----
        # per-node device feeder: concurrent single-search traffic on
        # the shard path coalesces into the same batched programs the
        # msearch path uses, with one dispatch always in flight
        # (search/scheduler.py; settings search.scheduler.*)
        from elasticsearch_tpu.search.scheduler import (
            ContinuousBatchScheduler, settings_for)
        self.scheduler = ContinuousBatchScheduler(
            node_id=getattr(node, "node_id", None), **settings_for(get))
        # ---- dispatch watchdog (stall tolerance) ----
        # the module singleton guards every registered device wait (one
        # process = one device, the plane_breaker discipline); each node
        # applies its search.watchdog.* settings to it
        from elasticsearch_tpu.search import watchdog as _watchdog
        self.watchdog = _watchdog.dispatch_watchdog
        self.watchdog.configure(**_watchdog.settings_for(get))
        # background pack-build (plane warm) failure tracking: per-index
        # consecutive failures drive the retry backoff and, past
        # PLANE_WARM_MAX_RETRIES, the plane-degraded marking
        self._plane_warm_failures: dict[str, int] = {}
        # dedicated pool for _msearch item fan-out: sharing _pool with the
        # per-shard futures it spawns could deadlock at saturation
        self._msearch_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="msearch")
        node.transport_service.register_request_handler(
            self.FIELD_STATS, self._handle_field_stats, executor="search",
            sync=True)
        # keep-alive reaper: abandoned scroll contexts must not accumulate
        # for the node's lifetime (SearchService keep-alive reaper,
        # core/search/SearchService.java:1113)
        self._closed = False
        self._reaper = threading.Thread(target=self._reap_loop, daemon=True,
                                        name="scroll-reaper")
        self._reaper.start()

    def _submit(self, fn, *args):
        """Fan-out submit that carries the coordinating task across the
        pool boundary, so shard RPCs sent from pool threads stamp the
        parent-task header (TaskManager wiring)."""
        return self._pool.submit(tasks.bind_current(fn), *args)

    def _task_manager(self):
        return getattr(self.node, "task_manager", None)

    @contextlib.contextmanager
    def _coordinating_task(self, action: str, description: str,
                           timeout_ms: float | None = None):
        """Register the coordinator-side task for a client-entry search
        action, make it current for the duration, and wire the request
        `timeout` through the task's deadline. Yields the task (None
        when the node has no TaskManager — standalone unit tests)."""
        tm = self._task_manager()
        if tm is None:
            yield None
            return
        task = tm.register(action, description=description)
        if timeout_ms is not None:
            task.deadline = time.monotonic() + timeout_ms / 1000.0
        try:
            with tasks.use_task(task):
                yield task
        finally:
            tm.unregister(task)

    def _reap_loop(self) -> None:
        while not self._closed:
            time.sleep(5.0)
            if self._closed:
                return
            self.reap_expired()

    def close(self):
        self._closed = True
        self.scheduler.close()
        self._pool.shutdown(wait=False, cancel_futures=True)
        self._msearch_pool.shutdown(wait=False, cancel_futures=True)
        self._plane_warm_pool.shutdown(wait=False, cancel_futures=True)

    # ---- double-buffered plane refresh -------------------------------------

    def schedule_plane_rebuild(self, index_name: str) -> None:
        """Engine reader-swap hook: pipeline the next-generation
        collective-plane pack for `index_name` in the background.
        Coalesced (one queued build per index — a refresh storm folds
        into the next build, which reads the freshest generations) and
        lazy: only indices whose pack a search already created warm, so
        pure-indexing workloads pay nothing. Searches arriving before
        the build finishes wait on the per-index build lock instead of
        starting the compose cold — the refresh-to-first-search latency
        win the incremental data layer exists for."""
        if self._closed:
            return
        index = self.node.indices_service.indices.get(index_name)
        if index is None or "_mesh_cache" not in index.__dict__:
            return
        with self._plane_warm_lock:
            if index_name in self._plane_warm_pending:
                return
            self._plane_warm_pending.add(index_name)
        try:
            self._plane_warm_pool.submit(self._plane_warm, index_name)
        except RuntimeError:                 # pool shut down
            with self._plane_warm_lock:
                self._plane_warm_pending.discard(index_name)

    #: background pack-build hardening: failed warms retry with
    #: exponential backoff; past the retry budget the index is marked
    #: plane-degraded (searches keep serving the previous generation or
    #: the fan-out — never an error) until a build succeeds again
    PLANE_WARM_MAX_RETRIES = 3
    PLANE_WARM_BACKOFF_S = 0.25

    def _plane_warm(self, index_name: str) -> None:
        # the warm pool has no task context — attribute its compiles and
        # uploads to this node explicitly so per-node jit rollups hold
        from elasticsearch_tpu.observability import use_node
        with use_node(self.node.node_id):
            self._plane_warm_inner(index_name)

    def _plane_warm_inner(self, index_name: str) -> None:
        with self._plane_warm_lock:
            self._plane_warm_pending.discard(index_name)
        if self._closed:
            return
        index = self.node.indices_service.indices.get(index_name)
        if index is None:
            return
        if str(index.index_settings.get(
                "index.search.collective_plane", "true")).lower() \
                in ("false", "0"):
            return
        nshards = index.meta.number_of_shards
        if nshards < 2 or set(index.engines) != set(range(nshards)):
            return
        from elasticsearch_tpu.search import jit_exec
        try:
            if not any(e.acquire_searcher().segments
                       for e in index.shard_engines):
                return
            if not jit_exec.plane_breaker.allow():
                return          # unhealthy device: the breaker's probe,
            self._mesh_searcher_for([index])   # not the warm path, decides
        except Exception as e:               # noqa: BLE001 — warm-path
            # the failed build already returned its pack charge
            # (_mesh_build / _mesh_searcher_for release on the way out);
            # record the device error, then retry with backoff so a
            # transient fault doesn't silently kill the coalesced-
            # rebuild path — and degrade (never error) past the budget
            jit_exec.note_device_error(e)
            with self._plane_warm_lock:
                n = self._plane_warm_failures.get(index_name, 0) + 1
                self._plane_warm_failures[index_name] = n
            if n >= self.PLANE_WARM_MAX_RETRIES:
                index.plane_stats["degraded"] = True
                return
            if self._closed:
                return
            timer = threading.Timer(
                self.PLANE_WARM_BACKOFF_S * (2 ** (n - 1)),
                self.schedule_plane_rebuild, args=(index_name,))
            timer.daemon = True
            timer.start()
        else:
            jit_exec.plane_breaker.record_success()
            with self._plane_warm_lock:
                self._plane_warm_failures.pop(index_name, None)
            index.plane_stats.pop("degraded", None)

    # ---- data-node side ----------------------------------------------------

    @staticmethod
    def _apply_budget(req, budget_ms) -> None:
        """Shard-side deadline wiring: the coordinator ships the
        REMAINING time budget (its `timeout` minus wall time already
        spent queueing and fanning out), which tightens both the parsed
        request's timeout and the executing task's deadline — so
        per-shard ``timed_out`` reflects elapsed time on the whole
        request, not a clock restarted per shard."""
        if budget_ms is None:
            return
        budget_ms = max(float(budget_ms), 1.0)
        if req.timeout_ms is None or budget_ms < req.timeout_ms:
            req.timeout_ms = budget_ms
        cur = tasks.current_task()
        if cur is not None:
            dl = time.monotonic() + budget_ms / 1000.0
            cur.deadline = dl if cur.deadline is None \
                else min(cur.deadline, dl)

    def _scheduled_query_phase(self, searcher, req):
        """Shard-side query phase through the continuous-batching
        scheduler: concurrent single-search traffic targeting the same
        (reader, lane, shape) coalesces into ONE batched device program
        — the request-at-a-time gap BENCH_r04 measured. Falls back to
        the serial :meth:`ShardSearcher.query_phase` when the request's
        shape is unbatchable, the scheduler declines (ineligible batch,
        device fallback, shutdown), or the plane breaker is open (the
        serial path owns the breaker-gated eager fallback — the
        scheduler never queues toward an unhealthy device). SLO-burn
        sheds raise the typed 429 (SchedulerRejectedError) through to
        the coordinator."""
        sched = self.scheduler
        if sched is None or not sched.enabled:
            return searcher.query_phase(req)
        from elasticsearch_tpu.search import jit_exec
        from elasticsearch_tpu.search import scheduler as sched_mod
        lane, shape = sched_mod.classify(req, searcher)
        if lane is None or not jit_exec.plane_breaker.allow():
            return searcher.query_phase(req)
        out = sched.execute(
            lane,
            (searcher.ctx.index_name, searcher.shard_id, lane, shape,
             id(searcher.reader)),
            req, searcher.query_phase_batch_launch,
            searcher.query_phase_batch_drain)
        if out is None:
            return searcher.query_phase(req)
        return out

    def _hold_for_test(self) -> None:
        """Cancellation-checkpointed hold (see ``shard_query_delay``)."""
        delay = self.shard_query_delay
        if not delay:
            return
        deadline = time.monotonic() + float(delay)
        while time.monotonic() < deadline:
            tasks.raise_if_cancelled()
            time.sleep(0.005)

    def _handle_shard_query(self, request: dict, source) -> dict:
        return self._execute_shard(request["index"], request["shard"],
                                   request["body"],
                                   doc_slot=request.get("doc_slot"),
                                   dfs=request.get("dfs"),
                                   scroll_pin=request.get("scroll_pin"),
                                   budget_ms=request.get("budget_ms"))

    def _handle_shard_query_only(self, request: dict, source) -> dict:
        return self._execute_shard_query(
            request["index"], request["shard"], request["body"],
            doc_slot=request.get("doc_slot"), dfs=request.get("dfs"),
            pin=request["pin"], budget_ms=request.get("budget_ms"))

    def _shard_traced(self, phase: str, name: str, shard: int, fn):
        """Run one shard-phase callable under a per-shard attribution
        record (slow-log plane fields) and — when a trace is active — a
        ``shard`` span whose finished subtree is attached to the payload
        as ``_profile`` (the coordinator pops it into the response's
        profile section). The payload is shallow-copied before the
        attach so request-cache entries never carry spans."""
        if not obs_trace.active():
            with attribution.collect(admission="fanout"):
                return fn()
        from elasticsearch_tpu.observability import costs as obs_costs
        with attribution.collect(admission="fanout"), \
                obs_costs.collect_programs() as progs, \
                obs_trace.collect_spans() as spans, \
                obs_trace.span(phase, index=name, shard=shard):
            out = fn()
        out = dict(out)
        out["_profile"] = {"index": name, "shard": shard,
                           "node": self.node.node_id,
                           "spans": obs_trace.build_tree(spans),
                           # this shard phase's compiled programs (cost-
                           # observatory keys + measured µs), hottest
                           # first — joins the spans to /_cat/programs
                           "programs": obs_costs.render_rows(progs)}
        return out

    def _attach_ars(self, out: dict, t0: float) -> dict:
        """Piggyback this data node's adaptive-selection signals on the
        shard payload (the reference ships queue/service stats on the
        QuerySearchResult the same way): search-pool queue depth — the
        _cat/thread_pool accounting — plus the measured service time.
        Shallow-copied so request-cache entries never carry a stale
        snapshot."""
        try:
            queue = self.node.thread_pool.executor(
                "search").stats()["queue"]
        except Exception:        # noqa: BLE001 — pool closed/minimal node
            queue = 0
        out = dict(out)
        out["_ars"] = {"queue": queue,
                       "took_ms": (time.perf_counter() - t0) * 1e3}
        return out

    def _execute_shard_query(self, name: str, shard: int, body: dict,
                             doc_slot: int | None, dfs: dict | None,
                             pin: dict, budget_ms=None) -> dict:
        t0 = time.perf_counter()
        return self._attach_ars(self._shard_traced(
            "shard-query", name, shard,
            lambda: self._execute_shard_query_inner(
                name, shard, body, doc_slot, dfs, pin, budget_ms)), t0)

    def _execute_shard_query_inner(self, name: str, shard: int,
                                   body: dict, doc_slot: int | None,
                                   dfs: dict | None, pin: dict,
                                   budget_ms=None) -> dict:
        """Query phase only (QueryPhase.execute without fetch): rank this
        shard's top from+size and return compact hit DESCRIPTORS — ids,
        scores, sort keys — never `_source`. The reader pins under the
        request's context uid so the fetch round sees the same
        point-in-time (the reference holds the docs in the shard's search
        context between phases; ids crossing the wire + a pinned reader
        give the same contract)."""
        t0 = time.perf_counter()
        svc = self.node.indices_service.index(name)
        engine = svc.engine(shard)
        reader = self._pinned_reader(pin, name, shard, engine)
        breaker = None
        if svc.breaker_service is not None:
            breaker = svc.breaker_service.breaker("request")
            est = max(reader.num_docs, 1) * 16
            breaker.add_estimate(est, f"search [{name}][{shard}]")
        try:
            from elasticsearch_tpu.search.dfs import to_execution_stats
            searcher = ShardSearcher(shard, reader, svc.mapper_service,
                                     index_name=name, doc_slot=doc_slot,
                                     dfs_stats=to_execution_stats(dfs),
                                     version_fn=engine.doc_version)
            req = parse_search_request(body)
            self._apply_budget(req, budget_ms)
            self._hold_for_test()
            result = self._scheduled_query_phase(searcher, req)
            q_ms = (time.perf_counter() - t0) * 1000.0
            svc.note_search(body.get("stats"), q_ms)
            k = min(len(result.doc_ids), req.from_ + req.size)
            out = {"total": result.total,
                   "max_score": (float(result.max_score)
                                 if result.max_score is not None else None),
                   "docs": [int(d) for d in result.doc_ids[:k]],
                   "scores": [float(s) for s in result.scores[:k]],
                   "sort": wire_safe(result.sort_values[:k])
                   if result.sort_values is not None else None,
                   "aggs": wire_safe(result.agg_partials),
                   "terminated_early": result.terminated_early,
                   "timed_out": result.timed_out}
            if req.suggest:
                from elasticsearch_tpu.search.suggest import ShardSuggester
                sg = ShardSuggester(reader, svc.mapper_service)
                out["suggest"] = {spec.name: sg.collect(spec)
                                  for spec in req.suggest}
        finally:
            if breaker is not None:
                breaker.release(est)
        if svc.search_slow_log.thresholds:
            svc.search_slow_log.maybe_log(
                time.perf_counter() - t0,
                f"shard[{shard}], source[{json.dumps(body)[:512]}]")
        return out

    def _handle_shard_fetch(self, request: dict, source) -> dict:
        return self._shard_traced(
            "shard-fetch", request["index"], request["shard"],
            lambda: self._handle_shard_fetch_inner(request))

    def _handle_shard_fetch_inner(self, request: dict) -> dict:
        """Fetch phase for coordinator-chosen winners (fillDocIdsToLoad →
        the second fan-out, TransportSearchQueryThenFetchAction.java:
        89-150): build full hits for exactly the doc ids that made the
        global page, against the reader pinned by the query round."""
        from elasticsearch_tpu.search.phase import ShardQueryResult
        name, shard = request["index"], request["shard"]
        svc = self.node.indices_service.index(name)
        engine = svc.engine(shard)
        reader = self._pinned_reader({**request["pin"], "require": True},
                                     name, shard, engine)
        req = parse_search_request(request["body"])
        docs = np.asarray(request["docs"], np.int32)
        result = ShardQueryResult(
            shard, 0, None, docs,
            np.asarray(request["scores"], np.float32),
            request.get("sort"), {}, reader)
        searcher = ShardSearcher(shard, reader, svc.mapper_service,
                                 index_name=name,
                                 doc_slot=request.get("doc_slot"),
                                 version_fn=engine.doc_version)
        return {"hits": searcher.fetch_phase(req, result, name,
                                             list(range(len(docs))))}

    def _handle_free_context(self, request: dict, source) -> dict:
        """Release reader pins for a finished context (the reference's
        free-context round after query_then_fetch / on clear_scroll)."""
        self._drop_pins(request["uid"])
        return {}

    def _free_context(self, uid: str, node_ids) -> None:
        """Fire-and-forget pin release on exactly the nodes that served
        the context (the reference's free-context round)."""
        self._drop_pins(uid)
        state = self.node.cluster_service.state()
        for nid in set(node_ids):
            if nid == self.node.node_id:
                continue
            target = state.node(nid)
            if target is None:
                continue
            try:
                self.node.transport_service.send_request(
                    target, self.FREE_CONTEXT, {"uid": uid}, timeout=5.0)
            except Exception:        # noqa: BLE001 — pins age out anyway
                pass

    def _handle_shard_msearch(self, request: dict, source) -> dict:
        """Shard-side _msearch: B request bodies against one shard in ONE
        batched device program when they share a plan
        (ShardSearcher.query_phase_batch — the TPU-native multi-search),
        per-request execution otherwise. → {"payloads": [per body]}."""
        name, shard = request["index"], request["shard"]
        bodies = request["bodies"]
        svc = self.node.indices_service.index(name)
        engine = svc.engine(shard)
        reader = device_reader_for(engine)
        searcher = ShardSearcher(shard, reader, svc.mapper_service,
                                 index_name=name,
                                 doc_slot=request.get("doc_slot"),
                                 version_fn=engine.doc_version)
        reqs, errors = [], {}
        for i, body in enumerate(bodies):
            try:
                reqs.append(parse_search_request(body))
            except Exception as e:           # noqa: BLE001 — per-item error
                reqs.append(None)
                errors[i] = str(e)
        valid = [(i, r) for i, r in enumerate(reqs) if r is not None]
        results: dict[int, object] = {}
        try:
            batch = searcher.query_phase_batch([r for _, r in valid]) \
                if valid else []
        except Exception:                    # noqa: BLE001 — isolate items
            batch = None
        if batch is not None:
            for (i, _), res in zip(valid, batch):
                results[i] = res
        else:
            for i, r in valid:
                try:
                    results[i] = searcher.query_phase(r)
                except Exception as e:       # noqa: BLE001 — per-item error
                    errors[i] = str(e)       # others must still succeed
        payloads = []
        for i, body in enumerate(bodies):
            if i in errors:
                payloads.append({"error": errors[i]})
                continue
            req, result = reqs[i], results[i]
            try:
                k = min(len(result.doc_ids), req.from_ + req.size)
                hits = searcher.fetch_phase(req, result, name,
                                            list(range(k)))
                out = {
                    "total": result.total,
                    "max_score": (float(result.max_score)
                                  if result.max_score is not None else None),
                    "hits": hits, "aggs": wire_safe(result.agg_partials),
                    "terminated_early": result.terminated_early,
                    "timed_out": result.timed_out}
                if req.suggest:
                    from elasticsearch_tpu.search.suggest import \
                        ShardSuggester
                    sg = ShardSuggester(reader, svc.mapper_service)
                    out["suggest"] = {spec.name: sg.collect(spec)
                                      for spec in req.suggest}
                payloads.append(out)
            except Exception as e:           # noqa: BLE001 — per-item error
                payloads.append({"error": str(e)})
        return {"payloads": payloads}

    def _handle_shard_dfs(self, request: dict, source) -> dict:
        """DFS phase (DfsPhase.execute analog): term/collection statistics
        of this shard for the query's terms."""
        from elasticsearch_tpu.search.dfs import shard_dfs
        from elasticsearch_tpu.search.query_dsl import parse_query
        name, shard = request["index"], request["shard"]
        svc = self.node.indices_service.index(name)
        reader = device_reader_for(svc.engine(shard))
        query = parse_query((request.get("body") or {}).get("query"))
        return shard_dfs(reader, svc.mapper_service, query)

    def _execute_shard(self, name: str, shard: int, body: dict,
                       doc_slot: int | None = None,
                       dfs: dict | None = None,
                       scroll_pin: dict | None = None,
                       budget_ms=None) -> dict:
        t0 = time.perf_counter()
        return self._attach_ars(self._shard_traced(
            "shard", name, shard,
            lambda: self._execute_shard_inner(
                name, shard, body, doc_slot=doc_slot, dfs=dfs,
                scroll_pin=scroll_pin, budget_ms=budget_ms)), t0)

    def _execute_shard_inner(self, name: str, shard: int, body: dict,
                             doc_slot: int | None = None,
                             dfs: dict | None = None,
                             scroll_pin: dict | None = None,
                             budget_ms=None) -> dict:
        t0 = time.perf_counter()
        svc = self.node.indices_service.index(name)
        engine = svc.engine(shard)
        if scroll_pin is not None:
            reader = self._pinned_reader(scroll_pin, name, shard, engine)
        else:
            reader = device_reader_for(engine)
        # shard request cache: hits-free (size 0) requests keyed by reader
        # generation + request bytes (IndicesRequestCache.java:78); gated
        # by index.requests.cache.enable
        cache_key = None
        if scroll_pin is None and body.get("size") == 0 and \
                str(svc.index_settings.get(
                "index.requests.cache.enable", "true")).lower() != "false":
            cache_key = self.request_cache.key(engine.engine_uuid,
                                               reader.generation, body, dfs)
            cached = self.request_cache.get(cache_key)
            if cached is not None:
                # a cache hit is still a served query (ShardSearchStats
                # increments outside the request cache)
                svc.note_search(body.get("stats"),
                                (time.perf_counter() - t0) * 1000.0)
                return cached
        # per-request scratch accounting (request breaker): score + mask
        # arrays over every doc of the shard
        breaker = None
        if svc.breaker_service is not None:
            breaker = svc.breaker_service.breaker("request")
            est = max(reader.num_docs, 1) * 16
            breaker.add_estimate(est, f"search [{name}][{shard}]")
        try:
            from elasticsearch_tpu.search.dfs import to_execution_stats
            searcher = ShardSearcher(shard, reader, svc.mapper_service,
                                     index_name=name, doc_slot=doc_slot,
                                     dfs_stats=to_execution_stats(dfs),
                                     version_fn=engine.doc_version)
            req = parse_search_request(body)
            self._apply_budget(req, budget_ms)
            self._hold_for_test()
            result = self._scheduled_query_phase(searcher, req)
            q_ms = (time.perf_counter() - t0) * 1000.0
            k = min(len(result.doc_ids), req.from_ + req.size)
            hits = searcher.fetch_phase(req, result, name, list(range(k)))
            svc.note_search(body.get("stats"), q_ms,
                            (time.perf_counter() - t0) * 1000.0 - q_ms)
            out = {"total": result.total,
                   "max_score": (float(result.max_score)
                                 if result.max_score is not None else None),
                   "hits": hits,
                   "aggs": wire_safe(result.agg_partials),
                   "terminated_early": result.terminated_early,
                   "timed_out": result.timed_out}
            if req.suggest:
                from elasticsearch_tpu.search.suggest import ShardSuggester
                sg = ShardSuggester(reader, svc.mapper_service)
                out["suggest"] = {spec.name: sg.collect(spec)
                                  for spec in req.suggest}
        finally:
            if breaker is not None:
                breaker.release(est)
        if svc.search_slow_log.thresholds:       # skip json.dumps when off
            svc.search_slow_log.maybe_log(
                time.perf_counter() - t0,
                f"shard[{shard}], source[{json.dumps(body)[:512]}]")
        if cache_key is not None and not out.get("timed_out") \
                and not out.get("terminated_early"):
            # partial results must not pin themselves until the next
            # refresh (the reference cache refuses timed-out entries too)
            self.request_cache.put(cache_key, out)
        return out

    # ---- coordinator -------------------------------------------------------

    def _shard_groups(self, state, names: list[str],
                      routing: str | None = None,
                      preference: str | None = None):
        """→ [(index, shard, [copies in try-order])] — active copies only,
        local first, then rotated (preference/rotation,
        performFirstPhase :156). `routing` (comma-separated keys)
        restricts the fan-out to the shards those keys hash to
        (OperationRouting.searchShards with a routing set); `preference`
        selects/orders the copies per the reference's preference grammar
        (_primary/_primary_first/_local/_only_node/_prefer_node/_shards
        and custom sticky strings)."""
        from elasticsearch_tpu.cluster.routing import OperationRouting
        rot = next(self._rotation)
        pref = preference
        shard_filter: set[int] | None = None
        if pref and pref.startswith("_shards:"):
            # 2.x syntax: _shards:0,2[;<nested-preference>]
            spec, _, nested = pref[len("_shards:"):].partition(";")
            try:
                shard_filter = {int(s) for s in spec.split(",")
                                if s.strip()}
            except ValueError:
                from elasticsearch_tpu.common.errors import (
                    IllegalArgumentError)
                raise IllegalArgumentError(
                    f"invalid _shards preference [{preference}]") from None
            pref = nested or None
        groups = []
        for name in names:
            meta = state.indices[name]
            sids = OperationRouting.search_shards(
                meta.number_of_shards, routing=routing)
            for sid in sids:
                if shard_filter is not None and sid not in shard_filter:
                    continue
                copies = [c for c in
                          state.routing_table.shard_copies(name, sid)
                          if c.active]
                # a preference that excludes every copy still keeps the
                # group: the fan-out records a shard FAILURE for it (the
                # reference raises rather than silently shrinking the
                # result set)
                groups.append((name, sid,
                               self._copy_try_order(copies, pref, rot)))
        return groups

    def _copy_try_order(self, copies: list, pref: str | None, rot: int):
        """Adaptive replica selection: the static preference grammar
        still wins when the caller pinned placement (an explicit
        preference IS an ordering instruction), but the default
        try-order is re-ranked by each copy's observed health — C3
        score ascending over the ReplicaStatsTable's per-node EWMAs,
        queue depth and outstanding count — instead of blind rotation.
        The rank sort is stable, so unobserved/healthy-equal copies
        keep the local-first rotated baseline."""
        ordered = self._order_copies(copies, pref, rot)
        if pref is not None or not self.ars_enabled or len(ordered) < 2:
            return ordered
        return self.replica_stats.order(ordered)

    def _order_copies(self, copies: list, pref: str | None, rot: int):
        """Copy try-order under a preference (OperationRouting's
        preference-aware selection, reference :67-71)."""
        local_id = self.node.node_id
        if pref is None or pref == "_local":
            # default: local copy first, then rotate the rest
            local = [c for c in copies if c.node_id == local_id]
            rest = [c for c in copies if c.node_id != local_id]
            if rest:
                k = rot % len(rest)
                rest = rest[k:] + rest[:k]
            return local + rest
        if pref == "_primary":
            return [c for c in copies if c.primary]
        if pref == "_primary_first":
            return [c for c in copies if c.primary] + \
                [c for c in copies if not c.primary]
        if pref.startswith("_only_node:"):
            node_id = pref.split(":", 1)[1]
            return [c for c in copies if c.node_id == node_id]
        if pref.startswith("_prefer_node:"):
            node_id = pref.split(":", 1)[1]
            return [c for c in copies if c.node_id == node_id] + \
                [c for c in copies if c.node_id != node_id]
        # custom string: deterministic sticky rotation — the same
        # preference value always lands on the same copy, on every
        # coordinating node (murmur, NOT Python's per-process hash;
        # Python's % is already non-negative for a positive modulus)
        if copies:
            from elasticsearch_tpu.utils.hashing import murmur3_hash32
            k = murmur3_hash32(str(pref).encode("utf-8")) % len(copies)
            return copies[k:] + copies[:k]
        return []

    def _try_shard(self, state, name: str, sid: int, copies: list,
                   body: dict, doc_slot: int | None = None,
                   dfs: dict | None = None,
                   scroll_pin: dict | None = None,
                   qtf_pin: dict | None = None,
                   budget_deadline: float | None = None,
                   allow_hedge: bool = True):
        """→ ("ok", payload, node_id) or ("fail", reason-dict, None).
        Walks the copy list (shard-failover retry,
        TransportSearchTypeAction.java:205-247). With `qtf_pin`, runs the
        query-ONLY phase (descriptors, reader pinned) instead of
        query+fetch; the returned node_id tells the coordinator where the
        pin — and thus the fetch round — lives. ``budget_deadline`` is
        the request's absolute perf_counter deadline: EACH attempt
        receives only the milliseconds still remaining when IT launches
        (a retried copy must not restart the budget), so per-shard
        ``timed_out`` reflects total elapsed time.

        Single-round requests with ≥2 copies ride the HEDGED path
        (tail tolerance): pinned contexts stay sequential — a hedge
        would pin readers on the losing node the fetch round never
        frees."""
        if (self.hedge_enabled and allow_hedge and len(copies) > 1
                and scroll_pin is None and qtf_pin is None):
            return self._try_shard_hedged(state, name, sid, copies, body,
                                          doc_slot, dfs, budget_deadline)
        return self._try_shard_seq(state, name, sid, copies, body,
                                   doc_slot, dfs, scroll_pin, qtf_pin,
                                   budget_deadline)

    def _remaining_budget_ms(self, budget_deadline: float | None):
        """Milliseconds left on the request's absolute deadline at THIS
        instant — what a (re)launched copy attempt is allowed to spend
        (the 'shards get the REMAINING budget' rule, applied per
        attempt)."""
        if budget_deadline is None:
            return None
        return max((budget_deadline - time.perf_counter()) * 1000.0, 1.0)

    def _launch_copy(self, state, c, name: str, sid: int, body: dict,
                     doc_slot, dfs, scroll_pin, qtf_pin, budget_ms):
        """Launch ONE copy attempt asynchronously → Future resolving to
        the shard payload, or None when the copy's node left the
        cluster state. Local copies still execute ON the bounded search
        pool (the reference dispatches local shard ops to the SEARCH
        threadpool too) so saturation rejects instead of queueing
        unboundedly; a rejection fails over like any shard failure."""
        if c.node_id == self.node.node_id:
            if qtf_pin is not None:
                return self.node.thread_pool.submit(
                    "search", self._execute_shard_query, name, sid,
                    body, doc_slot, dfs, qtf_pin, budget_ms)
            return self.node.thread_pool.submit(
                "search", self._execute_shard, name, sid, body,
                doc_slot=doc_slot, dfs=dfs, scroll_pin=scroll_pin,
                budget_ms=budget_ms)
        target = state.node(c.node_id)
        if target is None:
            return None
        if qtf_pin is not None:
            action = self.QUERY_ID
            request = {"index": name, "shard": sid, "body": body,
                       "doc_slot": doc_slot, "dfs": dfs,
                       "pin": qtf_pin, "budget_ms": budget_ms}
        else:
            action = self.QUERY_FETCH
            request = {"index": name, "shard": sid, "body": body,
                       "doc_slot": doc_slot, "dfs": dfs,
                       "scroll_pin": scroll_pin, "budget_ms": budget_ms}
        return self.node.transport_service.send_request(
            target, action, request, timeout=30.0)

    def _note_copy_response(self, c, name: str, sid: int, t_att: float,
                            payload: dict) -> dict:
        """Feed one consumed copy response into the adaptive-selection
        table: observed response time, plus the piggybacked ``_ars``
        service-time/queue-depth block (popped — it must not leak into
        the merged response), and the shard group's latency histogram
        the hedge delay reads."""
        resp_ms = (time.perf_counter() - t_att) * 1e3
        ars = payload.pop("_ars", None) if isinstance(payload, dict) \
            else None
        self.replica_stats.observe(
            c.node_id, resp_ms,
            service_ms=(ars or {}).get("took_ms"),
            queue=(ars or {}).get("queue"))
        self.replica_stats.observe_group((name, sid), resp_ms)
        return payload

    @staticmethod
    def _shard_failure(name: str, sid: int, last: Exception | None) -> dict:
        fail = {"shard": sid, "index": name,
                "reason": {"type": "shard_search_failure",
                           "reason": str(last) if last
                           else "no active copy"}}
        if isinstance(last, ElasticsearchTpuError):
            fail["reason"] = last.to_xcontent()
            fail["status"] = last.status
        return fail

    def _try_shard_seq(self, state, name: str, sid: int, copies: list,
                       body: dict, doc_slot=None, dfs=None,
                       scroll_pin=None, qtf_pin=None,
                       budget_deadline: float | None = None,
                       last: Exception | None = None):
        """Sequential next-copy failover (the pre-hedging model, and the
        hedged path's tail for copies beyond the first two)."""
        from elasticsearch_tpu.action.replication import unwrap_remote
        from elasticsearch_tpu.common.errors import (
            IllegalArgumentError, MapperParsingError, QueryParsingError)
        rs = self.replica_stats
        for c in copies:
            # per-copy retry budget: remaining time at THIS attempt's
            # launch, never the original full budget
            budget_ms = self._remaining_budget_ms(budget_deadline)
            rs.begin(c.node_id)
            t_att = time.perf_counter()
            try:
                fut = self._launch_copy(state, c, name, sid, body,
                                        doc_slot, dfs, scroll_pin,
                                        qtf_pin, budget_ms)
                if fut is None:
                    continue
                try:
                    payload = fut.result(35.0)
                except Exception:
                    fut.cancel()     # don't leave abandoned work queued
                    raise
                return "ok", self._note_copy_response(
                    c, name, sid, t_att, payload), c.node_id
            except Exception as e:               # noqa: BLE001 — classify
                e = unwrap_remote(e)
                if isinstance(e, TaskCancelledError):
                    # a cancelled shard task must NOT fail over — re-running
                    # a shed query on the next copy defeats the cancel; the
                    # shard reports task_cancelled and the response stays
                    # partial
                    last = e
                    break
                # Deterministic request errors fail the same way on every
                # copy — abort the whole search with the real status.
                # Anything else (engine closed mid-relocation, node gone,
                # state lag) fails over to the next copy.
                if isinstance(e, (QueryParsingError, IllegalArgumentError,
                                  MapperParsingError)):
                    raise e from None
                last = e
            finally:
                rs.end(c.node_id)
        return "fail", self._shard_failure(name, sid, last), None

    # ---- hedged shard requests (tail tolerance) ----------------------------

    def _hedge_attempt(self, state, c, name: str, sid: int, body: dict,
                       doc_slot, dfs, budget_deadline):
        """Launch one hedged copy attempt under its OWN wrapper task —
        a child of the coordinating task, so the remote shard task
        parents on it and a ban on the wrapper id cancels exactly this
        attempt's work (the PR 2 machinery, scoped to one copy).
        → (future, wrapper-task-or-None); raises on synchronous launch
        failure (pool rejection / serialization)."""
        budget_ms = self._remaining_budget_ms(budget_deadline)
        tm = self._task_manager()
        task = None
        if tm is not None:
            task = tm.register(
                self.HEDGE_ACTION,
                description=f"[{name}][{sid}] copy[{c.node_id}]")
        ctx = tasks.use_task(task) if task is not None \
            else contextlib.nullcontext()
        try:
            with ctx:
                fut = self._launch_copy(state, c, name, sid, body,
                                        doc_slot, dfs, None, None,
                                        budget_ms)
        except BaseException:
            if tm is not None:
                tm.unregister(task)
            raise
        if fut is None:
            if tm is not None:
                tm.unregister(task)
            raise ElasticsearchTpuError(
                f"node [{c.node_id}] left the cluster")
        return fut, task

    def _cancel_hedge_loser(self, c, fut, task,
                            reason: str = "hedged request lost") -> None:
        """First response won: cancel the losing attempt through the
        task-ban machinery — the wrapper task (and, via the broadcast
        ban on its id, the remote shard task parented on it) cancels,
        the losing shard work aborts at its next cooperative checkpoint
        releasing every breaker byte and closing every span, and the
        ban lifts when the wrapper unregisters (done-callback: transport
        futures always complete — response, timeout or disconnect)."""
        tm = self._task_manager()
        if tm is not None and task is not None:
            tm.cancel(task, reason)
            if tm.ban_broadcaster is not None:
                # remote children (current and in-flight registrations)
                # cancel via the cluster-wide ban on the wrapper id
                task.ban_sent = True     # unregister lifts it
                try:
                    tm.ban_broadcaster(task.task_id, True, reason)
                except Exception:        # noqa: BLE001 — best effort
                    pass

        def _settle(f):
            self.replica_stats.end(c.node_id)
            if tm is not None and task is not None:
                tm.unregister(task)
            if not f.cancelled():
                f.exception()            # consume, never propagate
        fut.add_done_callback(_settle)
        fut.cancel()                     # unstarted local work: drop now

    def _try_shard_hedged(self, state, name: str, sid: int, copies: list,
                          body: dict, doc_slot, dfs,
                          budget_deadline: float | None):
        """Hedged single-round shard execution ("The Tail at Scale"):
        launch the best-ranked copy; if no response lands within the
        shard group's ADAPTIVE hedge delay (latency-histogram
        p-quantile, floor/ceiling bounded), fire ONE backup at the
        next-ranked copy. First response wins; the loser is cancelled
        through the task-ban machinery and its counters reconcile as
        ``hedges_launched == hedges_won + hedges_cancelled +
        in_flight``. Copies beyond the first two remain sequential
        failover via _try_shard_seq."""
        from concurrent.futures import FIRST_COMPLETED
        from concurrent.futures import wait as futures_wait
        from elasticsearch_tpu.action.replication import unwrap_remote
        from elasticsearch_tpu.common.errors import (
            IllegalArgumentError, MapperParsingError, QueryParsingError)
        rs = self.replica_stats
        deterministic = (QueryParsingError, IllegalArgumentError,
                         MapperParsingError)
        primary, backup = copies[0], copies[1]
        delay_s = rs.hedge_delay_ms(
            (name, sid), self.hedge_quantile, self.hedge_floor_ms,
            self.hedge_ceiling_ms) / 1000.0
        rs.begin(primary.node_id)
        t0 = time.perf_counter()
        try:
            fut0, task0 = self._hedge_attempt(
                state, primary, name, sid, body, doc_slot, dfs,
                budget_deadline)
        except Exception as e:               # noqa: BLE001 — classify
            rs.end(primary.node_id)
            e = unwrap_remote(e)
            if isinstance(e, deterministic):
                raise e from None
            return self._try_shard_seq(state, name, sid, copies[1:],
                                       body, doc_slot, dfs, None, None,
                                       budget_deadline, last=e)
        pend: dict = {fut0: (primary, task0, t0)}
        hedged_fut = None
        last: Exception | None = None
        tried = 1          # copies consumed by this hedged round
        # phase 1: give the primary its hedge-delay head start
        done, _ = futures_wait([fut0], timeout=delay_s)
        if not done:
            # the primary blew the hedge delay — that elapsed wait is a
            # FLOOR on its true latency; recording it is how a browned-
            # out (slow, not failed) copy sinks in the ARS ranks even
            # though its response is never consumed
            rs.observe(primary.node_id,
                       (time.perf_counter() - t0) * 1e3)
            rs.note_hedge_launched()
            rs.begin(backup.node_id)
            try:
                fut1, task1 = self._hedge_attempt(
                    state, backup, name, sid, body, doc_slot, dfs,
                    budget_deadline)
                hedged_fut = fut1
                pend[fut1] = (backup, task1, time.perf_counter())
                tried = 2
            except Exception as e:           # noqa: BLE001 — still-born
                rs.end(backup.node_id)
                rs.note_hedge_cancelled()
                tried = 2
                e = unwrap_remote(e)
                if isinstance(e, deterministic):
                    self._cancel_hedge_loser(primary, fut0, task0,
                                             "request aborted")
                    raise e from None
                last = e
        # phase 2: first successful response wins. The wait is SLICED so
        # a cancel of the coordinating request propagates promptly: the
        # local ban recursion cancels the hedge WRAPPER tasks, but the
        # remote shard tasks parent on the wrapper ids — broadcasting
        # the wrapper bans (via _cancel_hedge_loser) is what reaches
        # them, and only this loop knows the wrappers
        cur = tasks.current_task()
        hard_deadline = time.monotonic() + 35.0
        while pend:
            remaining = hard_deadline - time.monotonic()
            if remaining <= 0:
                break
            done, _ = futures_wait(list(pend),
                                   timeout=min(0.1, remaining),
                                   return_when=FIRST_COMPLETED)
            if not done:
                if cur is not None and cur.cancelled:
                    for lf, (lc, ltask, _) in pend.items():
                        if lf is hedged_fut:
                            rs.note_hedge_cancelled()
                        self._cancel_hedge_loser(lc, lf, ltask,
                                                 "request cancelled")
                    pend.clear()
                    last = TaskCancelledError(
                        f"task [{cur.task_id}] was cancelled "
                        f"[{cur.cancel_reason or 'unknown'}]")
                continue
            for f in done:
                c, task, t_att = pend.pop(f)
                tm = self._task_manager()
                try:
                    payload = f.result(0)
                except Exception as e:       # noqa: BLE001 — classify
                    rs.end(c.node_id)
                    if tm is not None and task is not None:
                        tm.unregister(task)
                    if f is hedged_fut:
                        rs.note_hedge_cancelled()   # backup lost by dying
                    e = unwrap_remote(e)
                    if isinstance(e, TaskCancelledError):
                        # the REQUEST was cancelled: stop, stay partial
                        last = e
                        for lf, (lc, ltask, _) in pend.items():
                            self._cancel_hedge_loser(lc, lf, ltask,
                                                     "request cancelled")
                        pend.clear()
                        break
                    if isinstance(e, deterministic):
                        for lf, (lc, ltask, _) in pend.items():
                            self._cancel_hedge_loser(lc, lf, ltask,
                                                     "request aborted")
                        raise e from None
                    last = e
                    continue
                # winner: cancel every still-pending loser
                rs.end(c.node_id)
                if tm is not None and task is not None:
                    tm.unregister(task)
                if f is hedged_fut:
                    rs.note_hedge_won()
                for lf, (lc, ltask, _) in pend.items():
                    if lf is hedged_fut:
                        rs.note_hedge_cancelled()
                    self._cancel_hedge_loser(lc, lf, ltask)
                return "ok", self._note_copy_response(
                    c, name, sid, t_att, payload), c.node_id
        if pend:
            # hard deadline blown with attempts still in flight: abandon
            # them (their transport timeouts settle the callbacks)
            for lf, (lc, ltask, _) in pend.items():
                if lf is hedged_fut:
                    rs.note_hedge_cancelled()
                self._cancel_hedge_loser(lc, lf, ltask,
                                         "shard request timed out")
            if last is None:
                last = ElasticsearchTpuError(
                    f"[{name}][{sid}] no copy responded in time")
        if not isinstance(last, TaskCancelledError) and \
                len(copies) > tried:
            return self._try_shard_seq(state, name, sid, copies[tried:],
                                       body, doc_slot, dfs, None, None,
                                       budget_deadline, last=last)
        return "fail", self._shard_failure(name, sid, last), None

    # accepted search types (ref: SearchType.fromString,
    # core/action/search/SearchType.java:29 — scan/count are deprecated
    # aliases there; query_and_fetch IS this implementation's execution
    # model, see module docstring)
    SEARCH_TYPES = (None, "query_then_fetch", "query_and_fetch",
                    "dfs_query_then_fetch", "dfs_query_and_fetch",
                    "scan", "count")

    def _tracing_on(self, profile: bool) -> bool:
        """Tracer gate: per-request ``profile`` opt-in, or the node-wide
        ``observability.tracer.enable`` setting (default off — the off
        path allocates no span objects)."""
        if profile:
            return True
        settings = getattr(self.node, "settings", None)
        if settings is None:
            return False
        return str(settings.get("observability.tracer.enable",
                                "false")).lower() in ("true", "1")

    def search(self, index_expr: str, body: dict | None = None,
               scroll: str | None = None,
               search_type: str | None = None,
               routing: str | None = None,
               preference: str | None = None) -> dict:
        """Client entry: registers the COORDINATING task (the root of the
        fan-out's task tree), wires the request `timeout` through its
        deadline, and — when the task was cancelled mid-flight — reports
        the partial response with an explicit ``cancelled`` flag.

        ``"profile": true`` in the body turns the span tracer on for
        this request and returns the resulting span trees (coordinator
        phases + per-shard device seams) under ``response["profile"]``.
        The flag is stripped BEFORE the fan-out, so shards execute the
        byte-identical request — profiled hits are guaranteed
        bit-identical to unprofiled ones."""
        body = dict(body or {})
        profile = bool(body.pop("profile", False))
        timeout_ms = None
        raw_timeout = body.get("timeout")
        if raw_timeout is not None:
            try:
                timeout_ms = parse_time_value(raw_timeout,
                                              "timeout") * 1000.0
            except (ValueError, TypeError):
                pass                     # parse_search_request re-raises
        with self._coordinating_task(
                "indices:data/read/search",
                f"indices[{index_expr}], search_type[{search_type or '-'}]"
                f"{', scroll' if scroll else ''}",
                timeout_ms=timeout_ms) as task:
            if task is not None and self._tracing_on(profile):
                # trace id IS the coordinating task id: the span tree
                # and the task tree describe the same request, and
                # GET /_tasks/{id}/trace joins them back up
                from elasticsearch_tpu.observability import \
                    costs as obs_costs
                with obs_trace.trace(task.task_id, self.node.node_id), \
                        obs_trace.profile_sink() as shard_profiles, \
                        obs_costs.collect_programs() as coord_progs, \
                        obs_trace.collect_spans() as coord_spans, \
                        obs_trace.span("search", index=index_expr):
                    resp = self._search(index_expr, body, scroll=scroll,
                                        search_type=search_type,
                                        routing=routing,
                                        preference=preference)
                if profile:
                    resp["profile"] = {
                        "trace_id": task.task_id,
                        "coordinator":
                            obs_trace.build_tree(coord_spans),
                        "shards": shard_profiles,
                        # coordinator-dispatched compiled programs (the
                        # collective plane, scheduler batches bound to
                        # this request) with cost-observatory keys +
                        # measured µs; per-shard rows ride each shard's
                        # profile payload
                        "programs": obs_costs.render_rows(coord_progs),
                    }
            else:
                resp = self._search(index_expr, body, scroll=scroll,
                                    search_type=search_type,
                                    routing=routing,
                                    preference=preference)
            if task is not None and task.cancelled:
                resp["cancelled"] = True
            return resp

    def _search(self, index_expr: str, body: dict | None = None,
                scroll: str | None = None,
                search_type: str | None = None,
                routing: str | None = None,
                preference: str | None = None) -> dict:
        from elasticsearch_tpu.common.errors import IllegalArgumentError
        if search_type not in self.SEARCH_TYPES:
            raise IllegalArgumentError(
                f"No search type for [{search_type}]")
        if search_type in ("dfs_query_and_fetch",):
            search_type = "dfs_query_then_fetch"
        t0 = time.perf_counter()
        body = dict(body or {})
        # deadline-bounded partial results: stripped BEFORE the fan-out
        # (like "profile") so shards execute the byte-identical request;
        # None defers to search.default_allow_partial_results
        allow_partial = body.pop("allow_partial_search_results", None)
        if search_type == "count":
            # deprecated alias for size=0 (SearchType.COUNT): hit counting
            # + aggregations, no fetch phase
            body["size"] = 0
            search_type = None
        scan = search_type == "scan"
        if scan:
            # SearchType.SCAN (2.x, deprecated in 2.1): unscored index-
            # order sweep behind a scroll cursor. First response carries
            # the total and a scroll id but NO hits; each scroll pulls
            # size docs per shard in _doc order (QueryPhase.java:161-186
            # MinDocQuery continuation)
            if scroll is None:
                raise IllegalArgumentError(
                    "scan search type requires a [scroll] parameter")
            body["sort"] = ["_doc"]
            search_type = None
        dfs_cache: dict | None = {} if scroll is not None else None
        scroll_pin = None
        if scroll is not None:
            body["sort"] = self._scroll_sort(body.get("sort"))
            import uuid as _uuid
            keep = parse_time_value(scroll, "scroll")
            scroll_pin = {"uid": _uuid.uuid4().hex, "keep_s": keep}
        if scan:
            # per-shard page size, like the reference's scan contexts —
            # counting only the ROUTED shards when routing narrows them
            names = self.node.indices_service.resolve_open(index_expr)
            n_shards = len(self._shard_groups(
                self.node.cluster_service.state(), names,
                routing=routing)) or 1
            body["size"] = int(body.get("size", 10)) * n_shards
            probe = dict(body, size=0)
            resp = self._search_once(index_expr, probe, t0,
                                     dfs_cache=dfs_cache,
                                     scroll_pin=scroll_pin,
                                     routing=routing,
                                     preference=preference,
                                     allow_partial=allow_partial)
            # cursor not advanced: the first scroll() call reads page one
            resp["_scroll_id"] = self._open_scroll(
                index_expr, body, scroll, {"hits": {"hits": [{}]}},
                dfs_cache=dfs_cache, ctx_uid=scroll_pin["uid"],
                routing=routing, preference=preference)
            return resp
        resp = self._search_once(index_expr, body, t0,
                                 search_type=search_type,
                                 dfs_cache=dfs_cache,
                                 scroll_pin=scroll_pin,
                                 routing=routing,
                                 preference=preference,
                                 allow_partial=allow_partial)
        if scroll is not None:
            resp["_scroll_id"] = self._open_scroll(index_expr, body, scroll,
                                                   resp,
                                                   search_type=search_type,
                                                   dfs_cache=dfs_cache,
                                                   ctx_uid=scroll_pin["uid"],
                                                   routing=routing,
                                                   preference=preference)
        return resp

    #: search types the plane can serve: dfs types score with global
    #: statistics (the mesh's native mode); the rest score each shard
    #: with its OWN statistics, bit-matching the default fan-out
    PLANE_SEARCH_TYPES = (None, "query_then_fetch", "query_and_fetch",
                          "dfs_query_then_fetch", "dfs_query_and_fetch")

    @staticmethod
    def _note_plane_fallback(indices, reason: str) -> None:
        """One plane admission attempt that fell back to the fan-out:
        label the node-wide reason counter AND each target index's
        admission stats (surfaced in _stats / _nodes/stats). Admission
        declines are NOT compiled-path `fallbacks` — the request still
        runs correctly on the RPC fan-out."""
        from elasticsearch_tpu.search import jit_exec
        jit_exec.note_plane_fallback(reason)
        for index in indices:
            index.note_plane_fallback(reason)

    def _try_collective_plane(self, names, bodies: list, reqs: list,
                              t0: float,
                              search_type: str | None = None
                              ) -> list[dict] | None:
        """→ full search responses for a BATCH of bodies served by ONE
        mesh program, or None (opted out / shards not all local /
        ineligible shape — the caller proceeds with the ordinary
        fan-out). DEFAULT-ON: eligible searches ride the plane unless
        `index.search.collective_plane: false` opts the index out. The
        merged global top-k of each item splits back by owning (index,
        shard) so the standard winner-only fetch assembles hits;
        _msearch groups ride the same call with B > 1 (the batch IS the
        accelerator's unit of work), and a multi-index request packs
        every index's shard columns into the SAME program — one mesh
        dispatch for an msearch spanning indices."""
        if not names or search_type not in self.PLANE_SEARCH_TYPES:
            return None
        svc = self.node.indices_service
        indices = []
        for nm in names:
            index = svc.indices.get(nm)
            if index is None:
                return None               # an index without local shards
            if str(index.index_settings.get(
                    "index.search.collective_plane", "true")).lower() \
                    in ("false", "0"):
                return None               # explicit opt-out
            indices.append(index)
        has_knn = any(req.knn is not None for req in reqs)
        if has_knn or self._impact_preferred(indices, reqs, search_type):
            # the planner owns the mesh-vs-lane routing that used to be
            # the pairwise impact-preferred / knn-lane decline edges: a
            # knn section ALWAYS routes to the vector lane (the mesh
            # program has no vector/fusion arms — silently dropping the
            # section would return lexical-only hits); an impact-
            # scorable batch on opted-in indices routes to the
            # quantized impact arm unless the cost observatory has
            # MEASURED the mesh strictly cheaper
            from elasticsearch_tpu.search import planner
            if planner.route_plane(indices, not has_knn,
                                   has_knn) is not None:
                return None
        owners = []                       # (index, local shard id)
        for index in indices:
            nshards = index.meta.number_of_shards
            if set(index.engines) != set(range(nshards)):
                self._note_plane_fallback(indices, "not-local")
                return None               # not every shard lives here
            owners.extend((index, sid) for sid in range(nshards))
        if len(owners) < 2:
            return None                   # single shard: nothing to merge
        if not any(e.acquire_searcher().segments
                   for index in indices for e in index.shard_engines):
            return None                   # nothing indexed yet: the
                                          # fan-out's empty response
        from elasticsearch_tpu.search import jit_exec
        # plane breaker: an unhealthy device costs fan-out latency, not
        # a failed mesh dispatch per query; a half-open probe is admitted
        # here and reports back through record_success/record_error below
        if not jit_exec.plane_breaker.allow():
            jit_exec.note_breaker_skip()
            self._note_plane_fallback(indices, "breaker-open")
            return None
        for req in reqs:
            if req.suggest or req.rescore:
                self._note_plane_fallback(indices, "ineligible-shape")
                return None
        if not all(self._plane_precheck(index, reqs)
                   for index in indices):
            # always-ineligible shape (_doc sort, sub-aggs, doc-id score
            # cursors, …): bail BEFORE the mesh build —
            # _mesh_searcher_for stacks every shard column into HBM, a
            # cost the RPC fallback should not pay per refresh generation
            self._note_plane_fallback(indices, "ineligible-shape")
            return None
        from elasticsearch_tpu.search.controller import merge_responses
        from elasticsearch_tpu.search.phase import (ShardQueryResult,
                                                    ShardSearcher)
        tasks.raise_if_cancelled()
        global_stats = search_type in ("dfs_query_then_fetch",
                                       "dfs_query_and_fetch")
        # A refresh between the mesh pack and the fetch readers would
        # make (slot, row) resolution disagree — both are immutable
        # point-in-time snapshots, so a generation comparison decides
        # validity once. On a race, retry ONCE against the fresh
        # snapshot (the pack was already built and breaker-charged;
        # throwing it away for the fan-out wastes that HBM), then yield.
        msearch = outs = searchers = None
        for attempt in (0, 1):
            try:
                msearch = self._mesh_searcher_for(indices)
            except QueryParsingError:     # vector/geo/nested layouts
                self._note_plane_fallback(indices, "ineligible-shape")
                return None
            except jit_exec.DeviceStallError as e:
                # a watchdog-abandoned wait surfacing through the pack:
                # distinct reason so the lane graph separates wedged
                # hardware from ordinary device faults
                jit_exec.note_fallback(e)
                jit_exec.note_device_error(e)
                self._note_plane_fallback(indices, "device-stall")
                return None
            except Exception as e:        # noqa: BLE001 — fallback seam
                jit_exec.note_fallback(e)
                jit_exec.note_device_error(e)
                self._note_plane_fallback(indices, "device-error")
                return None
            if any(r.terminate_after is not None for r in reqs) and \
                    msearch.n_slots > 1:
                # terminate_after over multi-segment shards diverges
                # from the fan-out's segment-prefix semantics — stay
                # exact, let the fan-out serve it
                self._note_plane_fallback(indices, "ineligible-shape")
                return None
            try:
                outs = msearch.search_batch(list(bodies),
                                            global_stats=global_stats)
            except QueryParsingError as e:
                # the mesh's own bails name the RPC path; anything else
                # is a body that failed the plane's re-parse
                self._note_plane_fallback(
                    indices, "ineligible-shape" if "RPC" in str(e)
                    else "parse-error")
                return None
            except TaskCancelledError:
                raise
            except jit_exec.DeviceStallError as e:
                jit_exec.note_fallback(e)
                jit_exec.note_device_error(e)
                self._note_plane_fallback(indices, "device-stall")
                return None
            except Exception as e:        # noqa: BLE001 — fallback seam
                jit_exec.note_fallback(e)
                jit_exec.note_device_error(e)
                self._note_plane_fallback(indices, "device-error")
                return None
            searchers = [
                ShardSearcher(sid, device_reader_for(index.engines[sid]),
                              index.mapper_service,
                              index_name=index.name,
                              version_fn=index.engines[sid].doc_version)
                for index, sid in owners]
            if all(s.reader.generation == msearch._views[si].generation
                   for si, s in enumerate(searchers)):
                break
            if attempt == 1:              # raced twice: fan-out path
                self._note_plane_fallback(indices, "refresh-race")
                return None
        index_names = [index.name for index, _ in owners]
        responses = []
        q_ms = (time.perf_counter() - t0) * 1e3
        for _ in bodies:
            obs_hist.observe_lane("plane", q_ms / len(bodies))
        for body, req, out in zip(bodies, reqs, outs):
            sort_vals = out.get("sort_values")
            per_shard: dict[int, list[tuple[int, float, list]]] = {}
            for pos, (g, sc) in enumerate(zip(out["doc_ids"],
                                              out["scores"])):
                si, j, row = msearch.resolve(int(g))
                rdoc = searchers[si].reader.segments[j].doc_base + row
                per_shard.setdefault(si, []).append(
                    (rdoc, float(sc),
                     sort_vals[pos] if sort_vals is not None else None))
            results = []
            ta = req.terminate_after
            for si, s in enumerate(searchers):
                rows = per_shard.get(si, [])
                # real per-shard totals from the program's all_gather
                # count lane; terminate_after caps them like the
                # fan-out's per-shard collection cap
                raw_total = int(out["shard_totals"][si])
                results.append(ShardQueryResult(
                    si,
                    raw_total if ta is None else min(raw_total, ta),
                    max((sc for _, sc, _ in rows), default=None),
                    np.asarray([d for d, _, _ in rows], np.int32),
                    np.asarray([sc for _, sc, _ in rows], np.float32),
                    [sv for _, _, sv in rows]
                    if sort_vals is not None else None,
                    {}, s.reader))
                if ta is not None and raw_total >= ta:
                    results[-1].terminated_early = True
            resp = merge_responses(index_names, req, results, searchers,
                                   (time.perf_counter() - t0) * 1e3, None)
            mesh_aggs = out.get("aggregations")
            if req.aggs and mesh_aggs is not None:
                resp["aggregations"] = mesh_aggs
            # elapsed-time truth: the request `timeout` and the task
            # deadline (PR-2 wiring) both bound the plane's one dispatch
            if req.timeout_ms is not None and \
                    (time.perf_counter() - t0) * 1e3 > req.timeout_ms:
                resp["timed_out"] = True
            cur = tasks.current_task()
            if cur is not None and cur.deadline is not None and \
                    time.monotonic() > cur.deadline:
                resp["timed_out"] = True
            responses.append(resp)
            # operators watch _stats/slow logs — the plane must feed
            # them like the fan-out does (one note per request per
            # index; per-shard granularity does not exist in a
            # one-program execution)
            for index in indices:
                index.note_search(body.get("stats"), q_ms / len(bodies))
                if index.search_slow_log.thresholds:
                    index.search_slow_log.maybe_log(
                        q_ms / 1e3 / len(bodies),
                        f"collective-plane, source"
                        f"[{json.dumps(body)[:512]}]")
        # a served plane batch is the breaker's success signal (closes a
        # half-open probe) and clears any plane-degraded marking left by
        # failed background builds
        jit_exec.plane_breaker.record_success()
        with self._plane_warm_lock:
            for index in indices:
                self._plane_warm_failures.pop(index.name, None)
        for index in indices:
            index.plane_stats.pop("degraded", None)
            index.note_plane_served(len(bodies))
        return responses

    @staticmethod
    def _impact_preferred(indices, reqs: list, search_type) -> bool:
        """Should this batch leave the mesh to the impact lane? Only
        when every index opted in (`index.search.impact_plane`), the
        search type is a plain (non-DFS) one — impacts bake shard-local
        idf — and every body resolves to an impact-scorable shape
        against every index's mappings (the same execute.impact_terms
        screen the shard-side admission applies)."""
        from elasticsearch_tpu.search import jit_exec
        from elasticsearch_tpu.search.execute import impact_terms
        from elasticsearch_tpu.search.phase import _is_score_order
        if search_type in ("dfs_query_then_fetch", "dfs_query_and_fetch"):
            return False
        cfgs = [jit_exec.impact_plane_config(index.name)
                for index in indices]
        if not all(cfgs):
            return False
        for req in reqs:
            if (req.aggs or not _is_score_order(req.sort)
                    or req.post_filter is not None
                    or req.min_score is not None or req.suggest
                    or req.terminate_after is not None
                    or req.timeout_ms is not None or req.rescore
                    or req.explain or req.knn is not None):
                return False
            if req.search_after is not None and \
                    len(req.search_after) not in (1, 2):
                return False              # only score-order cursors —
                                          # pagination must stay in the
                                          # quantized score domain
            for index, cfg in zip(indices, cfgs):
                if impact_terms(req.query, index.mapper_service,
                                max_terms=cfg.max_terms) is None:
                    return False
        return True

    @staticmethod
    def _plane_precheck(index, reqs: list) -> bool:
        """Mapping-only eligibility screen, run before committing to the
        mesh pack. Conservative: anything it cannot rule out passes
        through to the searcher's precise layout-based validation (which
        raises QueryParsingError → RPC fallback)."""
        from elasticsearch_tpu.parallel.mesh_engine import _MESH_METRICS
        from elasticsearch_tpu.search.phase import _is_score_order
        for req in reqs:
            if _is_score_order(req.sort):
                if req.search_after is not None and (
                        req.sort or len(req.search_after) != 1):
                    # a doc-id cursor component is numbering-relative
                    # (reader-local vs plane-local); an EXPLICIT _score
                    # sort makes the fan-out ignore the cursor — both
                    # stay host-side
                    return False
            else:
                for spec in req.sort:
                    (fname, opts), = spec.items()
                    if fname == "_doc":
                        return False
                    if fname == "_score":
                        continue
                    fm = index.mapper_service.field_mapper(fname)
                    if fm is not None and fm.type == "text":
                        return False      # analyzed text never sorts
                    if fm is not None and \
                            fm.type in ("keyword", "string") and \
                            opts.get("missing", "_last") not in \
                            ("_last", "_first"):
                        return False      # custom missing TERM: host
            for node in req.aggs:
                if node.subs or node.pipelines:
                    return False
                if node.type not in _MESH_METRICS + ("terms",
                                                     "histogram"):
                    return False
                if node.type == "terms":
                    fname = str(node.params.get("field", ""))
                    fm = index.mapper_service.field_mapper(fname)
                    if fm is not None and fm.type == "text":
                        return False      # analyzed-text terms
        return True

    def _plane_mesh_get(self):
        """One shared 1-device mesh for every plane pack on this node:
        re-using the SAME Mesh object keeps NamedSharding identity stable
        so shape-keyed programs re-dispatch without retracing."""
        mesh = getattr(self, "_plane_mesh", None)
        if mesh is None:
            import jax
            from elasticsearch_tpu.parallel import make_mesh
            mesh = make_mesh(dp=1, shard=1, devices=[jax.devices()[0]])
            self._plane_mesh = mesh      # benign race: equal meshes
        return mesh

    @staticmethod
    def _release_pack(entry) -> None:
        """Return a mesh pack's fielddata reservation (idempotent)."""
        if entry is None:
            return
        charge = getattr(entry[1], "_pack_charge", None)
        if charge is not None:
            charge.release()

    def _mesh_build(self, indices: list, cached):
        """DATA layer build: compose every index's shard columns into one
        MeshEngineSearcher → (gens, msearch, breaker bytes), reusing
        `cached` when no engine's reader generation moved. The build is
        INCREMENTAL: per-segment device blocks come from mesh_engine's
        module-level block cache (keyed engine uuid × block uid × slot
        layout), so a refresh re-uploads only new segments' columns and
        changed live masks, and the superseded pack keeps serving until
        this one swaps in (`prev` hands its unchanged stacked operands
        over). The stacked pack trades HBM for dispatch count —
        accounted against the fielddata breaker like every other HBM
        residency (device_reader_for does the same) via a one-shot
        charge that ALSO releases when any backing engine closes (shard
        relocation / teardown must not strand breaker budget); the
        blocks beneath it carry their own exact per-block charges.
        Compiled programs live in mesh_engine's module-level SHAPE-keyed
        cache, so a rebuild here re-dispatches them instead of
        re-tracing."""
        from elasticsearch_tpu.parallel.mesh_engine import (
            MeshEngineSearcher)
        engines, mappers, sinks = [], [], []
        for index in indices:
            sink = index.plane_stats.setdefault("data_layer", {})
            for sid in sorted(index.engines):
                engines.append(index.engines[sid])
                mappers.append(index.mapper_service)
                sinks.append(sink)
        gens = tuple(e.acquire_searcher().generation for e in engines)
        if cached is not None and cached[0] == gens:
            return cached[:3]
        prev = cached[1] if cached is not None else None
        self._release_pack(cached)       # superseded pack returns first
        bs = getattr(self.node, "breaker_service", None)
        new_bytes = sum(seg.memory_bytes() for e in engines
                        for seg in e.acquire_searcher().segments)
        reuse = all(
            str(index.index_settings.get(
                "index.search.plane_incremental", "true")).lower()
            not in ("false", "0") for index in indices)
        charge = _PackCharge(bs, new_bytes if bs is not None else 0,
                             component="pack",
                             index=",".join(index.name
                                            for index in indices))
        charge.charge(f"mesh plane "
                      f"[{','.join(index.name for index in indices)}]")
        try:
            msearch = MeshEngineSearcher(
                self._plane_mesh_get(), engines,
                indices[0].mapper_service, mapper_services=mappers,
                breaker_service=bs, prev=prev, reuse_blocks=reuse,
                stats_sinks=sinks)
        except BaseException:
            charge.release()
            raise
        msearch._pack_charge = charge
        for e in engines:
            lst = e.__dict__.setdefault("_close_listeners", [])
            # superseded packs' one-shots are spent — prune them so
            # long-lived engines don't accumulate dead callbacks
            lst[:] = [cb for cb in lst
                      if getattr(cb.__self__, "nbytes", 1)]
            lst.append(charge.release)
        return (gens, msearch, charge.nbytes)

    def _mesh_searcher_for(self, indices: list):
        """Per-generation DATA-layer cache (a refresh on any shard
        rebuilds — reader reacquisition semantics), built under a lock
        so concurrent searches cannot double-pack. Single-index packs
        live on the index object (released by IndexService.close);
        multi-index packs live in a small LRU here, validated against
        live index identity (a deleted/recreated index must not serve a
        stale pack) and breaker-released on eviction."""
        import threading
        if len(indices) == 1:
            index = indices[0]
            lock = index.__dict__.setdefault("_mesh_lock",
                                             threading.Lock())
            with lock:
                try:
                    entry = self._mesh_build(
                        indices, index.__dict__.get("_mesh_cache"))
                except BaseException:
                    # the superseded pack's charge was already released
                    # on the way into the failed build — drop the stale
                    # cache entry so a gens-matched retry can't serve a
                    # zero-charged pack (breaker-byte accounting drift)
                    index.__dict__["_mesh_cache"] = None
                    raise
                index.__dict__["_mesh_cache"] = entry
                return entry[1]
        key = tuple(index.name for index in indices)
        ids = tuple(id(index) for index in indices)
        with self._mesh_multi_lock:
            cached = self._mesh_multi.get(key)
            if cached is not None and cached[3] != ids:
                # an index was deleted/recreated under the same name:
                # the pack is stale, return its budget and rebuild
                self._release_pack(cached)
                del self._mesh_multi[key]
                cached = None
            try:
                entry = self._mesh_build(indices, cached)
            except BaseException:
                self._mesh_multi.pop(key, None)   # same staleness rule
                raise
            self._mesh_multi[key] = entry + (ids,)
            self._mesh_multi.move_to_end(key)
            while len(self._mesh_multi) > 4:
                _, old = self._mesh_multi.popitem(last=False)
                self._release_pack(old)
            return entry[1]

    def _shard_wait_s(self, deadline_at: float | None) -> float:
        """Every coordinator wait on a shard future is BOUNDED: the
        remaining request deadline (+ grace) when one exists, the stall
        ceiling otherwise — a wedged shard becomes a typed shard
        failure / partial result, never a hung request."""
        if deadline_at is None:
            return self.SHARD_WAIT_CEILING_S
        return min(self.SHARD_WAIT_CEILING_S,
                   max(deadline_at - time.perf_counter(), 0.0)
                   + self.PARTIAL_GRACE_S)

    def _dfs_phase(self, state, groups, body: dict,
                   deadline_at: float | None = None) -> dict:
        """The DFS round preceding the query round
        (executeDfsPhase, core/search/SearchService.java:264 +
        aggregateDfs SearchPhaseController.java:105): gather each shard's
        term/collection statistics, reduce to global stats."""
        from concurrent.futures import TimeoutError as FutTimeout
        from elasticsearch_tpu.search.dfs import aggregate_dfs
        futures = [self._submit(
            self._try_shard_action, state, n, s, copies, self.DFS,
            self._handle_shard_dfs, body) for n, s, copies in groups]
        results = []
        for fut in futures:
            try:
                status, payload = fut.result(
                    self._shard_wait_s(deadline_at))
            except FutTimeout:
                # a stalled dfs shard contributes no stats, exactly
                # like a failed one — its query round reports the
                # failure; the dfs wait must never wedge the request
                continue
            if status == "ok":
                results.append(payload)
            # a failed shard contributes no stats — its query round will
            # fail over / report the shard failure itself
        return aggregate_dfs(results)

    def _resolve_allow_partial(self, allow_partial) -> bool:
        """Request-level ``allow_partial_search_results`` overrides the
        node's ``search.default_allow_partial_results`` setting."""
        if allow_partial is None:
            return self.default_allow_partial
        return str(allow_partial).lower() not in ("false", "0")

    def _collect_shard_result(self, fut, name: str, sid: int,
                              deadline_at: float | None,
                              allow_partial: bool):
        """Collect one shard group's fan-out future. When partial
        results are allowed and the request deadline expires before the
        group responds, ABANDON it — deadline-bounded partial results:
        the group is accounted as a failed shard with a timed-out
        reason, and the response ships whatever completed. The
        abandoned shard work self-cancels: it carries the remaining
        budget as its task deadline."""
        from concurrent.futures import TimeoutError as FutTimeout
        if allow_partial and deadline_at is not None:
            wait = max(deadline_at - time.perf_counter(), 0.0) \
                + self.PARTIAL_GRACE_S
            try:
                return fut.result(wait)
            except FutTimeout:
                return "deadline", {
                    "shard": sid, "index": name,
                    "reason": {
                        "type": "timed_out_exception",
                        "reason": "shard group did not respond within "
                                  "the request timeout; partial results "
                                  "returned"},
                    "status": 504}, None
        # no deadline (or partial results disallowed — all-or-block
        # semantics wait out a merely-slow shard): still BOUNDED, by
        # the stall ceiling alone. A shard whose device dispatch
        # wedged must surface as a typed shard failure, never hold
        # the coordinator thread forever.
        try:
            return fut.result(self._shard_wait_s(None))
        except FutTimeout:
            return "stalled", {
                "shard": sid, "index": name,
                "reason": {
                    "type": "shard_stall_exception",
                    "reason": "shard group did not respond within the "
                              "coordinator stall ceiling; the wait was "
                              "abandoned (the shard task may still be "
                              "running)"},
                "status": 504}, None

    def _search_once(self, index_expr: str, body: dict, t0: float,
                     search_type: str | None = None,
                     dfs_cache: dict | None = None,
                     scroll_pin: dict | None = None,
                     routing: str | None = None,
                     preference: str | None = None,
                     allow_partial=None) -> dict:
        with obs_trace.span("parse"):
            names = self.node.indices_service.resolve_open(index_expr)
            body = rewrite_mlt_likes(self.node, body,
                                     names[0] if names else "_all")
            state = self.node.cluster_service.state()
            req = parse_search_request(body)
        groups = self._shard_groups(state, names, routing=routing,
                                    preference=preference)
        dfs = None
        if dfs_cache is None and scroll_pin is None and routing is None \
                and preference is None:
            # collective plane (DEFAULT-ON): when this node holds EVERY
            # shard of the target indices, an eligible search runs as
            # ONE shard_map program — per-shard emit, all_gather top-k
            # merge, psum counts, metric/bucket aggs — instead of the
            # per-shard fan-out + host merge (SURVEY §2.2: scatter/
            # gather + reduce onto ICI collectives). dfs types score
            # with global statistics (the plane's native mode); plain
            # searches score each shard with its own statistics,
            # bit-matching the fan-out. Routed/preference-restricted
            # searches skip it (the one-program fan-out always covers
            # EVERY shard; restricting the mesh would cost a recompile
            # per subset) and scroll pages need pinned readers the pack
            # does not provide.
            from elasticsearch_tpu.search import jit_exec
            with attribution.collect(admission="plane"), \
                    obs_trace.span("plane") as psp:
                mesh_resp = self._try_collective_plane(
                    names, [body], [req], t0, search_type=search_type)
                psp.set(served=mesh_resp is not None,
                        breaker=jit_exec.plane_breaker.state)
            if mesh_resp is not None:
                return mesh_resp[0]
        if search_type == "dfs_query_then_fetch":
            # scroll contexts reuse the stats gathered for page one: the
            # reference keeps AggregatedDfs in the search context — fresh
            # stats per page would cost S extra RPCs per page and could
            # shift scores across the search_after boundary mid-scroll
            if dfs_cache is not None and "wire" in dfs_cache:
                dfs = dfs_cache["wire"]
            else:
                dfs = self._dfs_phase(
                    state, groups, body,
                    deadline_at=None if req.timeout_ms is None
                    else t0 + req.timeout_ms / 1000.0)
                if dfs_cache is not None:
                    dfs_cache["wire"] = dfs
        # dense, deterministic _doc slots per (index, shard): sorted so a
        # scroll's later pages (same index set) assign identical slots
        slot_of = {(n, s): i for i, (n, s) in
                   enumerate(sorted((n, s) for n, s, _ in groups))}
        # True QUERY_THEN_FETCH (fillDocIdsToLoad + second fan-out,
        # SearchPhaseController.java:289, TransportSearchQueryThenFetch
        # Action.java:89-150) when the window is deep enough that shipping
        # every shard's full from+size `_source` payloads would dominate:
        # the query round moves only ids/scores, the fetch round touches
        # only the shards owning the global page. Shallow windows keep the
        # single-round QUERY_AND_FETCH model (module docstring) — the
        # extra round trip costs more than the surplus hit bytes.
        use_qtf = scroll_pin is None and len(groups) > 1 and (
            search_type in ("query_then_fetch", "dfs_query_then_fetch")
            or (search_type is None
                and req.from_ + req.size >= self.QTF_WINDOW_THRESHOLD))
        # the request's absolute deadline: shards get the REMAINING
        # budget at dispatch, so queue/fan-out time counts against the
        # timeout (wired through the task's deadline on the shard side)
        deadline_at = None if req.timeout_ms is None \
            else t0 + req.timeout_ms / 1000.0
        allow_partial = self._resolve_allow_partial(allow_partial)
        # hedging needs the freedom to pick the copy — an explicit
        # preference pinned placement, so it stays sequential
        allow_hedge = preference is None
        if use_qtf:
            return self._query_then_fetch(state, groups, body, req, t0,
                                          slot_of, dfs, deadline_at,
                                          allow_partial=allow_partial,
                                          allow_hedge=allow_hedge)
        q_t0 = time.perf_counter()
        payloads, failures = [], []
        with obs_trace.span("query", shards=len(groups)):
            futures = [self._submit(self._try_shard, state, n, s, copies,
                                    body, slot_of[(n, s)], dfs,
                                    scroll_pin, None, deadline_at,
                                    allow_hedge)
                       for n, s, copies in groups]
            for (n, s, _c), fut in zip(groups, futures):
                status, payload, _node = self._collect_shard_result(
                    fut, n, s, deadline_at, allow_partial)
                if status == "ok":
                    obs_trace.sink_shard_profile(
                        payload.pop("_profile", None))
                    payloads.append(payload)
                else:
                    failures.append(payload)
        q_ms = (time.perf_counter() - q_t0) * 1e3
        r_t0 = time.perf_counter()
        with obs_trace.span("reduce"):
            resp = merge_shard_payloads(
                req, payloads, (time.perf_counter() - t0) * 1e3,
                total_shards=len(groups), failures=failures)
        from elasticsearch_tpu.search.controller import attach_phase_took
        attach_phase_took(
            resp, {"query": q_ms,
                   "reduce": (time.perf_counter() - r_t0) * 1e3},
            tasks.current_task())
        obs_hist.observe_lane("fanout", (time.perf_counter() - t0) * 1e3)
        if deadline_at is not None and time.perf_counter() > deadline_at:
            # elapsed-time truth at the coordinator too: a request that
            # blew its budget in fan-out/queueing is timed out even if
            # no shard individually noticed (controller.py:104 only
            # aggregates per-shard flags)
            resp["timed_out"] = True
        return resp

    def _query_then_fetch(self, state, groups, body: dict, req, t0: float,
                          slot_of: dict, dfs: dict | None,
                          budget_deadline: float | None = None,
                          allow_partial: bool = False,
                          allow_hedge: bool = True) -> dict:
        """Two-round distributed search: query (descriptors only) →
        coordinator merge → winner-only fetch → assemble."""
        import uuid as _uuid
        from elasticsearch_tpu.search.controller import _hit_comparator
        pin = {"uid": _uuid.uuid4().hex, "keep_s": 30.0}
        q_t0 = time.perf_counter()
        qpayloads, failures = [], []   # (payload, node_id, name, sid, slot)
        with obs_trace.span("query", shards=len(groups)):
            futures = [self._submit(self._try_shard, state, n, s, copies,
                                    body, slot_of[(n, s)], dfs,
                                    None, pin, budget_deadline,
                                    allow_hedge)
                       for n, s, copies in groups]
            for (n, s, _), fut in zip(groups, futures):
                status, payload, node_id = self._collect_shard_result(
                    fut, n, s, budget_deadline, allow_partial)
                if status == "ok":
                    obs_trace.sink_shard_profile(
                        payload.pop("_profile", None))
                    qpayloads.append((payload, node_id, n, s,
                                      slot_of[(n, s)]))
                else:
                    failures.append(payload)
        q_ms = (time.perf_counter() - q_t0) * 1e3
        fetch_ms = 0.0
        try:
            # sortDocs over descriptors → the global [from, from+size)
            entries = []
            for si, (p, _, _, _, _) in enumerate(qpayloads):
                sort_vals = p.get("sort")
                for pos in range(len(p["docs"])):
                    entries.append((
                        sort_vals[pos] if sort_vals is not None else None,
                        p["scores"][pos], si, pos))
            keyfn = _hit_comparator(req)
            entries.sort(key=keyfn)
            page = entries[req.from_: req.from_ + req.size]
            # fillDocIdsToLoad → fetch ONLY from shards owning winners,
            # targeting the exact node whose reader is pinned
            by_shard: dict[int, list[int]] = {}
            for e in page:
                by_shard.setdefault(e[2], []).append(e[3])
            f_t0 = time.perf_counter()
            fetched: dict[tuple[int, int], dict] = {}
            fetch_failed: set[int] = set()
            with obs_trace.span("fetch", shards=len(by_shard)):
                fetch_futs = {}
                for si, positions in by_shard.items():
                    p, node_id, name, sid, slot = qpayloads[si]
                    request = {
                        "index": name, "shard": sid, "body": body,
                        "pin": pin, "doc_slot": slot,
                        "docs": [p["docs"][pos] for pos in positions],
                        "scores": [p["scores"][pos]
                                   for pos in positions],
                        "sort": ([p["sort"][pos] for pos in positions]
                                 if p.get("sort") is not None else None)}
                    if node_id == self.node.node_id:
                        fetch_futs[si] = self.node.thread_pool.submit(
                            "search", self._handle_shard_fetch, request,
                            None)
                    else:
                        target = state.node(node_id)
                        if target is None:
                            fetch_futs[si] = None
                            continue
                        fetch_futs[si] = self.node.transport_service.\
                            send_request(target, self.FETCH_ID, request,
                                         timeout=30.0)
                for si, positions in by_shard.items():
                    fut = fetch_futs.get(si)
                    try:
                        if fut is None:
                            raise ElasticsearchTpuError(
                                "fetch target node left the cluster")
                        wait = 35.0
                        if allow_partial and budget_deadline is not None:
                            # deadline-bounded fetch too: a browned-out
                            # pin holder must not stall the partial
                            # response past the deadline
                            wait = min(wait, max(
                                budget_deadline - time.perf_counter(),
                                0.0) + self.PARTIAL_GRACE_S)
                        payload_f = fut.result(wait)
                        obs_trace.sink_shard_profile(
                            payload_f.pop("_profile", None))
                        hits = payload_f["hits"]
                        for pos, hit in zip(positions, hits):
                            fetched[(si, pos)] = hit
                    except Exception as e:  # noqa: BLE001 — per-shard
                        fetch_failed.add(si)
                        _, _, name, sid, _ = qpayloads[si]
                        failures.append({
                            "shard": sid, "index": name,
                            "reason": {"type": "fetch_phase_failure",
                                       "reason": str(e)}})
            fetch_ms = (time.perf_counter() - f_t0) * 1e3
            hits_out = [fetched[(e[2], e[3])] for e in page
                        if (e[2], e[3]) in fetched]
        finally:
            self._free_context(pin["uid"],
                               [nid for _, nid, *_ in qpayloads])
        from elasticsearch_tpu.search.controller import (
            assemble_response, attach_phase_took)
        r_t0 = time.perf_counter()
        payloads = [p for p, *_ in qpayloads]
        with obs_trace.span("reduce"):
            resp = assemble_response(
                req, payloads, hits_out,
                (time.perf_counter() - t0) * 1e3,
                total_shards=len(groups), failures=failures,
                successful=len(qpayloads) - len(fetch_failed))
        attach_phase_took(
            resp, {"query": q_ms, "fetch": fetch_ms,
                   "reduce": (time.perf_counter() - r_t0) * 1e3},
            tasks.current_task())
        obs_hist.observe_lane("fanout", (time.perf_counter() - t0) * 1e3)
        if budget_deadline is not None and \
                time.perf_counter() > budget_deadline:
            resp["timed_out"] = True
        return resp

    def count(self, index_expr: str, body: dict | None = None,
              routing: str | None = None,
              preference: str | None = None) -> dict:
        resp = self.search(index_expr, {**(body or {}), "size": 0},
                           routing=routing, preference=preference)
        return {"count": resp["hits"]["total"],
                "_shards": resp["_shards"]}

    # ---- _msearch (ref: core/action/search/TransportMultiSearchAction) ----

    def multi_search(self, items: list) -> dict:
        """Execute B (index_expr, body[, search_type]) search items →
        {"responses": [...]}.

        Consecutive items on the SAME (index expression, search_type)
        batch into one shard fan-out carrying every body — each data node
        then runs the whole batch as one vmapped program when the plans
        align (the reference fans request-at-a-time; an accelerator wants
        the batch); dfs batches on an opted-in local index ride the
        collective plane as ONE mesh program. Per-item failures return an
        {"error": ...} entry (the _msearch contract), never failing the
        whole request.
        """
        items = [(it[0], it[1], it[2] if len(it) > 2 else None)
                 for it in items]
        responses: list[dict | None] = [None] * len(items)
        groups: list[tuple[str, str | None, list[int]]] = []
        for i, (index_expr, _body, stype) in enumerate(items):
            if groups and groups[-1][0] == index_expr \
                    and groups[-1][1] == stype:
                groups[-1][2].append(i)
            else:
                groups.append((index_expr, stype, [i]))
        with self._coordinating_task(
                "indices:data/read/msearch",
                f"requests[{len(items)}]"):
            futures = [self._msearch_pool.submit(
                tasks.bind_current(self._msearch_group), expr,
                [items[i][1] for i in idxs],
                stype) for expr, stype, idxs in groups]
            return self._collect_msearch(groups, futures, responses)

    def _collect_msearch(self, groups, futures, responses) -> dict:
        from concurrent.futures import TimeoutError as FutTimeout
        for (expr, stype, idxs), fut in zip(groups, futures):
            try:
                # BOUNDED backstop: every wait inside a group is itself
                # deadline/ceiling bounded, so 2x the shard stall
                # ceiling only fires if a group wedges outside those
                # bounds — the msearch then reports per-item stall
                # errors instead of hanging the whole multi-request
                outs = fut.result(2 * self.SHARD_WAIT_CEILING_S)
            except FutTimeout:
                cause = {"type": "shard_stall_exception",
                         "reason": "msearch group did not respond within "
                                   "the coordinator stall ceiling; the "
                                   "wait was abandoned"}
                outs = [{"error": {"root_cause": [cause], **cause}}] \
                    * len(idxs)
            except Exception as e:           # noqa: BLE001 — per-group error
                from elasticsearch_tpu.common.errors import (
                    ElasticsearchTpuError)
                if isinstance(e, ElasticsearchTpuError):
                    cause = e.to_xcontent()
                else:
                    cause = {"type": "search_phase_execution_exception",
                             "reason": str(e)}
                outs = [{"error": {"root_cause": [cause], **cause}}] \
                    * len(idxs)
            for i, out in zip(idxs, outs):
                responses[i] = out
        return {"responses": responses}

    def _msearch_group(self, index_expr: str, bodies: list[dict],
                       search_type: str | None = None) -> list[dict]:
        """One shard fan-out for a group of bodies on one index expr.
        Bodies are parsed ONCE here — invalid items answer immediately and
        never ship; per-item SHARD errors surface as that item's shard
        failures (partial results stay visible as partial)."""
        t0 = time.perf_counter()
        names = self.node.indices_service.resolve_open(index_expr)
        bodies = [rewrite_mlt_likes(self.node, b,
                                    names[0] if names else "_all")
                  for b in bodies]
        outs: list[dict | None] = [None] * len(bodies)
        parsed: dict[int, object] = {}
        for i, body in enumerate(bodies):
            try:
                parsed[i] = parse_search_request(body)
            except Exception as e:           # noqa: BLE001 — per-item error
                outs[i] = {"error": {"type": "parsing_exception",
                                     "reason": str(e)}}
        valid = sorted(parsed)
        if not valid:
            return [o for o in outs]
        send_bodies = [bodies[i] for i in valid]
        if search_type in self.PLANE_SEARCH_TYPES:
            # an msearch group is the collective plane's natural batch:
            # ONE mesh program scores every item — global statistics for
            # dfs groups, per-shard statistics otherwise — and a group
            # whose expression spans several indices still packs into
            # the same single dispatch; fallback runs the items through
            # the ordinary paths
            with attribution.collect(admission="plane"), \
                    obs_trace.span("plane", batch=len(send_bodies)):
                mesh_outs = self._try_collective_plane(
                    names, send_bodies, [parsed[i] for i in valid], t0,
                    search_type=search_type)
            if mesh_outs is not None:
                for i, r in zip(valid, mesh_outs):
                    outs[i] = r
                return [o for o in outs]
        if search_type in ("dfs_query_then_fetch", "dfs_query_and_fetch"):
            # per-item dfs fallback, concurrently. A transient pool (not
            # _pool/_msearch_pool) because this frame already RUNS on
            # _msearch_pool and _search_once fans shards onto _pool —
            # same-pool nesting deadlocks under saturation
            from concurrent.futures import ThreadPoolExecutor as _TPE
            from concurrent.futures import TimeoutError as FutTimeout
            pool = _TPE(max_workers=min(len(valid), 4))
            try:
                futs = {i: pool.submit(
                    tasks.bind_current(self._search_once), index_expr,
                    bodies[i], t0, "dfs_query_then_fetch")
                        for i in valid}
                for i in valid:
                    try:
                        outs[i] = futs[i].result(
                            2 * self.SHARD_WAIT_CEILING_S)
                    except FutTimeout:
                        futs[i].cancel()
                        outs[i] = {"error": {
                            "type": "shard_stall_exception",
                            "reason": "dfs msearch item did not respond "
                                      "within the coordinator stall "
                                      "ceiling; the wait was abandoned"}}
            finally:
                # NOT wait=True: joining a wedged worker here would
                # re-introduce the unbounded wait this path just shed —
                # queued items are cancelled, running ones are
                # deadline/ceiling bounded and the pool threads exit
                # on their own when those bounds fire
                pool.shutdown(wait=False, cancel_futures=True)
            return [o for o in outs]
        state = self.node.cluster_service.state()
        groups = self._shard_groups(state, names)
        slot_of = {(n, s): i for i, (n, s) in
                   enumerate(sorted((n, s) for n, s, _ in groups))}
        futures = [self._submit(
            self._try_shard_action, state, n, s, copies, self.MSEARCH_SHARD,
            self._handle_shard_msearch, None,
            {"bodies": send_bodies, "doc_slot": slot_of[(n, s)]})
            for n, s, copies in groups]
        per_shard, group_failures = [], []
        from concurrent.futures import TimeoutError as FutTimeout
        for (n, s, _copies), fut in zip(groups, futures):
            try:
                status, payload = fut.result(self._shard_wait_s(None))
            except FutTimeout:
                status, payload = "stalled", {
                    "shard": s, "index": n,
                    "reason": {
                        "type": "shard_stall_exception",
                        "reason": "msearch shard group did not respond "
                                  "within the coordinator stall ceiling; "
                                  "the wait was abandoned"},
                    "status": 504}
            if status == "ok":
                per_shard.append((n, s, payload["payloads"]))
            else:
                group_failures.append(payload)
        took = (time.perf_counter() - t0) * 1e3
        for pos, i in enumerate(valid):
            item_payloads = []
            item_failures = list(group_failures)
            for n, s, shard_payloads in per_shard:
                p = shard_payloads[pos]
                if "error" in p:
                    # same shape as group-level shard failures
                    item_failures.append({"shard": s, "index": n,
                                          "reason": {
                                              "type": "shard_search_failure",
                                              "reason": p["error"]}})
                else:
                    item_payloads.append(p)
            if not item_payloads and item_failures:
                # every shard failed for this item: an error entry, not a
                # legitimate-looking empty result (the _msearch contract)
                outs[i] = {"error": {
                    "type": "search_phase_execution_exception",
                    "reason": "all shards failed",
                    "failed_shards": item_failures}}
                continue
            outs[i] = merge_shard_payloads(
                parsed[i], item_payloads, took, total_shards=len(groups),
                failures=item_failures)
        return [o for o in outs]

    # ---- field stats (core/action/fieldstats/TransportFieldStatsAction) ----

    def field_stats(self, index_expr: str, fields: list[str],
                    level: str = "cluster",
                    index_constraints: dict | None = None) -> dict:
        """Per-field min/max/doc-count over one copy of every shard,
        reduced cluster-wide or per index (the 2.x _field_stats API
        `level` param)."""
        names = self.node.indices_service.resolve_open(index_expr)
        state = self.node.cluster_service.state()
        groups = self._shard_groups(state, names)
        fetch = list(fields)
        for f in (index_constraints or {}):
            if f not in fetch:
                fetch.append(f)
        body = {"fields": fetch}
        futures = [self._submit(
            self._try_shard_action, state, n, s, copies, self.FIELD_STATS,
            self._handle_field_stats, body) for n, s, copies in groups]
        buckets: dict[str, dict[str, dict]] = {}
        ok = failed = 0

        def fold(merged: dict, payload: dict) -> None:
            for f, st in payload["fields"].items():
                cur = merged.get(f)
                if cur is None:
                    merged[f] = dict(st)
                    continue
                cur["doc_count"] += st["doc_count"]
                cur["max_doc"] += st["max_doc"]
                for k, pick in (("min_value", min), ("max_value", max)):
                    if st.get(k) is None:
                        continue
                    if cur.get(k) is None:
                        cur[k] = st[k]
                    elif isinstance(st[k], str) != isinstance(cur[k], str):
                        # same field name mapped to different types across
                        # indices (numeric vs text) — the values are not
                        # comparable; flag instead of crashing (the
                        # reference reports per-field conflicts)
                        cur[k] = None
                        cur["type_conflict"] = True
                    else:
                        cur[k] = pick(cur[k], st[k])
        from concurrent.futures import TimeoutError as FutTimeout
        for (n, _s, _c), fut in zip(groups, futures):
            try:
                status, payload = fut.result(self._shard_wait_s(None))
            except FutTimeout:
                # a stalled field-stats shard counts as failed — the
                # reduce ships whatever responded inside the ceiling
                failed += 1
                continue
            if status != "ok":
                failed += 1
                continue
            ok += 1
            key = n if level == "indices" else "_all"
            fold(buckets.setdefault(key, {}), payload)
        for merged in buckets.values():
            for st in merged.values():
                st["density"] = int(100 * st["doc_count"] /
                                    max(st["max_doc"], 1))
        if index_constraints:
            # drop indices whose constrained field stats miss the bounds
            # (FieldStatsRequest indexConstraints)
            def meets(merged: dict) -> bool:
                for f, spec in index_constraints.items():
                    st = merged.get(f)
                    if st is None:
                        return False
                    for prop, bounds in spec.items():
                        val = st.get(prop)
                        if val is None:
                            return False
                        for op, want in bounds.items():
                            try:
                                if isinstance(val, str):
                                    w = str(want)
                                else:
                                    try:
                                        w = type(val)(want)
                                    except (TypeError, ValueError):
                                        # date-string constraint against a
                                        # millis-valued field
                                        from elasticsearch_tpu.mapping \
                                            .mapper import parse_date
                                        w = type(val)(parse_date(want))
                            except Exception:  # noqa: BLE001 — no compare
                                return False
                            if op == "gte" and not val >= w:
                                return False
                            if op == "gt" and not val > w:
                                return False
                            if op == "lte" and not val <= w:
                                return False
                            if op == "lt" and not val < w:
                                return False
                return True
            buckets = {k: v for k, v in buckets.items() if meets(v)}
            want_fields = set(fields)
            buckets = {k: {f: st for f, st in v.items()
                           if f in want_fields}
                       for k, v in buckets.items()}
        return {"_shards": {"total": len(groups), "successful": ok,
                            "failed": failed},
                "indices": {k: {"fields": v} for k, v in buckets.items()}}

    def _try_shard_action(self, state, name, sid, copies, action,
                          local_handler, body, extra: dict | None = None):
        """Copy-failover for non-search per-shard actions."""
        from elasticsearch_tpu.action.replication import unwrap_remote
        last = None
        for c in copies:
            try:
                request = {"index": name, "shard": sid, "body": body,
                           **(extra or {})}
                if c.node_id == self.node.node_id:
                    # same bounded-search-pool dispatch as _try_shard:
                    # local msearch/DFS/field_stats work must not bypass
                    # the backpressure the remote path gets
                    fut = self.node.thread_pool.submit(
                        "search", local_handler, request, None)
                    try:
                        return "ok", fut.result(35.0)
                    except Exception:
                        fut.cancel()
                        raise
                target = state.node(c.node_id)
                if target is None:
                    continue
                return "ok", self.node.transport_service.send_request(
                    target, action, request, timeout=30.0).result(35.0)
            except Exception as e:               # noqa: BLE001 — failover
                last = unwrap_remote(e)
        return "fail", {"shard": sid, "index": name, "reason": str(last)}

    def _handle_field_stats(self, request: dict, source) -> dict:
        import numpy as np
        name, shard = request["index"], request["shard"]
        fields = (request.get("body") or {}).get("fields") or []
        svc = self.node.indices_service.index(name)
        engine = svc.engine(shard)
        reader = device_reader_for(engine)
        out: dict[str, dict] = {}
        max_doc = reader.num_docs
        for f in fields:
            doc_count = 0
            min_v = max_v = None
            for s in reader.segments:
                live = np.asarray(s.live)
                ncol = s.seg.numeric_fields.get(f)
                if ncol is not None:
                    exists = np.asarray(ncol.exists)[:live.shape[0]] & live
                    doc_count += int(exists.sum())
                    if exists.any():
                        vals = np.asarray(ncol.values)[:live.shape[0]][exists]
                        lo, hi = float(vals.min()), float(vals.max())
                        min_v = lo if min_v is None else min(min_v, lo)
                        max_v = hi if max_v is None else max(max_v, hi)
                    continue
                all_live = bool(live.all())
                tcol = s.seg.text_fields.get(f)
                if tcol is not None:
                    uterms = np.asarray(tcol.uterms)[:live.shape[0]]
                    has = (uterms >= 0).any(axis=1)
                    doc_count += int((has & live).sum())
                    # min/max over terms with >=1 LIVE posting only —
                    # terms surviving solely in deleted docs must not
                    # skew the bounds. No-deletes fast path: the sorted
                    # dictionary endpoints are already exact.
                    if all_live:
                        bounds = (tcol.terms[0], tcol.terms[-1]) \
                            if tcol.terms else None
                    else:
                        live_tids = np.unique(uterms[live])
                        live_tids = live_tids[live_tids >= 0]
                        bounds = (tcol.terms[int(live_tids[0])],
                                  tcol.terms[int(live_tids[-1])]) \
                            if live_tids.size else None
                    if bounds:
                        min_v = bounds[0] if min_v is None \
                            else min(min_v, bounds[0])
                        max_v = bounds[1] if max_v is None \
                            else max(max_v, bounds[1])
                    continue
                kcol = s.seg.keyword_fields.get(f)
                if kcol is not None:
                    ords = np.asarray(kcol.ords)[:live.shape[0]]
                    has = (ords >= 0).any(axis=1)
                    doc_count += int((has & live).sum())
                    if all_live:
                        bounds = (kcol.vocab[0], kcol.vocab[-1]) \
                            if kcol.vocab else None
                    else:
                        live_ords = np.unique(ords[live])
                        live_ords = live_ords[live_ords >= 0]
                        bounds = (kcol.vocab[int(live_ords[0])],
                                  kcol.vocab[int(live_ords[-1])]) \
                            if live_ords.size else None
                    if bounds:
                        min_v = bounds[0] if min_v is None \
                            else min(min_v, bounds[0])
                        max_v = bounds[1] if max_v is None \
                            else max(max_v, bounds[1])
            if doc_count:
                out[f] = {"max_doc": max_doc, "doc_count": doc_count,
                          "min_value": min_v, "max_value": max_v}
        return {"fields": out}

    def _pinned_reader(self, scroll_pin: dict, name: str, shard: int,
                       engine):
        """Point-in-time reader for a scroll context: the FIRST page pins
        the shard's current SearcherView (segments are immutable, the view
        object keeps them alive); later pages reuse it regardless of
        refreshes — ScrollContext semantics (SearchService.java:533-558).
        Views expire with the scroll keep-alive."""
        from elasticsearch_tpu.index.device_reader import DeviceReader
        key = (scroll_pin["uid"], name, shard)
        now = time.monotonic()
        with self._lock:
            # lazy sweep of expired pins
            dead = [k for k, (_, _, exp) in self._pinned.items()
                    if exp < now]
            for k in dead:
                del self._pinned[k]
            hit = self._pinned.get(key)
            if hit is not None:
                view, reader, _ = hit
                self._pinned[key] = (view, reader,
                                     now + scroll_pin["keep_s"])
                return reader
        if scroll_pin.get("require"):
            # a fetch round arriving after its query-round pin expired
            # MUST fail: re-pinning the current view would resolve the
            # shipped reader-local doc ids against a different point in
            # time and silently return the wrong documents
            raise SearchContextMissingError(
                f"no pinned context [{scroll_pin['uid']}] for "
                f"[{name}][{shard}]")
        view = engine.acquire_searcher()
        reader = device_reader_for(engine, view)
        if reader.generation != view.generation:
            reader = DeviceReader(view)
        with self._lock:
            self._pinned[key] = (view, reader, now + scroll_pin["keep_s"])
        return reader

    def _drop_pins(self, uid: str) -> None:
        with self._lock:
            for k in [k for k in self._pinned if k[0] == uid]:
                del self._pinned[k]

    # ---- scroll ------------------------------------------------------------

    @staticmethod
    def _scroll_sort(sort) -> list:
        """Scroll pages continue via search_after, which needs a total
        order: append a `_doc` tie-break."""
        if not sort:
            sort = [{"_score": {"order": "desc"}}]
        elif isinstance(sort, (str, dict)):
            sort = [sort]
        else:
            sort = list(sort)
        if not any((s == "_doc") or (isinstance(s, dict) and "_doc" in s)
                   for s in sort):
            sort = sort + [{"_doc": {"order": "asc"}}]
        return sort

    def _open_scroll(self, index_expr: str, body: dict, scroll: str,
                     first_page: dict, search_type: str | None = None,
                     dfs_cache: dict | None = None,
                     ctx_uid: str | None = None,
                     routing: str | None = None,
                     preference: str | None = None) -> str:
        keep = parse_time_value(scroll, "scroll")
        ctx = _ScrollContext(index_expr, body, keep, search_type=search_type,
                             ctx_uid=ctx_uid)
        ctx.routing = routing
        ctx.preference = preference
        ctx.dfs_cache = dfs_cache if dfs_cache is not None else {}
        self._note_page(ctx, first_page)
        with self._lock:
            cid = f"ctx{next(self._ctx_ids)}"
            self._contexts[cid] = ctx
        return base64.b64encode(json.dumps({"id": cid}).encode()).decode()

    @staticmethod
    def _note_page(ctx: _ScrollContext, page: dict):
        hits = page["hits"]["hits"]
        if not hits:
            ctx.finished = True
            return
        ctx.last_sort_key = hits[-1].get("sort")

    def scroll(self, scroll_id: str, scroll: str | None = None) -> dict:
        with self._coordinating_task("indices:data/read/scroll",
                                     "scroll page") as task:
            resp = self._scroll_page(scroll_id, scroll)
            if task is not None and task.cancelled:
                resp["cancelled"] = True
            return resp

    def _scroll_page(self, scroll_id: str,
                     scroll: str | None = None) -> dict:
        try:
            cid = json.loads(base64.b64decode(scroll_id))["id"]
        except Exception:                        # noqa: BLE001 — bad id
            raise SearchContextMissingError(
                f"invalid scroll id [{scroll_id}]") from None
        with self._lock:
            ctx = self._contexts.get(cid)
        if ctx is None or ctx.expires_at < time.monotonic():
            with self._lock:
                self._contexts.pop(cid, None)
            raise SearchContextMissingError(f"No search context found for "
                                            f"id [{cid}]")
        ctx.touch(parse_time_value(scroll, "scroll")
                  if scroll is not None else None)
        if ctx.finished:
            resp = {"took": 0, "timed_out": False,
                    "_shards": {"total": 0, "successful": 0, "failed": 0},
                    "hits": {"total": 0,
                             "max_score": None, "hits": []}}
            resp["_scroll_id"] = scroll_id
            return resp
        body = dict(ctx.body)
        body["from"] = 0
        if ctx.last_sort_key is not None:
            body["search_after"] = ctx.last_sort_key
        resp = self._search_once(ctx.index_expr, body, time.perf_counter(),
                                 search_type=ctx.search_type,
                                 dfs_cache=ctx.dfs_cache,
                                 scroll_pin={"uid": ctx.ctx_uid,
                                             "keep_s": ctx.keep_alive_s},
                                 routing=ctx.routing,
                                 preference=ctx.preference)
        self._note_page(ctx, resp)
        resp["_scroll_id"] = scroll_id
        return resp

    def clear_scroll(self, scroll_id: str | None) -> int:
        with self._lock:
            if scroll_id is None:
                n = len(self._contexts)
                self._contexts.clear()
                self._pinned.clear()     # free pinned readers with them
                return n
            try:
                cid = json.loads(base64.b64decode(scroll_id))["id"]
            except Exception:                    # noqa: BLE001 — bad id
                return 0
            ctx = self._contexts.pop(cid, None)
        if ctx is not None:
            # local pins die now; REMOTE nodes' pins age out with the
            # keep-alive (a clear RPC would tighten this cluster-wide)
            self._drop_pins(ctx.ctx_uid)
            return 1
        return 0

    def reap_expired(self) -> int:
        now = time.monotonic()
        with self._lock:
            dead = [k for k, c in self._contexts.items()
                    if c.expires_at < now]
            for k in dead:
                del self._contexts[k]
            # expired reader pins release their device-resident views here
            # too — lazy sweeping inside _pinned_reader alone would leak
            # them on nodes that never serve another pinned search
            for k in [k for k, (_, _, exp) in self._pinned.items()
                      if exp < now]:
                del self._pinned[k]
        return len(dead)

    def active_contexts(self) -> int:
        with self._lock:
            return len(self._contexts)
