"""SearchPhaseController — cross-shard reduce at the coordinator.

Reference: core/search/controller/SearchPhaseController.java —
``sortDocs`` (:165, TopDocs.merge semantics), ``fillDocIdsToLoad`` (:289),
final ``merge`` (:300-431) assembling hits + reducing aggregations.

Shard results arrive as host arrays (k entries per shard); the merge is a
numpy stable sort in shard order, reproducing the (score desc, shard index,
position) merge order of the reference.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from elasticsearch_tpu.search.aggregations import reduce_aggs
from elasticsearch_tpu.search.phase import ParsedSearchRequest, ShardQueryResult


@dataclass
class MergedHitRef:
    shard_idx: int      # position in the results list
    position: int       # hit position within that shard's result
    score: float | None
    sort_values: list | None


def sort_docs(results: list[ShardQueryResult],
              req: ParsedSearchRequest) -> list[MergedHitRef]:
    """Merge per-shard rankings → global [from, from+size) slice."""
    refs: list[MergedHitRef] = []
    for si, r in enumerate(results):
        for pos in range(len(r.doc_ids)):
            refs.append(MergedHitRef(
                shard_idx=si, position=pos,
                score=float(r.scores[pos]) if r.sort_values is None else None,
                sort_values=r.sort_values[pos] if r.sort_values is not None
                else None))
    if not refs:
        return []
    keyfn = _hit_comparator(req)
    refs.sort(key=lambda r: keyfn((r.sort_values, r.score, r.shard_idx,
                                   r.position)))
    return refs[req.from_: req.from_ + req.size]


def _hit_comparator(req: ParsedSearchRequest):
    """Ordering over (sort_values | score, shard_idx, position) tuples —
    shared by the in-process and the serialized (distributed) merges."""
    import functools
    orders = [(list(spec.values())[0].get("order", "asc")) == "desc"
              for spec in req.sort]
    missing_first = [(list(spec.values())[0].get("missing", "_last"))
                     == "_first" for spec in req.sort]

    def cmp_entries(a, b) -> int:
        # entry: (sort_values|None, score|None, shard_idx, position)
        if a[0] is not None:
            for va, vb, desc, mfirst in zip(a[0], b[0], orders,
                                            missing_first):
                if va == vb:
                    continue
                if va is None:
                    return -1 if mfirst else 1
                if vb is None:
                    return 1 if mfirst else -1
                if isinstance(va, str) or isinstance(vb, str):
                    va, vb = str(va), str(vb)
                c = 1 if va > vb else -1
                return -c if desc else c
            return -1 if (a[2], a[3]) < (b[2], b[3]) else 1
        sa = a[1] if a[1] is not None else -np.inf
        sb = b[1] if b[1] is not None else -np.inf
        if sa != sb:
            return -1 if sa > sb else 1
        return -1 if (a[2], a[3]) < (b[2], b[3]) else 1

    return functools.cmp_to_key(cmp_entries)


def attach_phase_took(response: dict, phases: dict, task=None) -> dict:
    """Surface the coordinator's phase trace ({"query": ms, "fetch": ms,
    "reduce": ms}) as the response's ``took`` breakdown and record the
    spans on the coordinating task (the per-request twin of the
    nodes-stats phase rollup)."""
    response["took_breakdown"] = {k: int(v) for k, v in phases.items()}
    if task is not None:
        for name, ms in phases.items():
            task.add_span(name, ms)
    return response


def assemble_response(req: ParsedSearchRequest, payloads: list[dict],
                      hits_out: list[dict], took_ms: float,
                      total_shards: int, failures: list[dict],
                      successful: int | None = None) -> dict:
    """Final response assembly shared by both distributed execution
    models (SearchPhaseController.merge :300-431): totals, max_score
    gating, shard accounting, agg/suggest reduction — over pre-merged
    page hits."""
    total = sum(p["total"] for p in payloads)
    max_scores = [p["max_score"] for p in payloads
                  if p.get("max_score") is not None]
    max_score = max(max_scores) if max_scores and req.size > 0 \
        and not req.sort else None
    shards = {"total": total_shards,
              "successful": len(payloads) if successful is None
              else successful,
              "skipped": 0, "failed": len(failures)}
    if failures:
        shards["failures"] = failures
    response = {
        "took": int(took_ms),
        "timed_out": any(p.get("timed_out") for p in payloads),
        "_shards": shards,
        "hits": {
            "total": total,
            "max_score": max_score,
            "hits": hits_out,
        },
    }
    if any(p.get("terminated_early") for p in payloads):
        response["terminated_early"] = True
    if req.aggs:
        response["aggregations"] = reduce_aggs(
            req.aggs, [p["aggs"] for p in payloads])
    if req.suggest:
        from elasticsearch_tpu.search.suggest import reduce_suggest
        response["suggest"] = reduce_suggest(
            req.suggest, [p.get("suggest", {}) for p in payloads])
    return response


def merge_shard_payloads(req: ParsedSearchRequest, payloads: list[dict],
                         took_ms: float, total_shards: int,
                         failures: list[dict]) -> dict:
    """Reduce serialized per-shard query+fetch payloads
    ({total, max_score, hits, aggs}) arriving over the transport — the
    distributed twin of :func:`merge_responses`
    (SearchPhaseController.merge :300-431)."""
    entries = []
    for si, p in enumerate(payloads):
        for pos, hit in enumerate(p["hits"]):
            entries.append((hit.get("sort") if req.sort else None,
                            hit.get("_score"), si, pos, hit))
    keyfn = _hit_comparator(req)
    entries.sort(key=lambda e: keyfn((e[0], e[1], e[2], e[3])))
    page = entries[req.from_: req.from_ + req.size]
    return assemble_response(req, payloads, [e[4] for e in page], took_ms,
                             total_shards, failures)


def merge_responses(index_name: str | list, req: ParsedSearchRequest,
                    results: list[ShardQueryResult], searchers,
                    took_ms: float, agg_nodes) -> dict:
    """`index_name` is one name, or one name PER SEARCHER — the
    collective plane's multi-index batches merge shards of several
    indices in one result list and each hit must render its owner."""
    names = list(index_name) if isinstance(index_name, (list, tuple)) \
        else [index_name] * len(searchers)
    page = sort_docs(results, req)
    # fetch phase only on shards owning winning docs (fillDocIdsToLoad)
    by_shard: dict[int, list[int]] = {}
    for ref in page:
        by_shard.setdefault(ref.shard_idx, []).append(ref.position)
    fetched: dict[tuple[int, int], dict] = {}
    for si, positions in by_shard.items():
        hits = searchers[si].fetch_phase(req, results[si], names[si],
                                         positions)
        for pos, hit in zip(positions, hits):
            fetched[(si, pos)] = hit
    hits_out = [fetched[(ref.shard_idx, ref.position)] for ref in page]

    total = sum(r.total for r in results)
    max_scores = [r.max_score for r in results if r.max_score is not None]
    max_score = max(max_scores) if max_scores and req.size > 0 and not req.sort \
        else None

    response = {
        "took": int(took_ms),
        "timed_out": any(r.timed_out for r in results),
        "_shards": {"total": len(results), "successful": len(results),
                    "skipped": 0, "failed": 0},
        "hits": {
            "total": total,
            "max_score": max_score,
            "hits": hits_out,
        },
    }
    if any(r.terminated_early for r in results):
        response["terminated_early"] = True
    if agg_nodes:
        response["aggregations"] = reduce_aggs(
            agg_nodes, [r.agg_partials for r in results])
    return response
