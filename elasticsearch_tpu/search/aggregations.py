"""Aggregations: parse → per-shard collect → cross-shard reduce → render.

Reference: the aggregation framework (core/search/aggregations/, 335 files):
Aggregator collector trees per segment, `InternalAggregation.reduce`
(InternalAggregations.java:133) merging shard partials at the coordinator.

Here a shard's collect phase consumes the **device-computed query mask**
(one [N] bool transfer per shard) and reduces over the columnar doc values
with vectorized numpy; partials are plain dicts merged by the same `reduce`
tree the coordinator applies across shards (segment→shard→global, SURVEY.md
§2.10 "aggregation tree reduce"). The dense-kernel equivalents live in
ops/aggs_ops.py and take over on-device for the hot aggs as a perf pass.

Supported: terms, significant_terms, histogram, date_histogram (fixed +
calendar intervals), range, date_range, filter, filters, global, missing,
sampler, nested, reverse_nested, children, geohash_grid, geo_distance
(bucket); min/max/sum/avg/stats/extended_stats/value_count/cardinality/
percentiles/percentile_ranks/top_hits/geo_bounds/geo_centroid/
scripted_metric (metrics); avg_bucket/max_bucket/min_bucket/sum_bucket/
cumulative_sum/derivative/moving_avg/serial_diff/bucket_script/
bucket_selector (pipeline).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from elasticsearch_tpu.common.errors import QueryParsingError
from elasticsearch_tpu.common.settings import parse_time_value
from elasticsearch_tpu.mapping.mapper import parse_date

BUCKET_AGGS = {"terms", "histogram", "date_histogram", "range", "date_range",
               "filter", "filters", "global", "missing",
               "significant_terms", "sampler", "nested", "reverse_nested",
               "children", "geohash_grid", "geo_distance"}
METRIC_AGGS = {"min", "max", "sum", "avg", "stats", "extended_stats",
               "value_count", "cardinality", "percentiles",
               "percentile_ranks", "top_hits", "geo_bounds",
               "geo_centroid", "scripted_metric"}
PIPELINE_AGGS = {"avg_bucket", "max_bucket", "min_bucket", "sum_bucket",
                 "cumulative_sum", "derivative", "moving_avg",
                 "serial_diff", "bucket_script", "bucket_selector"}

_CALENDAR = {"year": "Y", "1y": "Y", "quarter": "Q", "1q": "Q",
             "month": "M", "1M": "M", "week": "W", "1w": "W"}


def _java_decimal_format(value, pattern: str) -> str:
    """Minimal Java DecimalFormat rendering for histogram `format`
    (ref: ValueFormatter.Number.Pattern): literal prefix/suffix around a
    #/0 digit pattern; the count of '0's after '.' fixes the decimals."""
    import re as _re
    m = _re.search(r"[#0][#0,]*(?:\.([0#]+))?", pattern)
    if m is None:
        return str(value)
    decimals = len(m.group(1)) if m.group(1) else 0
    num = f"{float(value):.{decimals}f}" if decimals else \
        str(int(round(float(value))))
    return pattern[:m.start()] + num + pattern[m.end():]


@dataclass
class AggNode:
    name: str
    type: str
    params: dict
    subs: list["AggNode"] = field(default_factory=list)
    pipelines: list["AggNode"] = field(default_factory=list)


def parse_aggs(body: dict | None) -> list[AggNode]:
    out: list[AggNode] = []
    if not body:
        return out
    for name, spec in body.items():
        sub_specs = spec.get("aggs", spec.get("aggregations")) or {}
        atype = None
        params: dict = {}
        for key, val in spec.items():
            if key in ("aggs", "aggregations", "meta"):
                continue
            atype, params = key, val
        if atype is None:
            raise QueryParsingError(f"aggregation [{name}] missing type")
        node = AggNode(name=name, type=atype, params=params or {})
        for sub in parse_aggs(sub_specs):
            (node.pipelines if sub.type in PIPELINE_AGGS else node.subs).append(sub)
        out.append(node)
    return out


# ---------------------------------------------------------------------------
# device collect fast path (ops/aggs_ops kernels)
# ---------------------------------------------------------------------------

# observability for tests/ops: how collection executed
DEVICE_AGG_STATS = {"device_collects": 0, "host_fallbacks": 0}


class DeviceAggState:
    """Per-segment DEVICE query masks (+ scores) for aggregation collection.

    The device fast path (collect_device) reduces on the accelerator and
    fetches only bucket-/scalar-sized results; nodes it can't serve fall
    back to the numpy collectors, which need the full masks on host —
    ``np_mask()`` materializes them lazily and counts doing so, so tests
    can assert the device path never transfers full columns."""

    def __init__(self, reader, masks_dev: list, scores_dev: list):
        self.reader = reader
        self.masks = masks_dev            # per segment [Np] bool (device)
        self.scores_dev = scores_dev      # per segment [Np] f32 (device)
        self.host_materializations = 0
        self._np_mask = None
        self._np_scores = None

    def np_mask(self) -> np.ndarray:
        if self._np_mask is None:
            self.host_materializations += 1
            self._np_mask = np.concatenate(
                [np.asarray(m) for m in self.masks]) if self.masks \
                else np.zeros(0, bool)
        return self._np_mask

    def np_scores(self) -> np.ndarray:
        if self._np_scores is None:
            self._np_scores = np.concatenate(
                [np.asarray(s) for s in self.scores_dev]) if self.scores_dev \
                else np.zeros(0, np.float32)
        return self._np_scores


_DEVICE_METRICS = {"min", "max", "sum", "avg", "stats", "extended_stats"}
_MAX_DEVICE_HISTO_BUCKETS = 10_000


def collect_device(node: AggNode, state: DeviceAggState) -> dict | None:
    """Device collection for the hot agg shapes: a segment-reduce on the
    accelerator with only bucket/scalar results crossing to host (SURVEY §7
    step 9; ref collector tree: AggregationPhase.java:44). Returns None for
    shapes it doesn't serve — script/missing params, sub-aggregations,
    calendar intervals, text-backed terms — and the numpy collectors (the
    parity oracle) take over.

    Precision note: device sums accumulate in f32 over the (hi, lo)
    double-double split — tests hold the numpy path to rtol 1e-5."""
    if node.subs or node.pipelines:
        return None
    params = node.params
    if "script" in params or "missing" in params or "order" in params:
        return None
    fname = params.get("field")
    if fname is None:
        return None
    try:
        if node.type in _DEVICE_METRICS:
            out = _d_metric(fname, state)
        elif node.type == "value_count":
            out = _d_value_count(fname, state)
        elif node.type == "terms":
            out = _d_terms(fname, state)
        elif node.type == "histogram":
            out = _d_histogram(node, fname, state)
        elif node.type == "date_histogram":
            interval = params.get("interval") or \
                params.get("calendar_interval") or params.get("fixed_interval")
            if _CALENDAR.get(str(interval)) is not None:
                return None               # calendar buckets stay host-side
            out = _d_date_histogram(node, fname, state)
        elif node.type in ("range", "date_range"):
            out = _d_range(node, fname, state,
                           is_date=node.type == "date_range")
        else:
            return None
    except _DeviceAggFallback:
        return None
    if out is not None:
        DEVICE_AGG_STATS["device_collects"] += 1
    return out


class _DeviceAggFallback(Exception):
    pass


def _d_numeric_cols(fname: str, state: DeviceAggState):
    cols = [seg.numeric.get(fname) for seg in state.reader.segments]
    if not any(c is not None for c in cols):
        raise _DeviceAggFallback
    return cols


def _d_count_minmax(fname: str, state: DeviceAggState):
    """→ (rows [segments, 5] = (count, min_hi, min_lo, max_hi, max_lo)
    fetched in ONE transfer, cols) — dd-exact extrema (aggs_ops.dd_min_max)."""
    import jax.numpy as jnp
    from elasticsearch_tpu.ops import aggs_ops
    cols = _d_numeric_cols(fname, state)
    rows = []
    for seg, col, mask in zip(state.reader.segments, cols, state.masks):
        if col is None:
            continue
        cnt, mn_hi, mn_lo, mx_hi, mx_lo = aggs_ops.dd_min_max(
            col.hi, col.lo, col.exists, mask)
        rows.append(jnp.stack([cnt.astype(jnp.float32),
                               mn_hi, mn_lo, mx_hi, mx_lo]))
    return np.asarray(jnp.stack(rows)), cols


def _dd_extrema(rows: np.ndarray) -> tuple[float, float]:
    """Host reduce of per-segment dd extrema → exact f64 (min, max)."""
    live = rows[:, 0] > 0
    mins = rows[live, 1].astype(np.float64) + rows[live, 2]
    maxs = rows[live, 3].astype(np.float64) + rows[live, 4]
    return float(mins.min()), float(maxs.max())


def _d_metric(fname: str, state: DeviceAggState) -> dict:
    import jax.numpy as jnp
    from elasticsearch_tpu.ops import aggs_ops
    mm_rows, cols = _d_count_minmax(fname, state)
    sums = []
    for seg, col, mask in zip(state.reader.segments, cols, state.masks):
        if col is None:
            continue
        s_hi = jnp.where(col.exists & mask, col.hi, 0.0).sum()
        s_lo = jnp.where(col.exists & mask, col.lo, 0.0).sum()
        ssq = aggs_ops.sum_of_squares(col.hi, col.exists, mask)
        sums.append(jnp.stack([s_hi, s_lo, ssq]))
    s_rows = np.asarray(jnp.stack(sums))
    count = int(mm_rows[:, 0].sum())
    out = {"count": count}
    if count:
        mn, mx = _dd_extrema(mm_rows)
        out.update(sum=float(s_rows[:, 0].sum() + s_rows[:, 1].sum()),
                   min=mn, max=mx, sum_sq=float(s_rows[:, 2].sum()))
    else:
        out.update(sum=0.0, min=None, max=None, sum_sq=0.0)
    return out


def _d_value_count(fname: str, state: DeviceAggState) -> dict:
    import jax.numpy as jnp
    from elasticsearch_tpu.ops import aggs_ops
    counts = []
    served = False
    for seg, mask in zip(state.reader.segments, state.masks):
        ncol = seg.numeric.get(fname)
        if ncol is not None:
            counts.append(aggs_ops.value_count(ncol.exists, mask))
            served = True
            continue
        kcol = seg.keyword.get(fname)
        if kcol is not None:
            counts.append(aggs_ops.value_count(
                (kcol.ords >= 0).any(axis=1), mask))
            served = True
    if not served:
        raise _DeviceAggFallback
    return {"count": int(np.asarray(jnp.stack(counts)).sum())}


def _d_terms(fname: str, state: DeviceAggState) -> dict:
    """Keyword terms agg: per-segment ordinal counts on device (vocab-sized
    fetches), union-merged host-side by term string. Resolution mirrors
    ShardAggContext.keyword_values: an analyzed text field wins over its
    .keyword multi-field (2.x fielddata tokens) and stays host-side."""
    from elasticsearch_tpu.ops import aggs_ops
    segs = state.reader.segments
    candidates = [fname]
    if not any(seg.text.get(fname) is not None for seg in segs):
        candidates.append(f"{fname}.keyword")
    for candidate in candidates:
        cols = [seg.keyword.get(candidate) for seg in segs]
        if not any(c is not None for c in cols):
            continue
        merged: dict[str, int] = {}
        for seg, col, mask in zip(segs, cols, state.masks):
            if col is None:
                continue
            vocab = col.column.vocab
            if not vocab:
                continue
            counts = np.asarray(aggs_ops.ord_value_counts(
                col.ords, mask, len(vocab)))
            for oid in np.nonzero(counts)[0]:
                key = vocab[int(oid)]
                merged[key] = merged.get(key, 0) + int(counts[oid])
        buckets = {k: {"doc_count": n} for k, n in merged.items()}
        return {"buckets": _as_pairs(buckets),
                "doc_count_error_upper_bound": 0}
    raise _DeviceAggFallback        # numeric/text terms stay host-side


def _d_histogram_common(node: AggNode, fname: str, state: DeviceAggState,
                        interval: float, offset: float):
    import jax.numpy as jnp
    from elasticsearch_tpu.index.device_reader import dd_split
    from elasticsearch_tpu.ops import aggs_ops
    rows, cols = _d_count_minmax(fname, state)
    if not int(rows[:, 0].sum()):
        return []
    # dd-exact extrema → the base bucket is exact; no edge docs can land
    # below index 0 or beyond the last bucket
    lo, hi = _dd_extrema(rows)
    first = math.floor((lo - offset) / interval)
    last = math.floor((hi - offset) / interval)
    n_buckets = int(last - first + 1)
    if n_buckets > _MAX_DEVICE_HISTO_BUCKETS:
        raise _DeviceAggFallback
    base = first * interval + offset
    base_hi, base_lo = dd_split(np.float64(base))
    per_seg = []
    for seg, col, mask in zip(state.reader.segments, cols, state.masks):
        if col is None:
            continue
        per_seg.append(aggs_ops.histogram_counts_dd(
            col.hi, col.lo, col.exists, mask, float(base_hi),
            float(base_lo), interval, n_buckets))
    counts = np.asarray(jnp.stack(per_seg)).sum(axis=0)
    return [(base + i * interval, int(c))
            for i, c in enumerate(counts) if c > 0]


def _d_histogram(node: AggNode, fname: str, state: DeviceAggState) -> dict:
    interval = float(node.params["interval"])
    offset = float(node.params.get("offset", 0.0))
    pairs = _d_histogram_common(node, fname, state, interval, offset)
    buckets = {float(k): {"doc_count": c} for k, c in pairs}
    return {"buckets": _as_pairs(buckets), "interval": interval,
            "min_doc_count": int(node.params.get("min_doc_count", 0))}


def _d_date_histogram(node: AggNode, fname: str,
                      state: DeviceAggState) -> dict:
    interval = node.params.get("interval") or \
        node.params.get("calendar_interval") or \
        node.params.get("fixed_interval")
    try:
        # calendar names the host path knows ('1d', 'day'...) may not be
        # fixed-parseable — fall back rather than error
        ms = parse_time_value(interval) * 1000.0
    except Exception:                       # noqa: BLE001 — fallback seam
        raise _DeviceAggFallback from None
    pairs = _d_histogram_common(node, fname, state, ms, 0.0)
    buckets = {int(k): {"doc_count": c} for k, c in pairs}
    return {"buckets": _as_pairs(buckets), "date": True}


def _d_range(node: AggNode, fname: str, state: DeviceAggState,
             is_date: bool) -> dict:
    import jax.numpy as jnp
    from elasticsearch_tpu.index.device_reader import dd_split
    from elasticsearch_tpu.ops import filters as filter_ops
    bounds = _range_bounds(node, is_date)
    if not bounds:
        return {"buckets": [], "keyed_order": []}
    cols = _d_numeric_cols(fname, state)
    per_seg = []
    for seg, col, mask in zip(state.reader.segments, cols, state.masks):
        if col is None:
            continue
        row = []
        for _key, lo, hi in bounds:
            # double-double comparison: exact for dates/large longs where
            # a single f32 bound would blur the boundary. Range semantics
            # are [from, to): the upper bound compares STRICTLY (a
            # nextafter-bumped bound would underflow the dd split for
            # small `to` values, e.g. to:0, and turn exclusive into
            # inclusive).
            ghi, glo = dd_split(np.float64(lo))
            lhi, llo = dd_split(np.float64(hi))
            m = filter_ops.numeric_range(
                col.hi, col.lo, col.exists,
                jnp.float32(ghi), jnp.float32(glo),
                jnp.float32(lhi), jnp.float32(llo),
                hi_strict=jnp.float32(0.0 if hi == np.inf else 1.0))
            row.append((m & mask).sum(dtype=jnp.int32))
        per_seg.append(jnp.stack(row))
    counts = np.asarray(jnp.stack(per_seg)).sum(axis=0)
    buckets = {}
    for (key, lo, hi), c in zip(bounds, counts):
        buckets[key] = {"doc_count": int(c),
                        "from": None if lo == -np.inf else lo,
                        "to": None if hi == np.inf else hi}
    return {"buckets": _as_pairs(buckets),
            "keyed_order": [b[0] for b in bounds]}


# ---------------------------------------------------------------------------
# collect phase (per shard)
# ---------------------------------------------------------------------------

class ShardAggContext:
    """Host views of one shard's reader for aggregation collection."""

    def __init__(self, reader, mapper_service, execute_filter, scores=None,
                 exec_ctx=None):
        self.reader = reader
        self.mapper_service = mapper_service
        self.execute_filter = execute_filter  # (Query) → list[np mask per seg]
        self.scores = scores                  # [N] query scores (top_hits)
        # the query ExecutionContext, when the caller has one — nested agg
        # sub-filters re-execute over CHILD segments through it
        self.exec_ctx = exec_ctx

    def live_mask(self) -> np.ndarray:
        """Concatenated live mask over the reader (significant_terms'
        background set)."""
        return np.concatenate([np.asarray(s.live)
                               for s in self.reader.segments]) \
            if self.reader.segments else np.zeros(0, bool)

    def numeric_values(self, fname: str):
        """→ (values f64 concat over segments, exists concat)."""
        vals, exists = [], []
        for s in self.reader.segments:
            col = s.seg.numeric_fields.get(fname)
            if col is None:
                vals.append(np.zeros(s.padded_docs))
                exists.append(np.zeros(s.padded_docs, bool))
            else:
                vals.append(col.values)
                exists.append(col.exists)
        return np.concatenate(vals), np.concatenate(exists)

    def keyword_values(self, fname: str):
        """→ (ords [N,K] concat (ord remapped to per-shard union), vocab).

        Resolution order: exact keyword column → uninverted text tokens
        (the reference loads fielddata for an analyzed string, so a
        terms/significant_terms agg on it yields the ANALYZED tokens —
        IndexFieldDataService on a string field, SURVEY §2.5 fielddata) →
        `{field}.keyword` multi-field as a last resort."""
        segs = self.reader.segments
        cols = [s.seg.keyword_fields.get(fname) for s in segs]
        if any(c is not None for c in cols):
            return self._union_ords(
                [(c.vocab, c.ords) if c is not None else None
                 for c in cols])
        tcols = [s.seg.text_fields.get(fname) for s in segs]
        if any(c is not None for c in tcols):
            return self._union_ords(
                [(c.terms, c.uterms) if c is not None else None
                 for c in tcols])
        cols = [s.seg.keyword_fields.get(f"{fname}.keyword") for s in segs]
        if any(c is not None for c in cols):
            return self._union_ords(
                [(c.vocab, c.ords) if c is not None else None
                 for c in cols])
        return self._union_ords([None] * len(segs))

    def geo_values(self, fname: str):
        """→ (lat f64, lon f64, exists) concatenated over segments."""
        lats, lons, exists = [], [], []
        for s in self.reader.segments:
            col = s.seg.geo_fields.get(fname)
            if col is None:
                lats.append(np.zeros(s.padded_docs))
                lons.append(np.zeros(s.padded_docs))
                exists.append(np.zeros(s.padded_docs, bool))
            else:
                lats.append(np.asarray(col.lat, np.float64))
                lons.append(np.asarray(col.lon, np.float64))
                exists.append(np.asarray(col.exists, bool))
        return (np.concatenate(lats), np.concatenate(lons),
                np.concatenate(exists))

    def _union_ords(self, per_seg):
        """[(vocab, ords[Np,K]) | None per segment] → shard-union view."""
        union: dict[str, int] = {}
        kmax = 1
        for item in per_seg:
            if item is not None:
                vocab, ords = item
                kmax = max(kmax, ords.shape[1])
                for v in vocab:
                    union.setdefault(v, len(union))
        rows = []
        for s, item in zip(self.reader.segments, per_seg):
            if item is None:
                rows.append(np.full((s.padded_docs, kmax), -1, np.int32))
                continue
            vocab, ords = item
            remap = np.array([union[v] for v in vocab] or [0], np.int32)
            out = np.full((ords.shape[0], kmax), -1, np.int32)
            valid = ords >= 0
            out[:, :ords.shape[1]] = np.where(
                valid, remap[np.clip(ords, 0, None)], -1)
            rows.append(out)
        vocab_out = [None] * len(union)
        for v, i in union.items():
            vocab_out[i] = v
        return np.concatenate(rows), vocab_out


def collect(node: AggNode, mask: np.ndarray, ctx: ShardAggContext) -> dict:
    """→ shard partial for this agg (merged by reduce())."""
    fn = _COLLECTORS.get(node.type)
    if fn is None:
        raise QueryParsingError(f"unknown aggregation type [{node.type}]")
    return fn(node, mask, ctx)


def _collect_subs(node: AggNode, mask: np.ndarray, ctx: ShardAggContext) -> dict:
    return {sub.name: collect(sub, mask, ctx) for sub in node.subs}


def _field_numeric(node: AggNode, ctx: ShardAggContext):
    fname = node.params.get("field")
    if fname is None:
        raise QueryParsingError(f"agg [{node.name}] requires a field")
    return ctx.numeric_values(fname)


def _c_metric(node, mask, ctx):
    vals, exists = _field_numeric(node, ctx)
    m = mask & exists
    v = vals[m]
    out = {"count": int(v.size)}
    if v.size:
        out.update(sum=float(v.sum()), min=float(v.min()), max=float(v.max()),
                   sum_sq=float((v * v).sum()))
    else:
        out.update(sum=0.0, min=None, max=None, sum_sq=0.0)
    return out


def _c_value_count(node, mask, ctx):
    fname = node.params.get("field")
    ncol_vals, exists = ctx.numeric_values(fname)
    if exists.any():
        return {"count": int((mask & exists).sum())}
    ords, _ = ctx.keyword_values(fname)
    valid = (ords >= 0).any(axis=1)
    return {"count": int((mask & valid).sum())}


def _c_cardinality(node, mask, ctx):
    fname = node.params.get("field")
    ords, vocab = ctx.keyword_values(fname)
    if vocab:
        sel = ords[mask]
        present = np.unique(sel[sel >= 0])
        return {"values": [vocab[i] for i in present]}
    vals, exists = ctx.numeric_values(fname)
    return {"values": np.unique(vals[mask & exists]).tolist()}


def _c_percentiles(node, mask, ctx):
    vals, exists = _field_numeric(node, ctx)
    return {"values": vals[mask & exists].tolist(),
            "percents": node.params.get("percents",
                                        [1, 5, 25, 50, 75, 95, 99])}


def _c_top_hits(node, mask, ctx):
    size = int(node.params.get("size", 3))
    idx = np.nonzero(mask)[0]
    if ctx.scores is not None and idx.size:
        # top hits ordered by query score desc, doc asc (ES default)
        order = np.lexsort((idx, -ctx.scores[idx]))
        idx = idx[order]
    idx = idx[:size]
    hits = []
    for gid in idx:
        score = float(ctx.scores[int(gid)]) if ctx.scores is not None else None
        hits.append({"_id": ctx.reader.doc_id(int(gid)),
                     "_score": score,
                     "_source": ctx.reader.source(int(gid))})
    return {"hits": hits, "total": int(mask.sum()), "size": size}


def _c_terms(node, mask, ctx):
    fname = node.params.get("field")
    ords, vocab = ctx.keyword_values(fname)
    if vocab:
        sel = ords[mask]
        sel = sel[sel >= 0]
        counts = np.bincount(sel, minlength=len(vocab))
        buckets = {}
        present = np.nonzero(counts)[0]
        # shard_size: collect more than size for accurate cross-shard merge
        # (reference: terms agg shard_size heuristics)
        order = node.params.get("order")
        for oid in present:
            key = vocab[oid]
            b = {"doc_count": int(counts[oid])}
            if node.subs:
                bmask = mask & (ords == oid).any(axis=1)
                b["subs"] = _collect_subs(node, bmask, ctx)
            buckets[key] = b
        return {"buckets": _as_pairs(buckets),
                "doc_count_error_upper_bound": 0}
    # numeric terms
    vals, exists = ctx.numeric_values(fname)
    sel = vals[mask & exists]
    uniq, counts = np.unique(sel, return_counts=True)
    buckets = {}
    for u, c in zip(uniq, counts):
        key = int(u) if float(u).is_integer() else float(u)
        b = {"doc_count": int(c)}
        if node.subs:
            bmask = mask & exists & (vals == u)
            b["subs"] = _collect_subs(node, bmask, ctx)
        buckets[key] = b
    return {"buckets": _as_pairs(buckets),
            "doc_count_error_upper_bound": 0}


def _c_histogram(node, mask, ctx):
    vals, exists = _field_numeric(node, ctx)
    interval = float(node.params["interval"])
    offset = float(node.params.get("offset", 0.0))
    m = mask & exists
    v = vals[m]
    buckets = {}
    if v.size:
        keys = np.floor((v - offset) / interval) * interval + offset
        uniq, counts = np.unique(keys, return_counts=True)
        for u, c in zip(uniq, counts):
            b = {"doc_count": int(c)}
            if node.subs:
                kk = np.floor((vals - offset) / interval) * interval + offset
                bmask = m.copy()
                bmask[m] = False  # rebuilt below
                bmask = mask & exists & (kk == u)
                b["subs"] = _collect_subs(node, bmask, ctx)
            buckets[float(u)] = b
    return {"buckets": _as_pairs(buckets), "interval": interval,
            "min_doc_count": int(node.params.get("min_doc_count", 0))}


def _c_date_histogram(node, mask, ctx):
    vals, exists = _field_numeric(node, ctx)
    interval = node.params.get("interval") or \
        node.params.get("calendar_interval") or \
        node.params.get("fixed_interval")
    m = mask & exists
    v = vals[m]
    buckets = {}
    cal = _CALENDAR.get(str(interval))
    if cal is not None:
        if v.size:
            dt = v.astype("datetime64[ms]").astype(f"datetime64[{cal}]")
            keys = dt.astype("datetime64[ms]").astype(np.int64)
            uniq, counts = np.unique(keys, return_counts=True)
            all_dt = vals.astype("datetime64[ms]").astype(f"datetime64[{cal}]") \
                .astype("datetime64[ms]").astype(np.int64)
            for u, c in zip(uniq, counts):
                b = {"doc_count": int(c)}
                if node.subs:
                    b["subs"] = _collect_subs(
                        node, mask & exists & (all_dt == u), ctx)
                buckets[int(u)] = b
        return {"buckets": _as_pairs(buckets), "date": True}
    ms = parse_time_value(interval) * 1000.0
    if v.size:
        keys = np.floor(v / ms) * ms
        uniq, counts = np.unique(keys, return_counts=True)
        for u, c in zip(uniq, counts):
            b = {"doc_count": int(c)}
            if node.subs:
                kk = np.floor(vals / ms) * ms
                b["subs"] = _collect_subs(node, mask & exists & (kk == u), ctx)
            buckets[int(u)] = b
    return {"buckets": _as_pairs(buckets), "date": True}


def _range_bounds(node, is_date: bool):
    bounds = []
    for r in node.params.get("ranges", []):
        frm = r.get("from")
        to = r.get("to")
        if is_date:
            frm = parse_date(frm) if frm is not None else None
            to = parse_date(to) if to is not None else None
        key = r.get("key")
        if key is None:
            key = f"{frm if frm is not None else '*'}-{to if to is not None else '*'}"
        bounds.append((key, -np.inf if frm is None else float(frm),
                       np.inf if to is None else float(to)))
    return bounds


def _c_range(node, mask, ctx, is_date=False):
    vals, exists = _field_numeric(node, ctx)
    m = mask & exists
    buckets = {}
    for key, lo, hi in _range_bounds(node, is_date):
        bmask = m & (vals >= lo) & (vals < hi)
        b = {"doc_count": int(bmask.sum()), "from": None if lo == -np.inf else lo,
             "to": None if hi == np.inf else hi}
        if node.subs:
            b["subs"] = _collect_subs(node, bmask, ctx)
        buckets[key] = b
    return {"buckets": _as_pairs(buckets), "keyed_order": [b[0] for b in
                                                _range_bounds(node, is_date)]}


def _c_filter(node, mask, ctx):
    from elasticsearch_tpu.search.query_dsl import parse_query
    fmask = ctx.execute_filter(parse_query(node.params))
    bmask = mask & fmask
    out = {"doc_count": int(bmask.sum())}
    if node.subs:
        out["subs"] = _collect_subs(node, bmask, ctx)
    return out


def _c_filters(node, mask, ctx):
    from elasticsearch_tpu.search.query_dsl import parse_query
    buckets = {}
    specs = node.params.get("filters", {})
    items = specs.items() if isinstance(specs, dict) else \
        ((str(i), s) for i, s in enumerate(specs))
    for key, spec in items:
        fmask = ctx.execute_filter(parse_query(spec))
        bmask = mask & fmask
        b = {"doc_count": int(bmask.sum())}
        if node.subs:
            b["subs"] = _collect_subs(node, bmask, ctx)
        buckets[key] = b
    return {"buckets": _as_pairs(buckets)}


def _c_global(node, mask, ctx):
    gmask = np.ones_like(mask)
    # global agg ignores the query, but not deletes/padding: rebuild liveness
    live = np.concatenate([np.asarray(s.live) for s in ctx.reader.segments]) \
        if ctx.reader.segments else mask
    out = {"doc_count": int(live.sum())}
    if node.subs:
        out["subs"] = _collect_subs(node, live, ctx)
    return out


def _c_missing(node, mask, ctx):
    fname = node.params.get("field")
    vals, exists = ctx.numeric_values(fname)
    if not exists.any():
        ords, vocab = ctx.keyword_values(fname)
        exists = (ords >= 0).any(axis=1)
    bmask = mask & ~exists
    out = {"doc_count": int(bmask.sum())}
    if node.subs:
        out["subs"] = _collect_subs(node, bmask, ctx)
    return out


def _c_significant_terms(node, mask, ctx):
    """significant_terms (ref: core/search/aggregations/bucket/significant/
    SignificantTermsAggregator + JLHScore): per-term foreground (query
    mask) and background (whole index) counts; the coordinator scores the
    merged counts."""
    fname = node.params.get("field")
    ords, vocab = ctx.keyword_values(fname)
    live = ctx.live_mask()
    if not vocab:
        return {"buckets": [], "fg_total": int((mask & live).sum()),
                "bg_total": int(live.sum())}
    fg_sel = ords[mask & live]
    bg_sel = ords[live]
    fg = np.bincount(fg_sel[fg_sel >= 0], minlength=len(vocab))
    bg = np.bincount(bg_sel[bg_sel >= 0], minlength=len(vocab))
    buckets = {}
    for oid in np.nonzero(fg)[0]:
        key = vocab[int(oid)]
        b = {"doc_count": int(fg[oid]), "bg_count": int(bg[oid])}
        if node.subs:
            bmask = mask & live & (ords == oid).any(axis=1)
            b["subs"] = _collect_subs(node, bmask, ctx)
        buckets[key] = b
    return {"buckets": _as_pairs(buckets),
            "fg_total": int((mask & live).sum()),
            "bg_total": int(live.sum())}


def _c_sampler(node, mask, ctx):
    """sampler (ref: bucket/sampler/SamplerAggregator): restrict sub-aggs
    to the shard's top `shard_size` docs by query score."""
    shard_size = int(node.params.get("shard_size", 100))
    bmask = mask
    if ctx.scores is not None and mask.sum() > shard_size:
        scores = np.where(mask, np.asarray(ctx.scores), -np.inf)
        top = np.argpartition(-scores, shard_size)[:shard_size]
        bmask = np.zeros_like(mask)
        bmask[top] = True
        bmask &= mask
    out = {"doc_count": int(bmask.sum())}
    if node.subs:
        out["subs"] = _collect_subs(node, bmask, ctx)
    return out


class _NestedCtx(ShardAggContext):
    """Child-row view for nested aggs: segments are the nested blocks'
    child DeviceSegments; `parent_ctx`/`parent_of` link back for
    reverse_nested."""

    def __init__(self, parent_ctx: ShardAggContext, path: str):
        self.parent_ctx = parent_ctx
        self.path = path
        self.mapper_service = parent_ctx.mapper_service
        self.exec_ctx = parent_ctx.exec_ctx
        # filters under a nested agg evaluate in CHILD-row space — the
        # parent's execute_filter would mask the wrong doc space
        self.execute_filter = self._child_filter
        self.scores = None
        import types
        segs = []
        self.parent_of: list[np.ndarray] = []
        self.parent_base: list[int] = []
        base = 0
        for s in parent_ctx.reader.segments:
            blk = s.nested.get(path)
            if blk is not None:
                segs.append(blk.child)
                self.parent_of.append(np.asarray(blk.parent))
            else:
                segs.append(None)
                self.parent_of.append(np.zeros(0, np.int64))
            self.parent_base.append(base)
            base += s.padded_docs
        self.reader = types.SimpleNamespace(
            segments=[x for x in segs if x is not None])
        self._all_segs = segs

    def _child_filter(self, query) -> np.ndarray:
        from elasticsearch_tpu.search.execute import SegmentExecutor
        if self.exec_ctx is None:
            raise QueryParsingError(
                "filter aggregations under [nested] need the query "
                "execution context")
        masks = []
        for seg in self.reader.segments:
            ex = SegmentExecutor(seg, self.exec_ctx)
            masks.append(np.asarray(ex.match_mask(query))
                         & np.asarray(seg.live)[:seg.padded_docs])
        return np.concatenate(masks) if masks else np.zeros(0, bool)

    def child_mask(self, parent_mask: np.ndarray) -> np.ndarray:
        """Parent-space mask → concatenated child-row mask."""
        outs = []
        for seg, parents, base in zip(self._all_segs, self.parent_of,
                                      self.parent_base):
            if seg is None:
                continue
            valid = parents >= 0
            m = np.zeros(seg.padded_docs, bool)
            live = np.asarray(seg.live)
            idx = np.nonzero(valid)[0]
            m[idx] = parent_mask[base + parents[idx]]
            outs.append(m & live[:len(m)])
        return np.concatenate(outs) if outs else np.zeros(0, bool)

    def parent_mask(self, child_mask: np.ndarray) -> np.ndarray:
        """Child-row mask → parent-space mask (reverse_nested)."""
        total = sum(s.padded_docs
                    for s in self.parent_ctx.reader.segments)
        out = np.zeros(total, bool)
        off = 0
        for seg, parents, base in zip(self._all_segs, self.parent_of,
                                      self.parent_base):
            if seg is None:
                continue
            n = seg.padded_docs
            cm = child_mask[off:off + n]
            idx = np.nonzero(cm & (parents[:n] >= 0))[0]                 if len(parents) >= n else np.nonzero(cm)[0][:0]
            out[base + parents[idx]] = True
            off += n
        return out


def _c_nested(node, mask, ctx):
    """nested agg (ref: bucket/nested/NestedAggregator): sub-aggs run over
    the path's CHILD rows of the matching parents."""
    path = node.params.get("path")
    nctx = _NestedCtx(ctx, path)
    cmask = nctx.child_mask(mask)
    out = {"doc_count": int(cmask.sum())}
    if node.subs:
        out["subs"] = {}
        for sub in node.subs:
            if sub.type == "reverse_nested":
                pmask = nctx.parent_mask(cmask)
                r = {"doc_count": int((pmask & mask).sum())}
                if sub.subs:
                    r["subs"] = _collect_subs(sub, pmask & mask, ctx)
                out["subs"][sub.name] = r
            else:
                out["subs"][sub.name] = collect(sub, cmask, nctx)
    return out


def _c_reverse_nested(node, mask, ctx):
    # only meaningful under a nested agg (handled in _c_nested); standalone
    # it is the identity bucket
    out = {"doc_count": int(mask.sum())}
    if node.subs:
        out["subs"] = _collect_subs(node, mask, ctx)
    return out


def _c_children(node, mask, ctx):
    """children agg (ref: bucket/children/ParentToChildrenAggregator):
    bucket = docs of child `type` whose _parent is a doc in the current
    bucket (parent/child colocate per shard, so the join is local)."""
    child_type = node.params.get("type")
    # matching parents' _ids per segment
    parent_ids: set[str] = set()
    off = 0
    for s in ctx.reader.segments:
        n = s.padded_docs
        seg_mask = mask[off:off + n]
        for local in np.nonzero(seg_mask[:s.seg.num_docs])[0]:
            parent_ids.add(s.seg.ids[int(local)])
        off += n
    # child mask: _type == child_type and _parent ∈ parent_ids
    outs = []
    for s in ctx.reader.segments:
        m = np.zeros(s.padded_docs, bool)
        tcol = s.seg.keyword_fields.get("_type")
        pcol = s.seg.keyword_fields.get("_parent")
        if tcol is not None and pcol is not None and parent_ids:
            t_ok = np.zeros(s.padded_docs, bool)
            if child_type in tcol.index:
                tid = tcol.index[child_type]
                t_ok[:tcol.ords.shape[0]] = (tcol.ords == tid).any(axis=1)
            p_ok = np.zeros(s.padded_docs, bool)
            wanted = np.array([v in parent_ids for v in pcol.vocab], bool)
            first = np.asarray(pcol.ords[:, 0])
            ok = first >= 0
            p_ok[:len(first)] = ok & wanted[np.maximum(first, 0)]
            m = t_ok & p_ok & np.asarray(s.live)[:s.padded_docs]
        outs.append(m)
    cmask = np.concatenate(outs) if outs else np.zeros(0, bool)
    out = {"doc_count": int(cmask.sum())}
    if node.subs:
        out["subs"] = _collect_subs(node, cmask, ctx)
    return out


def _c_geohash_grid(node, mask, ctx):
    from elasticsearch_tpu.utils.geohash import (
        geohash_encode, precision_to_length)
    fname = node.params.get("field")
    length = precision_to_length(node.params.get("precision", 5))
    lat, lon, exists = ctx.geo_values(fname)
    m = mask & exists
    buckets: dict = {}
    for i in np.nonzero(m)[0]:
        key = geohash_encode(float(lat[i]), float(lon[i]), length)
        b = buckets.setdefault(key, {"doc_count": 0, "_rows": []})
        b["doc_count"] += 1
        b["_rows"].append(int(i))
    out_buckets = {}
    for key, b in buckets.items():
        entry = {"doc_count": b["doc_count"]}
        if node.subs:
            bmask = np.zeros_like(mask)
            bmask[b["_rows"]] = True
            entry["subs"] = _collect_subs(node, bmask, ctx)
        out_buckets[key] = entry
    return {"buckets": _as_pairs(out_buckets)}


def _haversine_km(lat1, lon1, lat2, lon2):
    r = 6371.0087714
    p1, p2 = np.radians(lat1), np.radians(lat2)
    dphi = np.radians(lat2 - lat1)
    dl = np.radians(lon2 - lon1)
    a = np.sin(dphi / 2) ** 2 + np.cos(p1) * np.cos(p2) * np.sin(dl / 2) ** 2
    return 2 * r * np.arcsin(np.sqrt(a))


def _c_geo_distance(node, mask, ctx):
    """geo_distance ranges from an origin (ref: bucket/range/geodistance/)."""
    fname = node.params.get("field")
    origin = node.params.get("origin")
    if isinstance(origin, str):
        olat, olon = (float(x) for x in origin.split(","))
    elif isinstance(origin, (list, tuple)):
        olon, olat = float(origin[0]), float(origin[1])
    else:
        olat, olon = float(origin["lat"]), float(origin["lon"])
    unit = str(node.params.get("unit", "m"))
    per_km = {"m": 1000.0, "km": 1.0, "mi": 0.621371, "yd": 1093.61}.get(
        unit, 1000.0)
    lat, lon, exists = ctx.geo_values(fname)
    dist = _haversine_km(olat, olon, lat, lon) * per_km
    m = mask & exists
    buckets = {}
    order = []
    for r in node.params.get("ranges", []):
        frm = float(r["from"]) if r.get("from") is not None else -np.inf
        to = float(r["to"]) if r.get("to") is not None else np.inf
        key = r.get("key") or (
            f"{'*' if frm == -np.inf else r.get('from')}-"
            f"{'*' if to == np.inf else r.get('to')}")
        bmask = m & (dist >= frm) & (dist < to)
        b = {"doc_count": int(bmask.sum()),
             "from": None if frm == -np.inf else frm,
             "to": None if to == np.inf else to}
        if node.subs:
            b["subs"] = _collect_subs(node, bmask, ctx)
        buckets[key] = b
        order.append(key)
    return {"buckets": _as_pairs(buckets), "keyed_order": order}


def _c_geo_bounds(node, mask, ctx):
    lat, lon, exists = ctx.geo_values(node.params.get("field"))
    m = mask & exists
    if not m.any():
        return {"count": 0}
    return {"count": int(m.sum()),
            "top": float(lat[m].max()), "bottom": float(lat[m].min()),
            "left": float(lon[m].min()), "right": float(lon[m].max())}


def _c_geo_centroid(node, mask, ctx):
    lat, lon, exists = ctx.geo_values(node.params.get("field"))
    m = mask & exists
    n = int(m.sum())
    if not n:
        return {"count": 0, "lat_sum": 0.0, "lon_sum": 0.0}
    return {"count": n, "lat_sum": float(lat[m].sum()),
            "lon_sum": float(lon[m].sum())}


def _c_percentile_ranks(node, mask, ctx):
    vals, exists = _field_numeric(node, ctx)
    m = mask & exists
    return {"values": vals[m].tolist(),
            "wanted": [float(v) for v in node.params.get("values", [])]}


class _AggDocValues:
    """`doc` binding for interpreted scripted_metric: doc['f'].value over
    one segment's numeric/keyword columns, one doc at a time."""

    def __init__(self, seg):
        self.seg = seg
        self.doc = 0

    def __scriptlang_getitem__(self, field):
        return _AggFieldValue(self, field)

    # plain-Python subscripting for the lang-python engine (the
    # scriptlang interpreter goes through __scriptlang_getitem__)
    __getitem__ = __scriptlang_getitem__


class _AggFieldValue:
    def __init__(self, owner: _AggDocValues, field: str):
        self.owner = owner
        self.field = field

    def _keyword_col(self):
        seg = self.owner.seg
        # analyzed strings expose doc values through their .keyword
        # subfield (the columnar analog of fielddata on text)
        return seg.keyword_fields.get(self.field) or \
            seg.keyword_fields.get(self.field + ".keyword")

    def _values(self) -> list:
        seg, i = self.owner.seg, self.owner.doc
        num = seg.numeric_fields.get(self.field)
        if num is not None:
            return [float(num.values[i])] if num.exists[i] else []
        kw = self._keyword_col()
        if kw is not None:
            return [kw.vocab[o] for o in kw.ords[i] if o >= 0]
        return []

    def __scriptlang_getattr__(self, name: str):
        vals = self._values()
        if name == "value":
            return vals[0] if vals else (
                "" if self._keyword_col() is not None else 0.0)
        if name == "values":
            return vals
        if name == "empty":
            return not vals
        from elasticsearch_tpu.search.scriptlang import ScriptException
        raise ScriptException(f"no doc-value property [{name}]")

    def __scriptlang_method__(self, name: str, args):
        if name == "size":
            return len(self._values())
        if name == "isEmpty":
            return not self._values()
        if name == "getValue":
            return self.__scriptlang_getattr__("value")
        from elasticsearch_tpu.search.scriptlang import ScriptException
        raise ScriptException(f"no doc-value method [{name}]")

    # plain-Python attribute access for the lang-python engine
    # (.value / .values / .empty mirror the scriptlang protocol)
    @property
    def value(self):
        return self.__scriptlang_getattr__("value")

    @property
    def values(self):
        return self.__scriptlang_getattr__("values")

    @property
    def empty(self):
        return self.__scriptlang_getattr__("empty")


def _c_scripted_metric_interpreted(node, mask, ctx):
    """Full scripted_metric contract (ref: metrics/scripted/
    ScriptedMetricAggregator): init_script seeds `_agg`, map_script runs
    per matching doc with `doc` values, combine_script folds the shard
    state, reduce_script (reduce side) folds `_aggs`. Interpreted by
    GroovyLite — loops and collection state work as in lang-groovy."""
    from elasticsearch_tpu.search.script_engines import resolve_engine
    compile_fn = resolve_engine(node.params.get("lang"))
    params = dict(node.params.get("params", {}))
    agg: dict = {}
    bindings = {"_agg": agg, "params": params}
    init = node.params.get("init_script")
    if init:
        compile_fn(str(init)).run(dict(bindings))
    map_script = compile_fn(str(node.params["map_script"]))
    off = 0
    for s in ctx.reader.segments:
        n = s.padded_docs
        rows = np.nonzero(mask[off:off + n][:s.seg.num_docs])[0]
        if len(rows):
            dv = _AggDocValues(s.seg)
            b = {**bindings, "doc": dv}
            for r in rows:
                dv.doc = int(r)
                map_script.run(dict(b))
        off += n
    combine = node.params.get("combine_script")
    if combine:
        partial = compile_fn(str(combine)).run(dict(bindings))
    else:
        partial = agg
    from elasticsearch_tpu.action.search_action import wire_safe
    return {"partial": wire_safe(partial), "interpreted": True}


def _c_scripted_metric(node, mask, ctx):
    """scripted_metric (ref: metrics/scripted/): simple arithmetic map
    scripts run VECTORIZED as expressions (lang-expression speed); any
    init/combine/reduce phase — or a map script the expression grammar
    cannot compile — switches to the interpreted GroovyLite path with the
    full reference contract."""
    from elasticsearch_tpu.search.scripts import (
        ScriptContext, compile_script)
    map_src = node.params.get("map_script")
    if map_src is None:
        raise QueryParsingError(
            "[scripted_metric] requires a map_script")
    if any(node.params.get(p) for p in
           ("init_script", "combine_script", "reduce_script")):
        return _c_scripted_metric_interpreted(node, mask, ctx)
    try:
        script = compile_script(str(map_src))
    except QueryParsingError:                # not an expression: interpret
        return _c_scripted_metric_interpreted(node, mask, ctx)
    values = []
    off = 0
    for s in ctx.reader.segments:
        n = s.padded_docs
        seg_mask = mask[off:off + n]
        rows = np.nonzero(seg_mask[:s.seg.num_docs])[0]
        if len(rows):
            def get_numeric(field, _s=s):
                col = _s.seg.numeric_fields.get(field)
                if col is None:
                    z = np.zeros(_s.padded_docs)
                    return z, np.zeros(_s.padded_docs, bool)
                return (np.asarray(col.values, np.float64),
                        np.asarray(col.exists, bool))
            sctx = ScriptContext(
                get_numeric_column=get_numeric,
                get_vector_column=lambda f: (None, None),
                scores=np.zeros(n, np.float32),
                params=node.params.get("params", {}))
            arr = np.asarray(script.evaluate(sctx))
            if arr.ndim == 0:
                values.extend([float(arr)] * len(rows))
            else:
                values.extend(float(arr[int(r)]) for r in rows)
        off += n
    return {"values": values}


_COLLECTORS = {
    "min": _c_metric, "max": _c_metric, "sum": _c_metric, "avg": _c_metric,
    "stats": _c_metric, "extended_stats": _c_metric,
    "sampler": _c_sampler, "nested": _c_nested,
    "reverse_nested": _c_reverse_nested, "children": _c_children,
    "geohash_grid": _c_geohash_grid, "geo_distance": _c_geo_distance,
    "geo_bounds": _c_geo_bounds, "geo_centroid": _c_geo_centroid,
    "percentile_ranks": _c_percentile_ranks,
    "scripted_metric": _c_scripted_metric,
    "value_count": _c_value_count, "cardinality": _c_cardinality,
    "percentiles": _c_percentiles, "top_hits": _c_top_hits,
    "terms": _c_terms, "histogram": _c_histogram,
    "date_histogram": _c_date_histogram,
    "range": _c_range, "date_range": lambda n, m, c: _c_range(n, m, c, True),
    "filter": _c_filter, "filters": _c_filters,
    "global": _c_global, "missing": _c_missing,
    "significant_terms": _c_significant_terms,
}


# ---------------------------------------------------------------------------
# reduce phase (coordinator; InternalAggregations.reduce analog)
# ---------------------------------------------------------------------------

def reduce_aggs(nodes: list[AggNode], partials_per_shard: list[dict]) -> dict:
    out = {}
    siblings = [n for n in nodes if n.type not in PIPELINE_AGGS]
    pipelines = [n for n in nodes if n.type in PIPELINE_AGGS]
    for node in siblings:
        shard_parts = [p[node.name] for p in partials_per_shard if node.name in p]
        out[node.name] = _reduce_node(node, shard_parts)
    # sibling pipelines (avg/max/min/sum_bucket) consume the reduced output
    # of a multi-bucket sibling via buckets_path "agg>metric"
    for node in pipelines:
        path = node.params.get("buckets_path", "")
        head, _, rest = path.partition(">")
        buckets = out.get(head, {}).get("buckets", [])
        values = [v for v in (_bucket_path_value(b, rest or "_count")
                              for b in buckets) if v is not None]
        if node.type == "avg_bucket":
            value = sum(values) / len(values) if values else None
        elif node.type == "sum_bucket":
            value = sum(values) if values else 0.0
        elif node.type == "max_bucket":
            value = max(values) if values else None
        elif node.type == "min_bucket":
            value = min(values) if values else None
        else:
            continue  # cumulative_sum/derivative are parent pipelines
        out[node.name] = {"value": value}
    return out


def _merge_metric(parts: list[dict]) -> dict:
    count = sum(p["count"] for p in parts)
    s = sum(p["sum"] for p in parts)
    mins = [p["min"] for p in parts if p["min"] is not None]
    maxs = [p["max"] for p in parts if p["max"] is not None]
    return {"count": count, "sum": s,
            "min": min(mins) if mins else None,
            "max": max(maxs) if maxs else None,
            "sum_sq": sum(p.get("sum_sq", 0.0) for p in parts)}


def _as_pairs(buckets: dict) -> list:
    """Bucket map → [key, bucket] pairs. Shard partials cross the wire,
    whose codec stringifies dict KEYS (StreamOutput.write_value); carrying
    keys as list values keeps numeric histogram/terms keys typed."""
    return [[k, b] for k, b in buckets.items()]


def _bucket_dict(p: dict) -> dict:
    """Partial's buckets in either form (pairs from a shard, dict from
    older in-memory paths) → key→bucket dict with typed keys."""
    b = p.get("buckets", {})
    return dict(b) if isinstance(b, dict) else {k: v for k, v in b}


def _merge_buckets(node: AggNode, parts: list[dict]) -> dict:
    pdicts = [_bucket_dict(p) for p in parts]
    merged: dict = {}
    for pd in pdicts:
        for key, b in pd.items():
            cur = merged.setdefault(key, {"doc_count": 0, "_parts": []})
            cur["doc_count"] += b["doc_count"]
            for extra in ("from", "to"):
                if extra in b:
                    cur[extra] = b[extra]
            if "subs" in b:
                cur["_parts"].append(b["subs"])
    for key, b in merged.items():
        if b.pop("_parts", None) or node.subs:
            parts_list = [pd[key].get("subs", {})
                          for pd in pdicts if key in pd]
            b["aggs"] = reduce_aggs(node.subs, [pl for pl in parts_list if pl])
    return merged


def _bucket_path_value(bucket: dict, path: str):
    """Resolve a buckets_path within a rendered bucket: '_count',
    'sub_agg', 'sub_agg.metric', or 'sub>leaf' (reference:
    core/search/aggregations/pipeline/BucketHelpers.java)."""
    if path == "_count":
        return bucket.get("doc_count")
    node: Any = bucket
    for part in path.replace(">", ".").split("."):
        if not isinstance(node, dict):
            return None
        node = node.get(part)
    if isinstance(node, dict):
        return node.get("value", node.get("avg"))
    return node


def _moving_avg(values: list, params: dict) -> list:
    """moving_avg models (ref: pipeline/movavg/models/): simple, linear,
    ewma, holt, holt_winters (additive, no seasonality shortcut)."""
    window = int(params.get("window", 5))
    model = str(params.get("model", "simple"))
    settings = params.get("settings", {}) or {}
    out: list = []
    for i in range(len(values)):
        win = [v for v in values[max(0, i - window + 1): i + 1]
               if v is not None]
        if not win:
            out.append(None)
            continue
        if model == "linear":
            ws = list(range(1, len(win) + 1))
            out.append(sum(w * v for w, v in zip(ws, win)) / sum(ws))
        elif model == "ewma":
            alpha = float(settings.get("alpha", 0.3))
            acc = win[0]
            for v in win[1:]:
                acc = alpha * v + (1 - alpha) * acc
            out.append(acc)
        elif model in ("holt", "holt_winters"):
            alpha = float(settings.get("alpha", 0.3))
            beta = float(settings.get("beta", 0.1))
            level, trend = win[0], 0.0
            for v in win[1:]:
                last = level
                level = alpha * v + (1 - alpha) * (level + trend)
                trend = beta * (level - last) + (1 - beta) * trend
            out.append(level + trend)
        else:
            out.append(sum(win) / len(win))
    return out


def _pipe_expr(src: str, variables: dict):
    """bucket_script/bucket_selector expression over buckets_path values,
    evaluated by the lang-expression walker (search/scripts.py) with the
    bucket values bound as bare names — ONE sandbox to audit, never
    eval()."""
    from elasticsearch_tpu.search.scripts import (
        ScriptContext, compile_script)
    ctx = ScriptContext(get_numeric_column=None, get_vector_column=None,
                        scores=None, params={}, variables=variables)
    out = compile_script(str(src)).evaluate(ctx)
    return out


def _render_pipeline(node: AggNode, buckets: list[dict]) -> None:
    """Parent pipelines rendered into (or filtering) the buckets of the
    enclosing multi-bucket agg (ref: pipeline/*)."""
    for pipe in node.pipelines:
        if pipe.type == "bucket_selector":
            paths = pipe.params.get("buckets_path", {})
            script = pipe.params.get("script", "")
            if isinstance(script, dict):
                script = script.get("inline", script.get("source", ""))
            keep = []
            for b in buckets:
                variables = {k: _bucket_path_value(b, p)
                             for k, p in paths.items()}
                if any(v is None for v in variables.values()):
                    continue
                try:
                    if _pipe_expr(str(script), variables):
                        keep.append(b)
                except QueryParsingError:
                    raise
                except Exception:        # noqa: BLE001 — bucket dropped
                    continue
            buckets[:] = keep
            continue
        if pipe.type not in ("cumulative_sum", "derivative", "moving_avg",
                             "serial_diff", "bucket_script"):
            continue
        if pipe.type == "bucket_script":
            paths = pipe.params.get("buckets_path", {})
            script = pipe.params.get("script", "")
            if isinstance(script, dict):
                script = script.get("inline", script.get("source", ""))
            for b in buckets:
                variables = {k: _bucket_path_value(b, p)
                             for k, p in paths.items()}
                if any(v is None for v in variables.values()):
                    continue
                try:
                    b[pipe.name] = {"value": float(
                        _pipe_expr(str(script), variables))}
                except QueryParsingError:
                    raise
                except Exception:        # noqa: BLE001 — skip bucket
                    continue
            continue
        path = pipe.params.get("buckets_path", "_count")
        values = [_bucket_path_value(b, path) for b in buckets]
        if pipe.type == "cumulative_sum":
            acc = 0.0
            for b, v in zip(buckets, values):
                acc += (v or 0.0)
                b[pipe.name] = {"value": acc}
        elif pipe.type == "derivative":
            prev = None
            for b, v in zip(buckets, values):
                if prev is not None and v is not None:
                    b[pipe.name] = {"value": v - prev}
                prev = v
        elif pipe.type == "moving_avg":
            for b, v in zip(buckets, _moving_avg(values, pipe.params)):
                if v is not None:
                    b[pipe.name] = {"value": v}
        elif pipe.type == "serial_diff":
            lag = int(pipe.params.get("lag", 1))
            for i, b in enumerate(buckets):
                if i >= lag and values[i] is not None \
                        and values[i - lag] is not None:
                    b[pipe.name] = {"value": values[i] - values[i - lag]}


def _reduce_node(node: AggNode, parts: list[dict]) -> dict:
    t = node.type
    if t in ("min", "max", "sum", "avg"):
        m = _merge_metric(parts)
        if t == "avg":
            value = m["sum"] / m["count"] if m["count"] else None
        elif t == "sum":
            value = m["sum"]
        else:
            value = m[t]
        return {"value": value}
    if t == "stats" or t == "extended_stats":
        m = _merge_metric(parts)
        avg = m["sum"] / m["count"] if m["count"] else None
        out = {"count": m["count"], "min": m["min"], "max": m["max"],
               "sum": m["sum"], "avg": avg}
        if t == "extended_stats":
            if m["count"]:
                var = max(m["sum_sq"] / m["count"] - (avg or 0.0) ** 2, 0.0)
            else:
                var = None
            out.update(sum_of_squares=m["sum_sq"], variance=var,
                       std_deviation=math.sqrt(var) if var is not None else None)
        return out
    if t == "value_count":
        return {"value": sum(p["count"] for p in parts)}
    if t == "cardinality":
        values: set = set()
        for p in parts:
            values.update(map(str, p["values"]))
        return {"value": len(values)}
    if t == "percentiles":
        allv = np.sort(np.concatenate([np.asarray(p["values"], np.float64)
                                       for p in parts])) if parts else np.array([])
        percents = parts[0]["percents"] if parts else []
        vals = {}
        for pc in percents:
            vals[f"{float(pc)}"] = (float(np.percentile(allv, pc))
                                    if allv.size else None)
        return {"values": vals}
    if t == "top_hits":
        size = parts[0]["size"] if parts else 3
        hits = [h for p in parts for h in p["hits"]]
        hits.sort(key=lambda h: -(h.get("_score") or 0.0))
        return {"hits": {"total": sum(p["total"] for p in parts),
                         "hits": hits[:size]}}
    if t in ("filter", "global", "missing"):
        out = {"doc_count": sum(p["doc_count"] for p in parts)}
        sub_parts = [p["subs"] for p in parts if "subs" in p]
        if node.subs:
            out.update(reduce_aggs(node.subs, sub_parts))
        return out
    if t == "filters":
        merged = _merge_buckets(node, parts)
        return {"buckets": {k: _final_bucket(b) for k, b in merged.items()}}
    if t == "terms":
        merged = _merge_buckets(node, parts)
        size = int(node.params.get("size", 10) or 0) or len(merged)
        order = node.params.get("order", {"_count": "desc"})
        (okey, odir), = order.items() if isinstance(order, dict) else \
            (("_count", "desc"),)
        rev = str(odir).lower() == "desc"
        def sort_key(item):
            key, b = item
            if okey in ("_count",):
                return b["doc_count"]
            if okey in ("_term", "_key"):
                return key
            agg = b.get("aggs", {}).get(okey, {})
            return agg.get("value") or 0
        items = sorted(merged.items(), key=sort_key, reverse=rev)
        if okey == "_count":  # secondary order: term asc (ES tie-break)
            items = sorted(items, key=lambda kv: str(kv[0]))
            items = sorted(items, key=lambda kv: kv[1]["doc_count"],
                           reverse=rev)
        buckets = [{"key": k, **_final_bucket(b)} for k, b in items[:size]]
        sum_other = sum(b["doc_count"] for _, b in items[size:])
        _render_pipeline(node, buckets)
        return {"buckets": buckets, "sum_other_doc_count": sum_other,
                "doc_count_error_upper_bound": 0}
    if t in ("histogram", "date_histogram"):
        merged = _merge_buckets(node, parts)
        min_dc = int(node.params.get("min_doc_count",
                                     1 if t == "date_histogram" else 0))
        keys = sorted(merged)
        buckets = [{"key": k, **_final_bucket(merged[k])} for k in keys
                   if merged[k]["doc_count"] >= max(min_dc, 1) or min_dc == 0]
        fmt = node.params.get("format")
        if fmt and t == "histogram":
            for b in buckets:
                b["key_as_string"] = _java_decimal_format(b["key"], fmt)
        _render_pipeline(node, buckets)
        return {"buckets": buckets}
    if t in ("range", "date_range"):
        merged = _merge_buckets(node, parts)
        order = parts[0].get("keyed_order", list(merged)) if parts else []
        buckets = [{"key": k, **_final_bucket(merged[k])} for k in order
                   if k in merged]
        return {"buckets": buckets}
    if t in ("sampler", "nested", "reverse_nested", "children"):
        total = sum(p.get("doc_count", 0) for p in parts)
        out = {"doc_count": total}
        if node.subs:
            # reverse_nested subs were collected inline by _c_nested
            sub_parts = [p["subs"] for p in parts if "subs" in p]
            if sub_parts:
                out.update(reduce_aggs(node.subs, sub_parts))
        return out
    if t in ("geohash_grid", "geo_distance"):
        merged = _merge_buckets(node, parts)
        if t == "geo_distance":
            order = parts[0].get("keyed_order", list(merged)) \
                if parts else []
            buckets = [{"key": k, **_final_bucket(merged[k])}
                       for k in order if k in merged]
        else:
            size = int(node.params.get("size", 10000) or 0) or len(merged)
            items = sorted(merged.items(),
                           key=lambda kv: (-kv[1]["doc_count"], kv[0]))
            buckets = [{"key": k, **_final_bucket(b)}
                       for k, b in items[:size]]
        _render_pipeline(node, buckets)
        return {"buckets": buckets}
    if t == "geo_bounds":
        alive = [p for p in parts if p.get("count")]
        if not alive:
            return {"bounds": None}
        return {"bounds": {
            "top_left": {"lat": max(p["top"] for p in alive),
                         "lon": min(p["left"] for p in alive)},
            "bottom_right": {"lat": min(p["bottom"] for p in alive),
                             "lon": max(p["right"] for p in alive)}}}
    if t == "geo_centroid":
        n = sum(p.get("count", 0) for p in parts)
        if not n:
            return {"count": 0}
        return {"count": n,
                "location": {
                    "lat": sum(p.get("lat_sum", 0.0) for p in parts) / n,
                    "lon": sum(p.get("lon_sum", 0.0) for p in parts) / n}}
    if t == "percentile_ranks":
        allv = np.concatenate([np.asarray(p["values"], np.float64)
                               for p in parts]) if parts else \
            np.zeros(0)
        wanted = parts[0].get("wanted", []) if parts else []
        vals = {}
        for w in wanted:
            vals[f"{float(w)}"] = (
                float(100.0 * (allv <= w).sum() / allv.size)
                if allv.size else None)
        return {"values": vals}
    if t == "scripted_metric":
        if any(p.get("interpreted") for p in parts):
            # full contract: reduce_script folds the per-shard partials
            # (`_aggs`); without one the partials list IS the value
            # (ScriptedMetricAggregator doReduce)
            from elasticsearch_tpu.search.script_engines import (
                resolve_engine)
            compile_fn = resolve_engine(node.params.get("lang"))
            aggs_list = [p.get("partial") for p in parts]
            reduce_src = node.params.get("reduce_script")
            if reduce_src:
                value = compile_fn(str(reduce_src)).run(
                    {"_aggs": aggs_list,
                     "params": dict(node.params.get("params", {}))})
            else:
                value = aggs_list
            return {"value": value}
        allv = [v for p in parts for v in p.get("values", [])]
        # expression path reduces by summing map values
        return {"value": float(np.sum(allv)) if allv else 0.0}
    if t == "significant_terms":
        fg_total = sum(p.get("fg_total", 0) for p in parts)
        bg_total = sum(p.get("bg_total", 0) for p in parts)
        counts: dict = {}
        sub_parts: dict = {}
        for p in parts:
            for key, b in _bucket_dict(p).items():
                cur = counts.setdefault(key, {"doc_count": 0, "bg_count": 0})
                cur["doc_count"] += b["doc_count"]
                cur["bg_count"] += b.get("bg_count", 0)
                if "subs" in b:
                    sub_parts.setdefault(key, []).append(b["subs"])
        min_dc = int(node.params.get("min_doc_count", 3))
        size = int(node.params.get("size", 10) or 0) or len(counts)
        scored = []
        for key, b in counts.items():
            if b["doc_count"] < min_dc:
                continue
            fg_pct = b["doc_count"] / max(fg_total, 1)
            bg_pct = b["bg_count"] / max(bg_total, 1)
            # JLH (SignificanceHeuristic default): 0 unless the term is
            # MORE frequent in the foreground than in the background
            score = 0.0 if fg_pct <= bg_pct or bg_pct == 0 else \
                (fg_pct - bg_pct) * (fg_pct / bg_pct)
            if score > 0:
                scored.append((score, key, b))
        scored.sort(key=lambda x: (-x[0], str(x[1])))
        buckets = []
        for s, k, b in scored[:size]:
            bucket = {"key": k, "doc_count": b["doc_count"],
                      "score": s, "bg_count": b["bg_count"]}
            if node.subs and k in sub_parts:
                bucket.update(reduce_aggs(node.subs, sub_parts[k]))
            buckets.append(bucket)
        return {"doc_count": fg_total, "buckets": buckets}
    raise QueryParsingError(f"cannot reduce aggregation type [{node.type}]")


def _final_bucket(b: dict) -> dict:
    out = {"doc_count": b["doc_count"]}
    for extra in ("from", "to"):
        if extra in b and b[extra] is not None:
            out[extra] = b[extra]
    if "aggs" in b:
        out.update(b["aggs"])
    return out
