"""Continuous-batching device scheduler — the live serving path's
device feeder (ROADMAP item 6, the LLM-serving playbook).

The admission model before this module was drain-then-refill: a formed
micro-batch dispatched, every waiter blocked for its drain, and only
then did the next batch start forming — between dispatches the device
idled for a full host round trip (BENCH_r04: 16 closed-loop clients at
152 QPS against a 485 QPS batch ceiling, p50 owned by the 68 ms RTT
floor). Iteration-level scheduling (Orca/vLLM) inverts it: the device
never waits for a batch to *form* — it is fed whatever accumulated
while it was busy.

Mechanics, per node:

* requests join per-lane, shape-bucketed queues — ``plane`` / ``impact``
  / ``knn`` / ``percolate``, keyed by the same pow2 buckets the program
  caches use, so every formed batch is admissible to ONE compiled
  program by construction;
* one dispatcher thread keeps a dispatch always in flight: while batch
  N computes on-device, batch N+1 is host-packed and launched
  (``query_phase_batch_launch`` is async — JAX dispatch returns before
  the device finishes), and batch N−1's device→host drain rides a
  worker thread. Admission is continuous — a batch is whatever queued
  while the in-flight window was full, so an idle device serves a lone
  request instantly (no formation deadline) and a saturated one forms
  large batches for free;
* pickup across queues is weighted-fair (WRR over lanes, FIFO within a
  lane, oldest-head queue first): a low-rate percolate client is never
  starved by a query storm;
* load shedding: a waiter whose task deadline (PR 2) is already blown
  at pickup — or that out-waited ``max_queue_wait_s`` — is shed back to
  the caller's serial path (which owns the timed_out/cancel semantics)
  instead of being dispatched into a blown deadline; and when the
  ``queue_wait`` SLO burn rate (PR 13) exceeds the shed threshold, the
  scheduler sheds lowest-priority lanes first at admission with a typed
  429-shaped :class:`SchedulerRejectedError`. An open plane breaker
  (PR 6) is checked by the CALLER before submit — the scheduler never
  queues toward a device the breaker already declared unhealthy.

Results are bit-identical to the unscheduled path: batches execute the
same ``query_phase_batch_launch``/``_drain`` programs the msearch path
uses (fuzz-pinned in tests/test_scheduler.py). Counters live in the
lane registry (``lanes.JIT_COUNTERS`` ``scheduler_*`` keys, bumped via
``jit_exec.note_scheduler_*``) and shed reasons in
``lanes.LANE_REASONS["scheduler"]`` — the PR 12 counter-discipline and
fallback-taxonomy rules police the scheduler by construction.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutTimeout
from contextlib import nullcontext

from elasticsearch_tpu.common.threadpool import EsRejectedExecutionError
from elasticsearch_tpu.search.batching import pow2_bucket


class SchedulerRejectedError(EsRejectedExecutionError):
    """Typed 429-shaped admission rejection: the scheduler refused to
    queue this request (SLO-burn shedding / queue capacity) — retry
    later or on another node, the work was never started."""

    status = 429

    def __init__(self, lane: str, reason: str, message: str):
        super().__init__(message)
        self.lane = lane
        self.reason = reason


#: internal sentinel a waiter resolves to when the scheduler declines
#: the request (ineligible batch, launch fallback, shutdown) — the
#: caller runs its serial path; never surfaced to users
DECLINED = object()

#: WRR pickup weights (turns per cycle) — fairness, not priority:
#: every lane with queued work gets picked every cycle
DEFAULT_WEIGHTS = {"plane": 4, "impact": 3, "knn": 2, "percolate": 1}

#: shed order under SLO burn: LOWEST priority sheds first (level 1
#: sheds priority ≤ 1, level 2 ≤ 2, level 3 everything)
DEFAULT_PRIORITIES = {"plane": 3, "impact": 2, "knn": 2, "percolate": 1}

#: minimum queue_wait samples in a shed window before the burn signal
#: is trusted (a single slow wakeup must not open the shed gate)
SHED_MIN_SAMPLES = 16


def _invoke(fn, *args, **kwargs):
    """Trivial invoker ``bind_context`` wraps — identity when the
    submitting thread carried no observability context."""
    return fn(*args, **kwargs)


def query_shape(q_node) -> tuple:
    """Structural fingerprint of a query AST — type, field, operand
    COUNTS (term/value counts change the compiled plan), recursed into
    sub-queries. An approximation of jit_exec's plan signature good
    enough for queue grouping: over-splitting costs nothing (smaller
    batches), under-splitting only a declined batch → serial fallback,
    never a wrong result."""
    parts: list = [type(q_node).__name__,
                   getattr(q_node, "field", None)]
    text = getattr(q_node, "text", None)
    if isinstance(text, str):
        # the compiled plans pad operand lists to pow2 buckets, so the
        # fingerprint buckets the same way — "a b c" and "x y z w" share
        # a program family, "a b" does not
        parts.append(pow2_bucket(max(len(text.split()), 1)))
    values = getattr(q_node, "values", None)
    if isinstance(values, (list, tuple)):
        parts.append(pow2_bucket(max(len(values), 1)))
    msm = getattr(q_node, "minimum_should_match", None)
    if msm is not None:
        parts.append(msm)
    for attr in ("must", "should", "must_not", "filter"):
        subs = getattr(q_node, attr, None)
        if isinstance(subs, (list, tuple)) and subs:
            parts.append((attr, tuple(query_shape(s) for s in subs)))
    for attr in ("query", "positive", "negative"):
        sub = getattr(q_node, attr, None)
        if sub is not None and hasattr(sub, "__dataclass_fields__"):
            parts.append((attr, query_shape(sub)))
    return tuple(parts)


def _with_geometry(shape):
    """Append the serving mesh's geometry to a shape bucket. Programs
    compiled for different pod slices (or for single-chip vs mesh
    serving) are distinct executables, so requests classified under
    different geometries must never share a queue — one compile per
    (shape, geometry), not a decline-then-recompile churn when the
    serving mesh changes."""
    from elasticsearch_tpu.search import jit_exec
    mesh = jit_exec.serving_mesh()
    if mesh is None:
        return shape
    return shape + (("mesh-geometry",) + jit_exec.mesh_geom(mesh),)


def classify(req, searcher):
    """→ ``(lane, shape key)`` for a request the batched programs can
    serve, ``(None, None)`` otherwise (caller stays serial). The shape
    key mirrors the program caches' pow2 bucketing plus the query's
    structural fingerprint, so one queue's requests share a compiled
    plan family — a formed batch rarely declines on mixed shapes.
    When a serving mesh is installed the bucket also carries the mesh
    geometry (see :func:`_with_geometry`)."""
    from elasticsearch_tpu.search import jit_exec
    from elasticsearch_tpu.search.phase import _is_score_order
    if searcher.ctx.dfs_stats is not None:
        return None, None               # global-idf scoring: serial path
    if req.knn is not None:
        kn = req.knn
        qdims = len(kn.query_vector[0]) if kn.multi \
            else len(kn.query_vector)
        shape = (kn.field, bool(kn.hybrid), bool(kn.multi),
                 kn.num_candidates, qdims,
                 pow2_bucket(max(req.from_ + req.size, 1)))
        if kn.hybrid:
            shape = shape + (query_shape(req.query),)
        if kn.filter is not None:
            # the filter mask resolves IN-PROGRAM (the fused lane's
            # filter machinery) but its structure is part of the
            # compiled plan — fingerprint it so filtered and
            # unfiltered knn never share a queue and mixed-filter
            # batches don't decline at launch
            shape = shape + (("filter", query_shape(kn.filter)),)
        return "knn", _with_geometry(shape)
    if (req.aggs or not _is_score_order(req.sort)
            or req.post_filter is not None or req.min_score is not None
            or req.search_after is not None or req.suggest
            or req.terminate_after is not None
            or req.timeout_ms is not None):
        return None, None               # the batch programs decline these
    k = pow2_bucket(max(req.from_ + req.size, 1))
    if req.rescore:
        # single-pass rescore over an impact-opted index rides the
        # planner's composed impact→rescore arm — its own
        # "fused-program" bucket (window/score_mode/rescore-query are
        # program-static) so continuous batching keeps one-in-flight
        # semantics for fused plans too
        if len(req.rescore) != 1 or jit_exec.impact_plane_config(
                searcher.ctx.index_name) is None:
            return None, None           # multi-pass / exact-lane rescore
        rs = req.rescore[0]
        return "impact", _with_geometry(
            ("fused-program", k,
             pow2_bucket(max(int(rs.window_size), 1)),
             str(rs.score_mode), query_shape(req.query),
             query_shape(rs.query)))
    lane = "impact" if jit_exec.impact_plane_config(
        searcher.ctx.index_name) is not None else "plane"
    return lane, _with_geometry((k, query_shape(req.query)))


class _Waiter:
    __slots__ = ("req", "future", "enq_t", "deadline", "task", "picked",
                 "queue_ms", "bound_run")

    def __init__(self, req, deadline, task):
        self.req = req
        self.future: Future = Future()
        self.enq_t = time.perf_counter()
        self.deadline = deadline        # monotonic, or None
        self.task = task
        self.picked = threading.Event()
        self.queue_ms = 0.0
        # the submitting thread's observability context (trace ctx,
        # span collectors, attribution record) bound to an invoker:
        # single-waiter batches run launch/drain under it, so a
        # profiled / slow-logged request keeps its device spans and
        # program/device attribution even though the dispatch happens
        # on the scheduler's threads. Multi-waiter batches skip it —
        # one dispatch cannot attribute to N requests (the msearch
        # batching trade, unchanged).
        from elasticsearch_tpu.observability import tracing as obs_trace
        self.bound_run = obs_trace.bind_context(_invoke)


#: caller-side backstop on ``execute()``'s picked/result waits — the
#: watchdog abandon resolves every batch's waiters long before this;
#: the backstop only guards a disabled/dead watchdog (a timed-out
#: caller runs its serial path; the waiter's accounting is untouched)
EXECUTE_BACKSTOP_S = 600.0


class _BatchState:
    """Per-launched-batch abandon/finish state: the scheduler lock
    arbitrates the race between the drain worker finishing and the
    watchdog monitor abandoning, so the in-flight permit releases
    exactly once and a late (post-abandon) completion is discarded."""

    __slots__ = ("live", "finished", "abandoned")

    def __init__(self, live):
        self.live = live
        self.finished = False
        self.abandoned = False


class _LaneQueue:
    __slots__ = ("key", "lane", "waiters", "launch", "drain")

    def __init__(self, key, lane, launch, drain):
        self.key = key
        self.lane = lane
        self.waiters: deque = deque()
        # the creating waiter's callables serve every batch this queue
        # forms: the key pins reader identity + shape, so any member's
        # launch is interchangeable
        self.launch = launch
        self.drain = drain


class ContinuousBatchScheduler:
    """Per-node continuous-batching scheduler in front of the compiled
    batch programs. ``execute()`` blocks the calling (search-pool)
    thread until its own result is ready; formation, launch and drain
    ride the scheduler's dispatcher + drain workers."""

    def __init__(self, node_id: str | None = None, max_batch: int = 32,
                 max_in_flight: int = 4, max_queue: int = 1024,
                 max_queue_wait_s: float = 2.0,
                 weights: dict | None = None,
                 priorities: dict | None = None,
                 shed_threshold: float | None = 10.0,
                 enabled: bool = True, pad_to_bucket: bool = True):
        self.node_id = node_id
        self.enabled = enabled
        self.max_batch = max(int(max_batch), 1)
        self.max_in_flight = max(int(max_in_flight), 1)
        self.max_queue = max(int(max_queue), 1)
        self.max_queue_wait_s = float(max_queue_wait_s)
        self.pad_to_bucket = pad_to_bucket
        self.weights = dict(DEFAULT_WEIGHTS, **(weights or {}))
        self.priorities = dict(DEFAULT_PRIORITIES, **(priorities or {}))
        #: queue_wait burn multiple that opens the shed gate (None/<=0
        #: disables SLO shedding)
        self.shed_threshold = None if not shed_threshold \
            or float(shed_threshold) <= 0 else float(shed_threshold)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: dict = {}
        self._wrr: list = []            # lane pickup cycle, weight-expanded
        for lane in sorted(self.weights):
            self._wrr.extend([lane] * max(int(self.weights[lane]), 1))
        self._wrr_pos = 0
        self._inflight_sem = threading.BoundedSemaphore(self.max_in_flight)
        self._dispatcher: threading.Thread | None = None
        self._closed = False
        # counters (all under _lock; stats() snapshots one consistent
        # view so submitted == queued + in_flight + delivered + declined
        # + shed holds at EVERY sample)
        self._submitted = 0
        self._queued = 0
        self._inflight_reqs = 0
        self._delivered = 0
        self._declined = 0
        self._shed = 0
        self._shed_reasons: dict = {}
        self._batches_launched = 0
        self._batches_inflight = 0
        self._batches_drained = 0
        self._batches_abandoned = 0
        self._inflight_hw = 0
        self._pad_rows = 0
        # SLO-burn shed gate: the scheduler's OWN queue-wait good/bad
        # book (classified against the node's queue_wait SLO target) —
        # the shared queue_wait lane also carries threadpool samples,
        # and the scheduler must shed on ITS queue's burn, not a
        # neighbor's. Recompute throttled to 1/s.
        self._shed_gate_lock = threading.Lock()
        self._shed_level = 0
        self._shed_raw_prev = 0
        self._shed_at = 0.0
        self._slo_prev = (0, 0)
        self._qw_good = 0
        self._qw_bad = 0
        self._qw_target_ms = 50.0       # refreshed from slo config

    # ---- admission ---------------------------------------------------------

    def submit(self, lane: str, key, req, launch, drain=None) -> _Waiter:
        """Admission predicate of the ``scheduler`` lane: every shed and
        decline is reason-labeled here or at pickup
        (``jit_exec.note_scheduler_shed`` ←
        ``lanes.LANE_REASONS["scheduler"]``). Raises
        :class:`SchedulerRejectedError` (429) for SLO-burn and
        queue-capacity sheds; a declined waiter resolves to
        :data:`DECLINED` and the caller runs its serial path."""
        from elasticsearch_tpu.search import jit_exec
        from elasticsearch_tpu.tasks import current_task
        task = current_task()
        deadline = getattr(task, "deadline", None) if task is not None \
            else None
        w = _Waiter(req, deadline, task)
        if self._closed:
            jit_exec.note_scheduler_shed("closed")
            with self._lock:
                self._submitted += 1
                self._note_shed_locked("closed")
            w.picked.set()
            w.future.set_result(DECLINED)
            return w
        # SLO-burn shedding needs LOAD evidence from this scheduler,
        # not just a hot queue_wait book (the threadpool shares the
        # lane): with an empty queue the next pickup is immediate, so
        # shedding would be pure loss — admission throttling starts
        # only when a backlog exists
        level = self._shed_gate() if self._queued else 0
        if level >= self.priorities.get(lane, 2):
            jit_exec.note_scheduler_shed("slo-shed")
            with self._lock:
                self._submitted += 1
                self._note_shed_locked("slo-shed")
            raise SchedulerRejectedError(
                lane, "slo-shed",
                f"scheduler shed [{lane}] work: queue_wait SLO burn at "
                f"shed level {level} (search.scheduler.shed)")
        full = False
        with self._lock:                    # == the condition's lock
            if self._closed:
                pass                        # raced close(): fall through
            elif self._queued >= self.max_queue:
                full = True
                self._submitted += 1
                self._note_shed_locked("queue-full")
            else:
                q = self._queues.get(key)
                if q is None:
                    q = self._queues[key] = _LaneQueue(key, lane, launch,
                                                       drain)
                q.waiters.append(w)
                self._submitted += 1
                self._queued += 1
                self._ensure_dispatcher_locked()
                self._cond.notify()
                return w
        if full:
            jit_exec.note_scheduler_shed("queue-full")
            raise SchedulerRejectedError(
                lane, "queue-full",
                f"scheduler queue at capacity ({self.max_queue}) — "
                f"[{lane}] request rejected")
        jit_exec.note_scheduler_shed("closed")
        with self._lock:
            self._submitted += 1
            self._note_shed_locked("closed")
        w.picked.set()
        w.future.set_result(DECLINED)
        return w

    def execute(self, lane: str, key, req, launch, drain=None):
        """Blocking entry: queue, wait under a ``scheduler.queue`` span
        (PR 8 — the span covers exactly the queue wait), then wait for
        the batch's result. → result, or None when the scheduler
        declined (caller runs its serial path). Raises
        :class:`SchedulerRejectedError` when shed at admission."""
        from elasticsearch_tpu.observability import tracing as obs_trace
        w = self.submit(lane, key, req, launch, drain)
        if obs_trace.active():
            with obs_trace.span("scheduler.queue", lane=lane) as sp:
                w.picked.wait(EXECUTE_BACKSTOP_S)
                sp.set(queue_ms=round(w.queue_ms, 3))
        try:
            out = w.future.result(timeout=EXECUTE_BACKSTOP_S)
        except FutTimeout:
            # the watchdog should have abandoned this batch long ago;
            # the backstop fails the CALLER over to its serial path
            # without touching the waiter's books (a late delivery
            # still reconciles — the caller just isn't listening)
            return None
        if out is DECLINED:
            return None
        return out

    # ---- dispatcher --------------------------------------------------------

    def _ensure_dispatcher_locked(self) -> None:
        if self._dispatcher is None or not self._dispatcher.is_alive():
            t = threading.Thread(target=self._dispatch_loop, daemon=True,
                                 name="sched-dispatch")
            self._dispatcher = t
            t.start()

    def _dispatch_loop(self) -> None:
        from elasticsearch_tpu.observability import use_node
        ctx = use_node(self.node_id) if self.node_id is not None \
            else nullcontext()
        with ctx:
            try:
                self._dispatch_inner()
            finally:
                self._flush_closed()

    def _dispatch_inner(self) -> None:
        while True:
            with self._cond:
                while not self._closed and self._queued == 0:
                    self._cond.wait(0.25)
                if self._closed:
                    return
            # bound launched-but-undrained work BEFORE forming the
            # batch: while the in-flight window is full, arrivals keep
            # queueing — the next batch forms larger for free (the
            # continuous-batching win)
            self._inflight_sem.acquire()
            try:
                with self._lock:
                    q, batch = self._next_batch_locked()
                if q is None:
                    self._inflight_sem.release()
                    continue
                live = self._screen_pickup(batch)
                if not live:
                    self._inflight_sem.release()
                    continue
                self._launch_batch(q, live)
            except Exception:            # noqa: BLE001 — dispatcher must
                self._inflight_sem.release()   # survive any batch error
                raise

    def _next_batch_locked(self):
        """Weighted-fair pickup: cycle lanes by WRR weight, serve the
        chosen lane's oldest-head queue FIFO, up to max_batch. Empty
        queues are dropped (shape keys churn with reader generations)."""
        nonempty: dict = {}
        for key in list(self._queues):
            q = self._queues[key]
            if not q.waiters:
                del self._queues[key]
                continue
            nonempty.setdefault(q.lane, []).append(q)
        if not nonempty:
            return None, None
        chosen = None
        for step in range(len(self._wrr)):
            lane = self._wrr[(self._wrr_pos + step) % len(self._wrr)]
            if lane in nonempty:
                self._wrr_pos = (self._wrr_pos + step + 1) % len(self._wrr)
                chosen = nonempty[lane]
                break
        if chosen is None:                # lanes outside the WRR table
            chosen = next(iter(nonempty.values()))
        q = min(chosen, key=lambda c: c.waiters[0].enq_t)
        batch = []
        while q.waiters and len(batch) < self.max_batch:
            batch.append(q.waiters.popleft())
        self._queued -= len(batch)
        self._inflight_reqs += len(batch)
        if not q.waiters:
            self._queues.pop(q.key, None)
        return q, batch

    def _screen_pickup(self, batch: list) -> list:
        """Queue-time shedding at pickup: a cancelled task aborts (PR 2
        semantics), a blown deadline — the task's or the scheduler's own
        ``max_queue_wait_s`` bound — is shed back to the serial path,
        which owns the timed_out accounting. Returns the live waiters."""
        from elasticsearch_tpu.common.errors import TaskCancelledError
        from elasticsearch_tpu.search import jit_exec
        now_m = time.monotonic()
        now_p = time.perf_counter()
        # the watchdog quarantined the device: redirect the whole
        # pickup to the serial path instead of launching into a known
        # wedge (new arrivals stop at the caller's breaker check; this
        # drains what queued before the quarantine)
        quarantined = jit_exec.plane_breaker.quarantined
        live = []
        for w in batch:
            if quarantined:
                jit_exec.note_scheduler_shed("device-stall")
                with self._lock:
                    self._inflight_reqs -= 1
                    self._note_shed_locked("device-stall")
                w.picked.set()
                w.future.set_result(DECLINED)
                continue
            if w.task is not None and w.task.cancelled:
                jit_exec.note_scheduler_shed("task-cancelled")
                with self._lock:
                    self._inflight_reqs -= 1
                    self._note_shed_locked("task-cancelled")
                w.picked.set()
                w.future.set_exception(TaskCancelledError(
                    f"task [{w.task.task_id}] was cancelled while "
                    f"queued [{w.task.cancel_reason or 'unknown'}]"))
                continue
            blown = (w.deadline is not None and now_m > w.deadline) or \
                (now_p - w.enq_t > self.max_queue_wait_s)
            if blown:
                jit_exec.note_scheduler_shed("queue-deadline")
                with self._lock:
                    self._inflight_reqs -= 1
                    self._note_shed_locked("queue-deadline")
                w.picked.set()
                w.future.set_result(DECLINED)
                continue
            live.append(w)
        return live

    def _launch_batch(self, q: _LaneQueue, live: list) -> None:
        """Commit one formed batch to a drain worker. The worker owns
        BOTH launch and drain — a device dispatch can *hang*, and a
        hang on the dispatcher's own thread would wedge the whole
        scheduler; on a worker the watchdog abandons the wait and the
        dispatcher keeps feeding (the stall-tolerance contract). A
        batch counts ``launched`` when committed here and leaves the
        books exactly once: ``drained`` (worker finished — even on a
        launch error, matching the sync lane's accounting) or
        ``abandoned`` (watchdog gave up on the wait)."""
        from elasticsearch_tpu.observability import histograms as obs_hist
        from elasticsearch_tpu.search import jit_exec
        t_pick = time.perf_counter()
        bad = 0
        for w in live:
            w.queue_ms = (t_pick - w.enq_t) * 1e3
            obs_hist.observe_lane("queue_wait", w.queue_ms,
                                  self.node_id or "")
            bad += w.queue_ms > self._qw_target_ms
            w.picked.set()
        with self._lock:
            self._qw_good += len(live) - bad
            self._qw_bad += bad
        state = _BatchState(live)
        runner = live[0].bound_run if len(live) == 1 else None
        if runner is _invoke:
            runner = None               # no context was active at submit
        if q.drain is None:
            with self._lock:
                self._batches_launched += 1
                self._batches_inflight += 1
                self._inflight_hw = max(self._inflight_hw,
                                        self._batches_inflight)
            jit_exec.note_scheduler_batch(len(live), 0)
            self._spawn_worker(self._run_sync, q, live, runner, state)
            return
        reqs = [w.req for w in live]
        padded = 0
        if self.pad_to_bucket and len(reqs) < self.max_batch:
            # pad up to the program cache's pow2 bucket with a no-op
            # replica of the FIRST request: pad rows are sliced off
            # before delivery and excluded from lane stats via n_real —
            # never re-serving other queued requests (the old
            # pad_to_bucket wart double-counted them)
            bucket = pow2_bucket(len(reqs), self.max_batch)
            padded = bucket - len(reqs)
            reqs = reqs + [reqs[0]] * padded
        with self._lock:
            self._batches_launched += 1
            self._batches_inflight += 1
            self._pad_rows += padded
            self._inflight_hw = max(self._inflight_hw,
                                    self._batches_inflight)
        jit_exec.note_scheduler_batch(len(live), padded)
        self._spawn_worker(self._run_pipelined, q, live, runner, reqs,
                           state)

    def _spawn_worker(self, fn, *args) -> None:
        """One DAEMON worker thread per committed batch. Not a bounded
        pool on purpose: a wedged batch parks its worker on the device
        indefinitely (non-cancellable), and under repeated stalls a
        bounded pool starves — batches queue behind wedged threads and
        never even reach watchdog registration. Concurrency is still
        bounded by ``_inflight_sem`` (abandons release the permit, so
        live batches, not wedged threads, own the window), and daemon
        threads never block interpreter exit on a wedge. Each worker
        runs under this scheduler's node context so compiles, costs,
        spans and ledger charges attribute to the owning node exactly
        like the dispatcher thread."""
        def run() -> None:
            from elasticsearch_tpu.observability import use_node
            ctx = use_node(self.node_id) if self.node_id is not None \
                else nullcontext()
            with ctx:
                fn(*args)

        threading.Thread(target=run, daemon=True,
                         name="sched-batch").start()

    def _run_sync(self, q: _LaneQueue, live: list, runner,
                  state: _BatchState) -> None:
        """Whole-batch execution for sync (launch-only) lanes, under a
        registered watchdog wait."""
        from elasticsearch_tpu.search import watchdog as wd
        entry = wd.dispatch_watchdog.register(
            site="dispatch", lane=q.lane, shape_key=q.key,
            n_real=len(live),
            on_stall=lambda err: self._abandon_batch(state))
        try:
            reqs = [w.req for w in live]
            results = runner(q.launch, reqs) if runner is not None \
                else q.launch(reqs)
        except Exception:                # noqa: BLE001 — serial retry owns it
            results = None
        wd.dispatch_watchdog.complete(entry)
        self._finish_batch(state, live, results)

    def _run_pipelined(self, q: _LaneQueue, live: list, runner,
                       reqs: list, state: _BatchState) -> None:
        """Launch + drain for pipelined lanes, on a worker thread: the
        async launch overlaps the previous batch's drain exactly as
        before (the dispatcher keeps forming batches while this worker
        blocks on the device), but a wedged dispatch now wedges only
        THIS worker — the watchdog abandons the wait and the in-flight
        permit, and the dispatcher never stops."""
        from elasticsearch_tpu.search import watchdog as wd
        entry = wd.dispatch_watchdog.register(
            site="dispatch", lane=q.lane, shape_key=q.key,
            n_real=len(live),
            on_stall=lambda err: self._abandon_batch(state))
        results = None
        try:
            if runner is not None:
                handle = runner(q.launch, reqs, n_real=len(live))
            else:
                handle = q.launch(reqs, n_real=len(live))
            if handle is not None:
                results = runner(q.drain, handle) if runner is not None \
                    else q.drain(handle)
        except Exception:                # noqa: BLE001 — serial retry owns it
            results = None
        wd.dispatch_watchdog.complete(entry)
        self._finish_batch(state, live, results)

    def _finish_batch(self, state: _BatchState, live: list,
                      results) -> None:
        """Worker-side batch completion: exactly one of finish/abandon
        wins under the lock. A late completion of an abandoned batch
        discards its results — the waiters already failed over and the
        abandon path already released the permit and settled the
        books."""
        from elasticsearch_tpu.search import jit_exec
        with self._lock:
            if state.abandoned:
                return
            state.finished = True
            self._batches_inflight -= 1
            self._batches_drained += 1
        self._inflight_sem.release()
        jit_exec.note_scheduler_drain()
        self._deliver(live, results)

    def _abandon_batch(self, state: _BatchState) -> bool:
        """Watchdog-side batch abandonment (runs on the monitor thread;
        the wedged worker still blocks on the device — only the WAIT is
        abandoned): shed every waiter back to its serial path with
        registered reason ``device-stall``, settle the batch books, and
        release the in-flight permit so the dispatcher's window never
        shrinks under a wedge."""
        from elasticsearch_tpu.search import jit_exec
        with self._lock:
            if state.finished or state.abandoned:
                return False
            state.abandoned = True
            self._batches_inflight -= 1
            self._batches_abandoned += 1
            self._inflight_reqs -= len(state.live)
            for _ in state.live:
                self._note_shed_locked("device-stall")
        jit_exec.note_scheduler_shed("device-stall", len(state.live))
        self._inflight_sem.release()
        for w in state.live:
            w.picked.set()
            if not w.future.done():
                w.future.set_result(DECLINED)
        return True

    def _deliver(self, live: list, results) -> None:
        if results is None:
            self._deliver_declined(live)
            return
        # slice to the REAL waiters: pad rows never deliver (and never
        # counted — note_scheduler_batch took n_real)
        for w, res in zip(live, results):
            if not w.future.done():
                w.future.set_result(res)
        with self._lock:
            self._inflight_reqs -= len(live)
            self._delivered += len(live)

    def _deliver_declined(self, live: list) -> None:
        for w in live:
            w.picked.set()
            if not w.future.done():
                w.future.set_result(DECLINED)
        with self._lock:
            self._inflight_reqs -= len(live)
            self._declined += len(live)

    # ---- SLO-burn shed gate ------------------------------------------------

    def _note_shed_locked(self, reason: str) -> None:
        self._shed += 1
        self._shed_reasons[reason] = self._shed_reasons.get(reason, 0) + 1

    def _shed_gate(self) -> int:
        """Current shed level from the windowed ``queue_wait`` SLO burn
        of THIS scheduler's queue (good/bad classified against the
        node's queue_wait target — the PR 13 SLO book the pickup seam
        feeds): 0 below threshold t, 1 at ≥t, 2 at ≥2t, 3 at ≥4t.
        Recomputed at most 1/s so admission pays a dict read."""
        if self.shed_threshold is None:
            return 0
        now = time.monotonic()
        with self._shed_gate_lock:
            if now - self._shed_at < 1.0:
                return self._shed_level
            self._shed_at = now
            from elasticsearch_tpu.observability import slo
            doc = slo.stats(self.node_id or "")
            st = doc["lanes"].get("queue_wait")
            if st is not None:
                self._qw_target_ms = st["target_ms"]
            with self._lock:
                good, bad = self._qw_good, self._qw_bad
            pg, pb = self._slo_prev
            self._slo_prev = (good, bad)
            dg, db = good - pg, bad - pb
            raw = 0
            if dg + db >= SHED_MIN_SAMPLES:
                burn = slo.burn_rate(dg, db, doc["objective"])
                t = self.shed_threshold
                if burn >= t:
                    raw = 1 + (burn >= 2 * t) + (burn >= 4 * t)
            # hysteresis: shed only on SUSTAINED burn — two consecutive
            # windows at the level. A transient spike (a compile burst
            # stalling the dispatcher for one window) must not 429 users
            self._shed_level = min(raw, self._shed_raw_prev)
            self._shed_raw_prev = raw
            return self._shed_level

    # ---- stats / lifecycle -------------------------------------------------

    def stats(self) -> dict:
        """The ``_nodes/stats.scheduler`` document. ``reconciled`` is
        the sample-time invariant the bench and chaos scenarios assert:
        every submitted request is exactly one of queued / in-flight /
        delivered / declined / shed, and every launched batch is
        drained or in flight."""
        with self._lock:
            queues = {}
            for q in self._queues.values():
                queues[q.lane] = queues.get(q.lane, 0) + len(q.waiters)
            doc = {
                "enabled": self.enabled,
                "max_batch": self.max_batch,
                "max_in_flight": self.max_in_flight,
                "queue_depth": self._queued,
                "queue_depth_by_lane": queues,
                "submitted": self._submitted,
                "in_flight_requests": self._inflight_reqs,
                "delivered": self._delivered,
                "declined": self._declined,
                "shed": self._shed,
                "shed_reasons": dict(self._shed_reasons),
                "batches_launched": self._batches_launched,
                "batches_in_flight": self._batches_inflight,
                "batches_drained": self._batches_drained,
                "batches_abandoned": self._batches_abandoned,
                "in_flight_high_water": self._inflight_hw,
                "pad_rows": self._pad_rows,
                "reconciled": (
                    self._submitted == self._queued + self._inflight_reqs
                    + self._delivered + self._declined + self._shed
                    and self._batches_launched == self._batches_drained
                    + self._batches_inflight + self._batches_abandoned),
            }
        return doc

    def _flush_closed(self) -> None:
        """Resolve every queued waiter with DECLINED on shutdown — the
        serial path still serves them; nobody hangs on a future the
        dead dispatcher would never complete."""
        from elasticsearch_tpu.search import jit_exec
        with self._lock:
            leftovers = [w for q in self._queues.values()
                         for w in q.waiters]
            for q in self._queues.values():
                q.waiters.clear()
            self._queues.clear()
            self._queued -= len(leftovers)
            for _ in leftovers:
                self._note_shed_locked("closed")
        if leftovers:
            jit_exec.note_scheduler_shed("closed", len(leftovers))
        for w in leftovers:
            w.picked.set()
            if not w.future.done():
                w.future.set_result(DECLINED)

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
            dispatcher = self._dispatcher
        if dispatcher is not None:
            dispatcher.join(timeout=5.0)
        else:
            self._flush_closed()
        # in-flight batch workers are daemon threads that resolve their
        # own waiters (or the watchdog abandons them) — close() never
        # waits on a possibly-wedged device drain


def settings_for(get) -> dict:
    """Constructor kwargs from node settings (``get`` is
    ``settings.get``-shaped): ``search.scheduler.{enabled,max_batch,
    max_in_flight,max_queue,fairness,shed}``. ``fairness`` is a
    ``lane:weight,...`` string overriding the WRR weights; ``shed`` is
    the queue_wait burn multiple that opens the shed gate (default
    10.0 — i.e. ≥10 % of a window's pickups late under the default
    0.99 objective; "off" disables)."""
    def _flag(key, default):
        val = get(key)
        return default if val is None \
            else str(val).lower() not in ("false", "0")
    kwargs = {
        "enabled": _flag("search.scheduler.enabled", True),
        "max_batch": int(get("search.scheduler.max_batch") or 32),
        "max_in_flight": int(get("search.scheduler.max_in_flight") or 4),
        "max_queue": int(get("search.scheduler.max_queue") or 1024),
    }
    raw = get("search.scheduler.fairness")
    if raw:
        weights = {}
        for part in str(raw).split(","):
            lane, _, wt = part.partition(":")
            if lane.strip() and wt.strip():
                weights[lane.strip()] = int(wt)
        if weights:
            kwargs["weights"] = weights
    shed = get("search.scheduler.shed")
    if shed is not None:
        kwargs["shed_threshold"] = None \
            if str(shed).lower() in ("off", "false", "0") else float(shed)
    return kwargs
