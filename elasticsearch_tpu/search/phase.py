"""Query phase and fetch phase (per shard).

Reference split: SearchService.executeQueryPhase/executeFetchPhase
(core/search/SearchService.java:293,385-504) with QueryPhase building the
collector stack and FetchPhase materializing `_source`
(core/search/query/QueryPhase.java:99-314, core/search/fetch/FetchPhase.java:98).

Here the query phase walks segments of the shard's DeviceReader: the
executor lowers the query AST to device ops, the live bitmap and optional
post_filter mask in, then per-segment device top-k results merge (still on
device) into the shard's top-k — only k (score, doc) pairs ever leave the
device. Sort-by-field runs on host columns (numpy argsort) for exact f64
semantics. The fetch phase resolves winning global doc ids to _id/_source
and runs sub-phases (source filtering, highlight, script fields analog).
"""

from __future__ import annotations

import fnmatch
import threading
import time
from dataclasses import dataclass, field
from typing import Any

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.common.errors import (QueryParsingError,
                                             TaskCancelledError)
from elasticsearch_tpu.index.device_reader import DeviceReader
from elasticsearch_tpu.ops import topk as topk_ops
from elasticsearch_tpu.search import query_dsl as q
from elasticsearch_tpu.search.aggregations import (
    AggNode, ShardAggContext, collect, parse_aggs)
from elasticsearch_tpu.search.execute import ExecutionContext, SegmentExecutor
from elasticsearch_tpu.search.highlight import highlight_hit
from elasticsearch_tpu.search.query_dsl import parse_query


@dataclass
class RescoreSpec:
    """One rescore pass (ref: core/search/rescore/QueryRescorer.java +
    RescoreParseElement): re-rank the top window_size hits of each shard
    by combining the primary score with a rescore-query score."""
    query: q.Query
    window_size: int = 10
    query_weight: float = 1.0
    rescore_query_weight: float = 1.0
    score_mode: str = "total"          # total | multiply | avg | max | min


@dataclass
class ParsedSearchRequest:
    query: q.Query
    from_: int = 0
    size: int = 10
    sort: list = field(default_factory=list)       # [{"field": {"order": ...}}...]
    aggs: list[AggNode] = field(default_factory=list)
    post_filter: q.Query | None = None
    min_score: float | None = None
    source_filter: Any = True                      # True | False | includes spec
    highlight: dict | None = None
    search_after: list | None = None
    track_total_hits: bool = True
    explain: bool = False
    script_fields: dict = field(default_factory=dict)
    suggest: list = field(default_factory=list)    # [SuggestSpec]
    stored_fields: list = field(default_factory=list)
    docvalue_fields: list = field(default_factory=list)
    version: bool = False                          # render _version per hit
    terminate_after: int | None = None             # per-shard collected cap
    timeout_ms: float | None = None                # per-shard time budget
    rescore: list[RescoreSpec] = field(default_factory=list)
    # top-level "knn" search section (dense / late-interaction lane;
    # combined with `query` → in-program hybrid fusion)
    knn: q.KnnSection | None = None


def _task_budget(req: ParsedSearchRequest):
    """→ (current task, effective monotonic deadline): the tighter of
    the request's own timeout and the executing task's deadline (the
    coordinator wires `timeout` through the task so a shard's budget
    shrinks by the wall time already spent queueing and fanning out)."""
    from elasticsearch_tpu.tasks import current_task
    task = current_task()
    deadline = None if req.timeout_ms is None \
        else time.monotonic() + req.timeout_ms / 1000.0
    if task is not None and task.deadline is not None:
        deadline = task.deadline if deadline is None \
            else min(deadline, task.deadline)
    return task, deadline


def _checkpoint(task) -> None:
    """Cooperative cancellation checkpoint at a segment boundary."""
    if task is not None and task.cancelled:
        raise TaskCancelledError(
            f"task [{task.task_id}] was cancelled "
            f"[{task.cancel_reason or 'unknown'}]")


def parse_search_request(body: dict | None) -> ParsedSearchRequest:
    body = body or {}
    req = ParsedSearchRequest(query=parse_query(body.get("query")))
    req.from_ = int(body.get("from", 0))
    req.size = int(body.get("size", 10))
    raw_sort = body.get("sort", [])
    if isinstance(raw_sort, (str, dict)):
        raw_sort = [raw_sort]
    for s in raw_sort:
        if isinstance(s, str):
            req.sort.append({s: {"order": "desc" if s == "_score" else "asc"}})
        else:
            req.sort.append({k: ({"order": v} if isinstance(v, str) else v)
                             for k, v in s.items()})
    req.aggs = parse_aggs(body.get("aggs", body.get("aggregations")))
    if "post_filter" in body:
        req.post_filter = parse_query(body["post_filter"])
    if body.get("min_score") is not None:
        req.min_score = float(body["min_score"])
    req.source_filter = body.get("_source", True)
    req.highlight = body.get("highlight")
    req.search_after = body.get("search_after")
    req.explain = bool(body.get("explain", False))
    req.version = bool(body.get("version", False))
    req.script_fields = body.get("script_fields", {})
    raw_dvf = body.get("fielddata_fields", body.get("docvalue_fields", []))
    req.docvalue_fields = [raw_dvf] if isinstance(raw_dvf, str) \
        else list(raw_dvf)
    req.stored_fields = body.get("stored_fields", body.get("fields", []))
    if isinstance(req.stored_fields, str):
        req.stored_fields = [req.stored_fields]
    if req.stored_fields and "_source" not in body:
        # `fields` without an explicit _source suppresses the source
        # (FetchSourceContext.DO_NOT_FETCH_SOURCE unless "_source" listed)
        if "_source" in req.stored_fields:
            req.stored_fields = [f for f in req.stored_fields
                                 if f != "_source"]
        else:
            req.source_filter = False
    if body.get("terminate_after"):
        req.terminate_after = int(body["terminate_after"])
    tth = body.get("track_total_hits")
    if tth is not None and str(tth).lower() in ("false", "0"):
        # totals not tracked: the block-max impact lane may skip blocks
        # (a skipped block's matches are never counted); any other value
        # keeps exact totals
        req.track_total_hits = False
    if body.get("timeout") is not None:
        from elasticsearch_tpu.common.settings import parse_time_value
        req.timeout_ms = parse_time_value(body["timeout"], "timeout") * 1000.0
    from elasticsearch_tpu.search.suggest import parse_suggest
    req.suggest = parse_suggest(body.get("suggest"))
    raw_rescore = body.get("rescore")
    if raw_rescore:
        if isinstance(raw_rescore, dict):
            raw_rescore = [raw_rescore]
        for spec in raw_rescore:
            inner = spec.get("query", {})
            if "rescore_query" not in inner:
                raise QueryParsingError("rescore requires [rescore_query]")
            mode = str(inner.get("score_mode", "total")).lower()
            if mode not in ("total", "multiply", "avg", "max", "min"):
                raise QueryParsingError(
                    f"illegal rescore score_mode [{mode}]")
            req.rescore.append(RescoreSpec(
                query=parse_query(inner["rescore_query"]),
                window_size=int(spec.get("window_size", 10)),
                query_weight=float(inner.get("query_weight", 1.0)),
                rescore_query_weight=float(
                    inner.get("rescore_query_weight", 1.0)),
                score_mode=mode))
        if req.sort:
            raise QueryParsingError(
                "rescore cannot be combined with sort (QueryRescorer "
                "re-ranks by score)")
    if body.get("knn") is not None:
        req.knn = q.parse_knn_section(body["knn"])
        req.knn.hybrid = "knn" in body and "query" in body
        # v1 lane surface: the knn section composes with from/size,
        # _source/fields/highlight and its own `filter`; request
        # features that would need rank-fused score arrays over the
        # whole corpus are rejected up front with a clear 400
        bad = [label for cond, label in (
            (bool(req.sort) and not _is_score_order(req.sort), "sort"),
            (bool(req.aggs), "aggs"),
            (req.post_filter is not None, "post_filter"),
            (req.min_score is not None, "min_score"),
            (req.search_after is not None, "search_after"),
            (bool(req.rescore), "rescore"),
            (bool(req.suggest), "suggest"),
            (req.terminate_after is not None, "terminate_after"),
        ) if cond]
        if bad:
            raise QueryParsingError(
                f"[knn] cannot be combined with {bad} — use the knn "
                f"section's own [filter] for filtering")
    return req


def _is_score_order(sort: list) -> bool:
    """True iff results follow the default (_score desc) order: no sort, or
    exactly [{"_score": {"order": "desc"}}]. An ASCENDING _score sort must
    take the field-sort path or its direction would be silently dropped."""
    if not sort:
        return True
    if len(sort) != 1 or "_score" not in sort[0]:
        return False
    return sort[0]["_score"].get("order", "desc") == "desc"


@dataclass
class ShardQueryResult:
    shard_id: int
    total: int
    max_score: float | None
    # top hits as host arrays (scores may be sort keys when sorting by field)
    doc_ids: np.ndarray            # global (reader-local) doc ids
    scores: np.ndarray             # f32 scores
    sort_values: list[list] | None  # per hit, when sort-by-field
    agg_partials: dict
    reader: DeviceReader
    terminated_early: bool = False  # terminate_after tripped on this shard
    timed_out: bool = False         # timeout budget tripped on this shard


class ShardSearcher:
    """Per-shard query execution over a DeviceReader."""

    def __init__(self, shard_id: int, reader: DeviceReader, mapper_service,
                 index_name: str = "", doc_slot: int | None = None,
                 dfs_stats: dict | None = None, version_fn=None):
        self.shard_id = shard_id
        self.reader = reader
        self.mapper_service = mapper_service
        # doc_id → live version (engine.doc_version) for version:true hits
        self.version_fn = version_fn
        # 11-bit (index, shard) slot for the _doc tie-break: doc ids use
        # bits 0-41, the slot bits 42-52 — all within float64's 53-bit
        # mantissa so cross-shard search_after cursors stay exact. The
        # coordinator assigns DENSE slots (its position in the request's
        # shard-group enumeration) so multi-index scrolls are collision-
        # free by construction; the crc fallback only serves local
        # single-index paths that never mix indices in one cursor.
        if doc_slot is None:
            import zlib
            doc_slot = ((zlib.crc32(index_name.encode()) * 31 + shard_id)
                        & 0x7FF)
        self._doc_slot = doc_slot & 0x7FF
        self.ctx = ExecutionContext(reader=reader,
                                    mapper_service=mapper_service,
                                    dfs_stats=dfs_stats,
                                    index_name=index_name or None)

    # -- mask/scores over every segment --------------------------------------

    def _execute_query(self, query: q.Query):
        """→ list of (scores, mask) device pairs, live-masked, per segment."""
        query = self._rewrite_joins(query)
        out = []
        for seg in self.reader.segments:
            ex = SegmentExecutor(seg, self.ctx)
            scores, mask = ex.execute(query)
            mask = mask & seg.live
            out.append((scores, mask))
        return out

    # ---- parent/child joins ------------------------------------------------

    def _rewrite_joins(self, query: q.Query) -> q.Query:
        """Shard-local parent/child join rewrite: children colocate with
        their parent (routing = parent id), so has_child/has_parent reduce
        to (1) run the inner query over the typed docs, (2) lift the
        per-doc scores through the _parent column host-side, (3) replace
        the node with a ParentIdsQuery the device resolves like ids.
        The reference's two-pass join (ChildrenQuery/ParentQuery,
        core/index/search/child/) does the same dance over Lucene
        ordinals; here the join state is a small id→score map."""
        if isinstance(query, q.HasChildQuery):
            inner = q.BoolQuery(
                must=[self._rewrite_joins(query.query)],
                filter=[q.TermQuery(field="_type", value=query.type)])
            scores: dict[str, list] = {}
            for seg, (sc, mask) in zip(self.reader.segments,
                                       self._execute_query(inner)):
                m = np.asarray(mask)
                s = np.asarray(sc)
                col = seg.seg.keyword_fields.get("_parent")
                if col is None:
                    continue
                for local in np.nonzero(m[:seg.seg.num_docs])[0]:
                    o = int(col.ords[int(local), 0])
                    if o >= 0:
                        scores.setdefault(col.vocab[o],
                                          []).append(float(s[int(local)]))
            mode = query.score_mode
            id_scores = {}
            for pid, vals in scores.items():
                n = len(vals)
                if n < max(query.min_children, 1) or \
                        (query.max_children and n > query.max_children):
                    continue
                if mode == "sum":
                    v = sum(vals)
                elif mode == "max":
                    v = max(vals)
                elif mode == "min":
                    v = min(vals)
                elif mode == "avg":
                    v = sum(vals) / n
                else:
                    v = 1.0
                id_scores[pid] = v
            return q.ParentIdsQuery(field="_id", id_scores=id_scores,
                                    boost=query.boost)
        if isinstance(query, q.HasParentQuery):
            inner = q.BoolQuery(
                must=[self._rewrite_joins(query.query)],
                filter=[q.TermQuery(field="_type",
                                    value=query.parent_type)])
            id_scores = {}
            for seg, (sc, mask) in zip(self.reader.segments,
                                       self._execute_query(inner)):
                m = np.asarray(mask)
                s = np.asarray(sc)
                for local in np.nonzero(m[:seg.seg.num_docs])[0]:
                    pid = seg.seg.ids[int(local)]
                    v = float(s[int(local)]) \
                        if query.score_mode == "score" else 1.0
                    id_scores[pid] = max(id_scores.get(pid, 0.0), v)
            return q.ParentIdsQuery(field="_parent", id_scores=id_scores,
                                    boost=query.boost)
        # recurse into compounds
        if isinstance(query, q.BoolQuery):
            return q.BoolQuery(
                must=[self._rewrite_joins(s) for s in query.must],
                should=[self._rewrite_joins(s) for s in query.should],
                must_not=[self._rewrite_joins(s) for s in query.must_not],
                filter=[self._rewrite_joins(s) for s in query.filter],
                minimum_should_match=query.minimum_should_match,
                boost=query.boost)
        for attr in ("query", "positive", "negative"):
            sub = getattr(query, attr, None)
            if isinstance(sub, q.Query):
                new = self._rewrite_joins(sub)
                if new is not sub:
                    import dataclasses as _dc
                    query = _dc.replace(query, **{attr: new})
        return query

    def _filter_masks_np(self, query: q.Query) -> np.ndarray:
        """Filter-context mask over the reader, memoized per (reader
        generation, filter shape) — the Lucene filter/query cache analog
        (ref: core/indices/cache/query/IndicesQueryCache.java:48): the
        same filter repeated across agg requests reuses its bitset until
        a refresh swaps the reader."""
        rd = self.reader.__dict__
        lock = rd.setdefault("_filter_cache_lock", threading.Lock())
        with lock:
            cache = rd.setdefault("_filter_mask_cache", {})
            stats = rd.setdefault(
                "_filter_cache_stats", {"hit_count": 0, "miss_count": 0,
                                        "evictions": 0})
            # key on the PRE-rewrite query: the join rewrite is the
            # expensive part and is deterministic within a reader
            # generation, so a hit must skip it too
            key = repr(query)
            hit = cache.get(key)
            if hit is not None:
                stats["hit_count"] += 1
                return hit
            stats["miss_count"] += 1
        query = self._rewrite_joins(query)   # agg filter contexts too
        masks = []
        for seg in self.reader.segments:
            ex = SegmentExecutor(seg, self.ctx)
            masks.append(np.asarray(ex.match_mask(query) & seg.live))
        out = np.concatenate(masks) if masks else np.zeros(0, bool)
        with lock:
            if len(cache) >= 256:           # bounded like the reference's
                cache.pop(next(iter(cache)))  # LRU-ish eviction
                stats["evictions"] += 1
            cache[key] = out
        return out

    # -- query phase ---------------------------------------------------------

    def query_phase(self, req: ParsedSearchRequest) -> ShardQueryResult:
        """One fused device program per segment (compile-cached across
        queries and same-shaped segments); falls back to the eager
        per-op walk if the plan/trace fails for an exotic query. Only the
        plan/trace seam is guarded — errors in parsing/aggs/sort raise
        normally without double execution."""
        from elasticsearch_tpu.search import jit_exec
        if req.knn is not None:
            # dense / late-interaction lane: compiled knn (or hybrid
            # fusion) program with an eager per-segment fallback —
            # breaker-gated and reason-labeled inside
            return self._knn_query_phase(req)
        rewritten = self._rewrite_joins(req.query)
        if rewritten is not req.query or (
                req.post_filter is not None):
            import dataclasses as _dc
            req = _dc.replace(
                req, query=rewritten,
                post_filter=None if req.post_filter is None
                else self._rewrite_joins(req.post_filter))
        # plane breaker: with the device marked unhealthy, go straight to
        # the eager executor instead of re-paying a failing dispatch per
        # query — the open breaker already knows how this would end; a
        # half-open probe is admitted below and reports back
        if not jit_exec.plane_breaker.allow():
            jit_exec.note_breaker_skip()
            return self._query_phase_eager(req)
        # Single-request fast path: delegate eligible requests to the
        # batched program with B=1. The batch program fuses scoring, merge
        # and packing into ONE dispatch + ONE device→host fetch; the
        # general path below pays one fetch per segment for counts plus
        # two for the merged top-k, and on a tunneled interconnect each
        # fetch is a full RTT (the request-at-a-time latency story).
        fast = self.query_phase_batch([req])
        if fast is not None:
            return fast[0]
        k = max(req.from_ + req.size, 1)
        if req.rescore:
            # the shard must collect at least the largest rescore window
            # (QueryRescorer re-ranks the top window of EACH shard)
            k = max(k, max(s.window_size for s in req.rescore))
        score_order = _is_score_order(req.sort)
        need_arrays = bool(req.aggs) or not score_order
        sa = req.search_after if (req.search_after is not None
                                  and not req.sort) else None
        terminated_early = timed_out = False
        task, deadline = _task_budget(req)
        try:
            outs = []
            running = 0
            for seg in self.reader.segments:
                _checkpoint(task)
                if deadline is not None and time.monotonic() > deadline:
                    timed_out = True           # partial results, remaining
                    break                      # segments skipped
                o = jit_exec.run_segment(
                    seg, self.ctx, req.query,
                    post_filter=req.post_filter, min_score=req.min_score,
                    search_after=sa, k=(k if score_order else None),
                    want_arrays=need_arrays)
                outs.append((seg, o))
                if req.terminate_after is not None or deadline is not None:
                    # early-termination modes need the running count /
                    # actual device completion → block per segment
                    # (QueryPhase.java:240-310 terminate-after + time-limit
                    # collector wrappers); without blocking, async dispatch
                    # would let device time escape the budget entirely
                    running += int(np.asarray(o["count"]))
                    if req.terminate_after is not None and \
                            running >= req.terminate_after:
                        terminated_early = True
                        break
        except (QueryParsingError, TaskCancelledError):
            # cancellation must ABORT, not fall back to the eager path —
            # re-running a cancelled query eagerly is the opposite of
            # shedding it
            raise
        except Exception as e:                # noqa: BLE001 — fallback seam
            jit_exec.note_fallback(e, reason="device-error")
            jit_exec.note_device_error(e)
            return self._query_phase_eager(req)
        jit_exec.plane_breaker.record_success()

        total = int(sum(int(np.asarray(o["count"])) for _, o in outs))
        if req.terminate_after is not None:
            # the reference reports the number of docs actually collected
            total = min(total, req.terminate_after)
        agg_partials = {}
        if req.aggs:
            # keep masks/scores ON DEVICE: the device agg fast path reduces
            # there and only bucket results cross to host; the numpy
            # fallback materializes lazily (early-terminated segments
            # contribute empty masks so columns stay reader-aligned)
            masks = [o["agg_mask"] for _, o in outs]
            scores = [o["scores"] for _, o in outs]
            for seg in self.reader.segments[len(outs):]:
                masks.append(jnp.zeros(seg.padded_docs, bool))
                scores.append(jnp.zeros(seg.padded_docs, jnp.float32))
            agg_partials = self._collect_aggs(req, masks, scores)

        if not outs:
            res = ShardQueryResult(self.shard_id, 0, None,
                                   np.zeros(0, np.int32),
                                   np.zeros(0, np.float32),
                                   [] if not score_order else None,
                                   agg_partials, self.reader)
        elif not score_order:
            per_seg = [(o["scores"], o["mask"]) for _, o in outs]
            res = self._sorted_query(req, per_seg, total, agg_partials,
                                     segments=[seg for seg, _ in outs])
        else:
            seg_scores = [o["top_scores"] for _, o in outs]
            seg_docs = [jnp.where(o["top_docs"] >= 0,
                                  o["top_docs"] + seg.doc_base, -1)
                        for seg, o in outs]
            res = self._finish_score_order(k, total, seg_scores, seg_docs,
                                           agg_partials)
        res.terminated_early = terminated_early
        res.timed_out = timed_out
        if req.rescore and res.sort_values is None:
            self._apply_rescore(req, res)
        return res

    def query_phase_batch(self, reqs: list[ParsedSearchRequest]
                          ) -> list[ShardQueryResult] | None:
        """Batched query phase: execute B score-ordered requests as ONE
        vmapped program per segment plus one batched cross-segment merge —
        the whole multi-query round trip is S+1 device dispatches instead
        of B×(S+1).

        The reference's _msearch fans requests out one at a time
        (core/action/search/TransportMultiSearchAction.java); on an
        accelerator the batch IS the unit of work, so this is the engine's
        primary high-throughput entry. Returns None when the batch is
        ineligible (aggs / sort-by-field / post_filter / min_score /
        search_after / suggest / partial-results modes) or the queries
        don't share one compiled plan — the caller then falls back to
        per-request :meth:`query_phase`.

        Implemented as launch + drain so a pipelined caller (the
        AdaptiveBatcher) can overlap batch N's device→host drain with
        batch N+1's device work — on a tunneled interconnect the drain
        round trip otherwise idles the chip for its full RTT.
        """
        handle = self.query_phase_batch_launch(reqs)
        if handle is None:
            return None
        return self.query_phase_batch_drain(handle)

    def query_phase_batch_launch(self, reqs: list[ParsedSearchRequest],
                                 n_real: int | None = None):
        """Phase 1 of the batched query phase: eligibility screen, ONE
        async device dispatch, and an async device→host copy kick-off.
        Returns an opaque handle for :meth:`query_phase_batch_drain`, or
        None when the batch is ineligible (caller falls back to serial
        :meth:`query_phase`). Never blocks on device results — JAX's
        async dispatch returns immediately and ``copy_to_host_async``
        starts the transfer in the background, so consecutive launches
        pipeline on the device while earlier drains ride the link.

        ``n_real`` (batching layers only): the first ``n_real`` rows are
        real queued requests, the rest pow2-bucket padding — lane
        admission stats count only the real rows, so a padded batch
        never double-counts."""
        from elasticsearch_tpu.search import planner
        from elasticsearch_tpu.tasks import current_task
        _checkpoint(current_task())
        if not reqs:
            return ("empty", [])
        # mixed knn/non-knn batches decline before planning — no single
        # compiled arm serves both shapes (the caller retries per
        # request, where each request plans onto its own arm)
        if any(r.knn is not None for r in reqs) and \
                not all(r.knn is not None for r in reqs):
            return None
        # the planner owns admission from here: it decomposes the batch
        # into priced candidate arms (knn/hybrid fusion, composed
        # impact→rescore, quantized impact, exact batch — each arm's
        # own eligibility screen retained), excludes device arms under
        # an open/quarantined breaker, and launches the cheapest
        # admissible arm under per-plan-node spans
        plan = planner.plan_batch(self, reqs, n_real=n_real)
        if plan is None:
            return None
        return planner.launch_plan(plan)

    def _exact_batch_launch(self, reqs: list, n_real: int | None = None):
        """The exact batched arm (the planner's tier-3 catch-all):
        generic eligibility screen + ONE reader-batch (or streamed)
        dispatch — the pre-planner default path, unchanged."""
        from elasticsearch_tpu.search import jit_exec
        for req in reqs:
            if (req.aggs or not _is_score_order(req.sort)
                    or req.post_filter is not None
                    or req.min_score is not None
                    or req.search_after is not None or req.suggest
                    or req.terminate_after is not None
                    or req.timeout_ms is not None or req.rescore
                    or req.knn is not None):
                return None
        k = max(max(req.from_ + req.size, 1) for req in reqs)
        queries = [req.query for req in reqs]
        if not self.reader.segments:
            return ("empty", reqs)
        # doc ids and counts survive the packed f32 fetch layout exactly
        # only below 2^24
        pack = self.reader.max_doc < (1 << 24)
        streamed = [s for s in self.reader.segments
                    if not getattr(s, "resident", True)]
        if streamed:
            # the streamed path is inherently synchronous (H2D double
            # buffering drives its own loop) — drain gets finished arrays
            res_sm = self._query_phase_batch_streamed(queries, k, streamed)
            if res_sm is None:
                return None
            return ("host", reqs, k, res_sm)
        try:
            out = jit_exec.run_reader_batch(self.reader.segments,
                                            self.ctx, queries, k=k,
                                            pack=pack, n_real=n_real)
        except QueryParsingError:
            raise
        except Exception as e:            # noqa: BLE001 — fallback seam
            jit_exec.note_fallback(e, reason="device-error")
            jit_exec.note_device_error(e)
            return None
        if out is None:                   # mixed plan signatures
            return None
        jit_exec.plane_breaker.record_success()
        for arr in ([out] if pack else
                    [out["top_scores"], out["top_docs"], out["count"]]):
            try:
                arr.copy_to_host_async()
            except Exception:             # noqa: BLE001 — optional fast path
                pass                      # drain's np.asarray still works
        return ("device", reqs, k, pack, out)

    def _impact_batch_launch(self, reqs: list, n_real: int | None = None):
        """Impact-lane admission + dispatch: serve B eligible requests
        from the quantized impact columns (jit_exec.run_impact_batch),
        with the block-max pruned sweep when no request tracks totals
        (jit_exec.run_impact_pruned). Opt-in per index
        (`index.search.impact_plane`) because quantized scores match
        the exact scorer only within the documented quantization bound
        — the exact scorer stays the default. Returns a drain handle or
        None (caller proceeds on the exact path); declines are
        reason-labeled via note_impact_fallback, mirroring the
        collective plane's admission accounting."""
        from elasticsearch_tpu.search import jit_exec
        from elasticsearch_tpu.search.execute import impact_terms
        cfg = jit_exec.impact_plane_config(self.ctx.index_name)
        if cfg is None or not reqs or not self.reader.segments:
            return None
        if self.ctx.dfs_stats is not None:
            # impacts bake READER-local idf; DFS global statistics
            # would score with different idf than the snapshot
            jit_exec.note_impact_fallback("dfs-stats")
            return None
        if any(not getattr(s, "resident", True)
               for s in self.reader.segments):
            jit_exec.note_impact_fallback("streamed-reader")
            return None
        specs = []
        for req in reqs:
            if (req.aggs or not _is_score_order(req.sort)
                    or req.post_filter is not None
                    or req.min_score is not None or req.suggest
                    or req.terminate_after is not None
                    or req.timeout_ms is not None or req.rescore
                    or req.explain):
                jit_exec.note_impact_fallback("ineligible-shape")
                return None
            if req.search_after is not None and \
                    len(req.search_after) not in (1, 2):
                jit_exec.note_impact_fallback("ineligible-cursor")
                return None
            spec = impact_terms(req.query, self.mapper_service,
                                max_terms=cfg.max_terms)
            if spec is None:
                jit_exec.note_impact_fallback("ineligible-query")
                return None
            specs.append(spec)
        if len({f for f, _, _ in specs}) != 1:
            jit_exec.note_impact_fallback("mixed-fields")
            return None
        field = specs[0][0]
        k = max(max(req.from_ + req.size, 1) for req in reqs)
        term_lists = [terms for _, terms, _ in specs]
        boosts = [boost for _, _, boost in specs]
        prune = cfg.prune and all(req.track_total_hits is False
                                  for req in reqs)
        try:
            pack = jit_exec.impact_pack_for(
                self.reader, field, cfg, k1=self.ctx.bm25.k1,
                b=self.ctx.bm25.b)
            if pack is None:
                jit_exec.note_impact_fallback("no-impact-columns")
                return None
            # cursor provenance: the in-program continuation compares
            # QUANTIZED scores, so a cursor minted by the exact scorer
            # (prior page fell back) or by a pre-requant quantization
            # would skip/duplicate hits across pages — verify each
            # cursor against the pack and decline the batch otherwise
            cursors = []
            for req, terms, boost in zip(reqs, term_lists, boosts):
                if req.search_after is None:
                    cursors.append(None)
                    continue
                cur = jit_exec.verify_impact_cursor(
                    pack, terms, boost, req.search_after)
                if cur is None:
                    jit_exec.note_impact_fallback("cross-lane-cursor")
                    return None
                cursors.append(cur)
            if prune and not pack.can_prune:
                prune = False               # block tables over budget
            mesh = jit_exec.serving_mesh()
            if mesh is not None:
                from elasticsearch_tpu.search.planner import \
                    prefer_mesh_serving
                if not prefer_mesh_serving("impact"):
                    mesh = None          # measured single-chip win
            if mesh is not None:
                out = jit_exec.run_impact_mesh(
                    self.reader, pack, mesh, term_lists, boosts,
                    cursors, k=k, prune=prune, n_real=n_real)
            else:
                run = jit_exec.run_impact_pruned if prune \
                    else jit_exec.run_impact_batch
                out = run(pack, term_lists, boosts, cursors, k=k,
                          n_real=n_real)
        except QueryParsingError:
            raise
        except Exception as e:            # noqa: BLE001 — fallback seam
            jit_exec.note_fallback(e, reason="device-error")
            jit_exec.note_device_error(e)
            jit_exec.note_impact_fallback("device-error")
            return None
        jit_exec.plane_breaker.record_success()
        for name in ("top_scores", "top_docs", "count"):
            try:
                out[name].copy_to_host_async()
            except Exception:             # noqa: BLE001 — optional
                pass
        return ("impact", reqs, k, out, prune, pack.total_blocks,
                n_real if n_real is not None else len(reqs))

    def _rescore_batch_launch(self, reqs: list,
                              n_real: int | None = None):
        """The planner's composed impact→rescore arm: impact-pruned/
        eager candidate generation feeding the QueryRescorer window
        combine as a device-side stage — one dispatch for primary
        scoring, secondary scoring AND the window re-sort
        (jit_exec.run_impact_rescore). Admission: the index opted into
        the impact plane, every request carries exactly ONE rescore
        pass with a shared score_mode, both the primary query and the
        rescore query are impact-scorable on the SAME field, and no
        cursors (rescore + search_after pagination stays serial).
        Declines return None — the quantized-impact and exact arms
        screen next (both reject rescore shapes, so the serial path
        serves the request as before this arm existed)."""
        from elasticsearch_tpu.search import jit_exec
        from elasticsearch_tpu.search.execute import impact_terms
        cfg = jit_exec.impact_plane_config(self.ctx.index_name)
        if cfg is None or not reqs or not self.reader.segments:
            return None
        if self.ctx.dfs_stats is not None:
            return None                   # impacts bake reader-local idf
        if any(not getattr(s, "resident", True)
               for s in self.reader.segments):
            return None
        specs, specs2, windows, qws, rws, modes = [], [], [], [], [], []
        for req in reqs:
            if (len(req.rescore) != 1 or req.aggs
                    or not _is_score_order(req.sort)
                    or req.post_filter is not None
                    or req.min_score is not None or req.suggest
                    or req.terminate_after is not None
                    or req.timeout_ms is not None or req.explain
                    or req.search_after is not None
                    or req.knn is not None):
                return None
            rs = req.rescore[0]
            spec = impact_terms(req.query, self.mapper_service,
                                max_terms=cfg.max_terms)
            spec2 = impact_terms(rs.query, self.mapper_service,
                                 max_terms=cfg.max_terms)
            if spec is None or spec2 is None:
                jit_exec.note_impact_fallback("ineligible-query")
                return None
            specs.append(spec)
            specs2.append(spec2)
            windows.append(int(rs.window_size))
            qws.append(float(rs.query_weight))
            rws.append(float(rs.rescore_query_weight))
            modes.append(rs.score_mode)
        if len({f for f, _, _ in specs} |
               {f for f, _, _ in specs2}) != 1:
            jit_exec.note_impact_fallback("mixed-fields")
            return None
        if len(set(modes)) != 1:
            return None                   # score_mode is program-static
        field = specs[0][0]
        k = max(max(req.from_ + req.size, 1, w)
                for req, w in zip(reqs, windows))
        try:
            pack = jit_exec.impact_pack_for(
                self.reader, field, cfg, k1=self.ctx.bm25.k1,
                b=self.ctx.bm25.b)
            if pack is None:
                jit_exec.note_impact_fallback("no-impact-columns")
                return None
            out = jit_exec.run_impact_rescore(
                pack, [t for _, t, _ in specs],
                [bo for _, _, bo in specs],
                [t for _, t, _ in specs2],
                [bo for _, _, bo in specs2],
                windows, qws, rws, modes[0], k=k, n_real=n_real)
        except QueryParsingError:
            raise
        except Exception as e:            # noqa: BLE001 — fallback seam
            jit_exec.note_fallback(e, reason="device-error")
            jit_exec.note_device_error(e)
            jit_exec.note_impact_fallback("device-error")
            return None
        jit_exec.plane_breaker.record_success()
        for name in ("top_scores", "top_docs", "count"):
            try:
                out[name].copy_to_host_async()
            except Exception:             # noqa: BLE001 — optional
                pass
        return ("rescore", reqs, k, out, pack.total_blocks,
                n_real if n_real is not None else len(reqs))

    # ---- dense / late-interaction lane (top-level "knn" section) ----------

    def _validate_knn(self, knn: q.KnnSection) -> None:
        """Parse-time mapping validation: the field must be mapped
        dense_vector (flat query_vector) or rank_vectors (list-of-
        vectors), and the query's dims must match the mapping — a clear
        400 before any device work, not a score-time shape error."""
        fm = self.mapper_service.field_mapper(knn.field)
        kind = getattr(fm, "kind", None)
        if fm is None or kind not in ("vector", "mvector"):
            raise QueryParsingError(
                f"[knn] field [{knn.field}] is not mapped as "
                f"dense_vector or rank_vectors")
        if knn.multi and kind != "mvector":
            raise QueryParsingError(
                f"[knn] field [{knn.field}] is dense_vector but "
                f"query_vector is a list of vectors — flat [dims] "
                f"expected")
        if not knn.multi and kind != "vector":
            raise QueryParsingError(
                f"[knn] field [{knn.field}] is rank_vectors — "
                f"query_vector must be a list of [dims] token vectors")
        dims = int(getattr(fm, "dims", 0))
        qdims = len(knn.query_vector[0]) if knn.multi \
            else len(knn.query_vector)
        if qdims != dims:
            raise QueryParsingError(
                f"[knn] query_vector dims [{qdims}] != mapped dims "
                f"[{dims}] of field [{knn.field}]")

    @staticmethod
    def _knn_limit(req: ParsedSearchRequest) -> int:
        """Hits a knn request may return: the from/size window, capped
        by the section's k for pure knn (k IS "how many neighbors");
        hybrid windows read from the fused list (depth bounded by
        num_candidates per lane)."""
        lim = max(req.from_ + req.size, 1)
        return lim if req.knn.hybrid else min(lim, req.knn.k)

    def _rewrite_knn(self, req: ParsedSearchRequest) -> ParsedSearchRequest:
        """Join-rewrite the hybrid lexical query and the knn filter."""
        import dataclasses as _dc
        knn = req.knn
        new_q = self._rewrite_joins(req.query) if knn.hybrid else req.query
        new_f = self._rewrite_joins(knn.filter) \
            if knn.filter is not None else None
        if new_q is req.query and new_f is knn.filter:
            return req
        return _dc.replace(req, query=new_q,
                           knn=_dc.replace(knn, filter=new_f))

    def _knn_batch_launch(self, reqs: list, n_real: int | None = None):
        """knn-lane admission + dispatch: serve B knn/hybrid requests
        as ONE compiled program (jit_exec.run_knn_hybrid_batch) over
        the reader's block-cached vector columns. Returns a drain
        handle or None (callers retry per request / fall back to the
        eager per-segment lane); declines are reason-labeled via
        note_knn_fallback, mirroring the impact lane's admission
        accounting. Mapping violations raise QueryParsingError — those
        are request errors (400), never fallbacks."""
        from elasticsearch_tpu.search import jit_exec
        for r in reqs:
            self._validate_knn(r.knn)
        if not self.reader.segments:
            return ("empty", reqs)
        knns = [r.knn for r in reqs]
        if len({(kn.field, kn.hybrid, kn.multi, kn.num_candidates)
                for kn in knns}) != 1:
            jit_exec.note_knn_fallback("mixed-shapes")
            return None
        if any(not getattr(s, "resident", True)
               for s in self.reader.segments):
            jit_exec.note_knn_fallback("streamed-reader")
            return None
        reqs = [self._rewrite_knn(r) for r in reqs]
        cfg = jit_exec.knn_plane_config(self.ctx.index_name)
        k_prog = max(self._knn_limit(r) for r in reqs)
        try:
            pack = jit_exec.vector_pack_for(self.reader, knns[0].field,
                                            cfg)
            if pack is None:
                # mapped but no segment carries vectors yet: the eager
                # lane returns the same empty result without a compile
                jit_exec.note_knn_fallback("no-vector-columns")
                return None
            if pack.multi != knns[0].multi:
                jit_exec.note_knn_fallback("mixed-shapes")
                return None
            mesh = jit_exec.serving_mesh()
            if mesh is not None:
                from elasticsearch_tpu.search.planner import \
                    prefer_mesh_serving
                if not prefer_mesh_serving("knn"):
                    mesh = None          # measured single-chip win
            if mesh is not None:
                out = jit_exec.run_knn_hybrid_mesh(
                    self.reader, self.ctx, reqs, pack, cfg, mesh,
                    k=k_prog, num_candidates=knns[0].num_candidates,
                    n_real=n_real)
            else:
                out = jit_exec.run_knn_hybrid_batch(
                    self.reader, self.ctx, reqs, pack, cfg, k=k_prog,
                    num_candidates=knns[0].num_candidates,
                    n_real=n_real)
        except QueryParsingError:
            raise
        except Exception as e:            # noqa: BLE001 — fallback seam
            jit_exec.note_fallback(e, reason="device-error")
            jit_exec.note_device_error(e)
            jit_exec.note_knn_fallback("device-error")
            return None
        if out is None:                   # mixed plan signatures
            jit_exec.note_knn_fallback("mixed-shapes")
            return None
        jit_exec.plane_breaker.record_success()
        hybrid = knns[0].hybrid
        n = n_real if n_real is not None else len(reqs)
        jit_exec.note_knn_served(
            self.ctx.index_name, n,
            fused=n if hybrid else 0,
            maxsim=n if pack.multi else 0)
        for name in ("top_scores", "top_docs", "count"):
            try:
                out[name].copy_to_host_async()
            except Exception:             # noqa: BLE001 — optional
                pass
        return ("knn", reqs, k_prog, out)

    def _knn_query_phase(self, req: ParsedSearchRequest
                         ) -> ShardQueryResult:
        """Single-request knn/hybrid entry: compiled lane when the
        breaker admits it, eager per-segment fallback otherwise."""
        from elasticsearch_tpu.search import jit_exec
        self._validate_knn(req.knn)
        if jit_exec.plane_breaker.allow():
            handle = self._knn_batch_launch([req])
            if handle is not None:
                return self.query_phase_batch_drain(handle)[0]
        else:
            jit_exec.note_breaker_skip()
            jit_exec.note_knn_fallback("breaker-open")
        return self._knn_query_phase_eager(req)

    def _knn_query_phase_eager(self, req: ParsedSearchRequest
                               ) -> ShardQueryResult:
        """Eager fallback lane: host-side per-segment scoring from the
        SAME cached host columns (normalized f32 / int8 snapshot) the
        compiled pack uploads, host candidate selection and host
        fusion — the reference implementation the compiled program is
        tested against."""
        from elasticsearch_tpu.search import jit_exec
        req = self._rewrite_knn(req)
        knn = req.knn
        cfg = jit_exec.knn_plane_config(self.ctx.index_name)
        task, deadline = _task_budget(req)
        qv = np.asarray(knn.query_vector, np.float32)
        if knn.multi:
            qn = qv / np.maximum(
                np.linalg.norm(qv, axis=1, keepdims=True), 1e-12)
        else:
            qn = qv / max(float(np.linalg.norm(qv)), 1e-12)
        knn_s, knn_d = [], []
        lex_s, lex_d = [], []
        eligible = 0
        for dseg in self.reader.segments:
            _checkpoint(task)
            base = dseg.doc_base
            live = np.asarray(dseg.live)
            fmask = None
            if knn.filter is not None:
                ex = SegmentExecutor(dseg, self.ctx)
                fmask = np.asarray(ex.match_mask(knn.filter))
            if knn.hybrid:
                ex = SegmentExecutor(dseg, self.ctx)
                scores, mask = ex.execute(req.query)
                m = np.asarray(mask) & live
                s = np.asarray(scores)
                idx = np.nonzero(m)[0]
                lex_s.append(s[idx].astype(np.float32))
                lex_d.append(idx.astype(np.int64) + base)
            entry = jit_exec._host_knn_column(dseg.seg, knn.field,
                                              cfg.quantization)
            if entry is None:
                continue
            host, multi, _dims = entry
            exists = host["exists"]
            elig = exists & live[:exists.shape[0]]
            if fmask is not None:
                elig = elig & fmask[:exists.shape[0]]
            if multi:
                s = _maxsim_host(host, qn)
            elif host["qcol"] is not None:
                s = (host["vecs"].astype(np.float32) @ qn) \
                    * np.float32(host["scale"]) \
                    + np.float32(host["offset"]) * np.float32(qn.sum())
            else:
                s = host["vecs"] @ qn
            eligible += int(elig.sum())
            idx = np.nonzero(elig)[0]
            knn_s.append(s[idx].astype(np.float32))
            knn_d.append(idx.astype(np.int64) + base)
        c = knn.num_candidates

        def topc(scores_l, docs_l, depth):
            s = np.concatenate(scores_l) if scores_l \
                else np.zeros(0, np.float32)
            d = np.concatenate(docs_l) if docs_l \
                else np.zeros(0, np.int64)
            order = np.lexsort((d, -s.astype(np.float64)))[:depth]
            return s[order], d[order]
        ds, dd = topc(knn_s, knn_d, c)
        kq = self._knn_limit(req)
        if not knn.hybrid:
            s_ = (ds * np.float32(knn.boost))[:kq]
            d_ = dd[:kq]
            total = eligible
        else:
            ls, ld = topc(lex_s, lex_d, c)
            s_, d_, total = fuse_host(ls, ld, ds, dd, knn.boost, cfg, kq)
        return ShardQueryResult(
            self.shard_id, int(total),
            float(s_[0]) if len(s_) else None,
            np.asarray(d_, np.int32), np.asarray(s_, np.float32),
            None, {}, self.reader)

    def query_phase_batch_drain(self, handle
                                ) -> list[ShardQueryResult]:
        """Phase 2: block until the launched batch's results are on host
        (one RTT, overlappable across batches — concurrent drains share
        the link's latency) and build per-request ShardQueryResults."""
        if handle[0] == "plan":
            # planner-wrapped handle: drain the inner arm, then stamp
            # predicted-vs-measured plan cost (a drain-side plan.cost
            # span on profiled responses; mispriced warm plans land on
            # the flight recorder)
            from elasticsearch_tpu.search import planner
            _, node, plan, t0, inner = handle
            results = self.query_phase_batch_drain(inner)
            planner.finish_plan(node, plan, t0)
            return results
        tag, reqs = handle[0], handle[1]
        if tag == "empty":
            return [ShardQueryResult(self.shard_id, 0, None,
                                     np.zeros(0, np.int32),
                                     np.zeros(0, np.float32), None, {},
                                     self.reader) for _ in reqs]
        if tag == "knn":
            _, _, _k, out = handle
            ms = np.asarray(out["top_scores"])
            md = np.asarray(out["top_docs"])
            totals = np.asarray(out["count"])
            results = []
            for bi, req in enumerate(reqs):
                kq = self._knn_limit(req)
                valid = md[bi] >= 0
                s_, d_ = ms[bi][valid][:kq], md[bi][valid][:kq]
                results.append(ShardQueryResult(
                    self.shard_id, int(totals[bi]),
                    float(s_[0]) if s_.size else None,
                    d_.astype(np.int32), s_.astype(np.float32), None,
                    {}, self.reader))
            return results
        if tag == "impact":
            from elasticsearch_tpu.observability import attribution
            from elasticsearch_tpu.search import jit_exec
            _, _, k, out, pruned, total_blocks, n_real = handle
            if pruned:
                scored = int(np.asarray(out["blocks_scored"]).sum())
                skipped = int(np.asarray(out["blocks_skipped"]).sum())
                attribution.label(
                    "pruned", f"{skipped}/{scored + skipped} blocks")
            else:
                # eager impact scoring touches every block — honest
                # effective-work accounting for the skip-ratio surfaces
                # (real rows only: pad replicas are not admissions)
                scored, skipped = total_blocks * n_real, 0
            ms = np.asarray(out["top_scores"])
            md = np.asarray(out["top_docs"])
            totals = np.asarray(out["count"])
            jit_exec.note_impact_served(self.ctx.index_name, n_real,
                                        scored, skipped)
        elif tag == "rescore":
            from elasticsearch_tpu.search import jit_exec
            _, _, k, out, total_blocks, n_real = handle
            ms = np.asarray(out["top_scores"])
            md = np.asarray(out["top_docs"])
            totals = np.asarray(out["count"])
            # the composed plan's candidate stage is eager — every
            # block scored — and the whole rescore rode the one
            # dispatch (the counter the fusion bench reconciles)
            jit_exec.note_impact_served(self.ctx.index_name, n_real,
                                        total_blocks * n_real, 0)
            jit_exec.note_rescore_fused(n_real)
        elif tag == "host":
            _, _, k, (ms, md, totals) = handle
        else:
            _, _, k, pack, out = handle
            if pack:
                # single-fetch fast path: scoring, merge AND result
                # packing ran as one program — one dispatch + one
                # device→host round trip per batch (RTT dominates on a
                # tunneled interconnect)
                ms, md, totals = topk_ops.unpack_batch_result(
                    np.asarray(out), k)
            else:
                ms = np.asarray(out["top_scores"])
                md = np.asarray(out["top_docs"])
                totals = np.asarray(out["count"])
        results = []
        for bi, req in enumerate(reqs):
            kq = max(req.from_ + req.size, 1)
            valid = md[bi] >= 0
            s_, d_ = ms[bi][valid][:kq], md[bi][valid][:kq]
            results.append(ShardQueryResult(
                self.shard_id, int(totals[bi]),
                float(s_[0]) if s_.size else None,
                d_.astype(np.int32), s_.astype(np.float32), None, {},
                self.reader))
        return results

    def _query_phase_batch_streamed(self, queries: list, k: int,
                                    streamed: list):
        """Batched query phase when the reader exceeds its HBM budget: the
        resident prefix runs as the usual one-program merge; streamed
        segments run double-buffered through jit_exec.run_segments_streamed;
        the final cross-part merge happens host-side in segment order (the
        stable (-score, segment) tie-break of the fully-resident path).
        → (ms, md, totals) numpy arrays or None (ineligible plans)."""
        from elasticsearch_tpu.search import jit_exec
        b = len(queries)
        resident = [s for s in self.reader.segments
                    if getattr(s, "resident", True)]
        try:
            out_r = None
            if resident:
                out_r = jit_exec.run_reader_batch(resident, self.ctx,
                                                  queries, k=k, pack=False)
                if out_r is None:
                    return None
            outs_s = jit_exec.run_segments_streamed(
                streamed, self.ctx, queries, k=k,
                device=getattr(self.reader, "device", None))
        except QueryParsingError:
            raise
        except Exception as e:            # noqa: BLE001 — fallback seam
            jit_exec.note_fallback(e, reason="device-error")
            jit_exec.note_device_error(e)
            return None
        if outs_s is None:
            return None
        jit_exec.plane_breaker.record_success()
        ms_parts, md_parts = [], []
        totals = np.zeros(b, np.int64)
        if out_r is not None:
            ms_parts.append(np.asarray(out_r["top_scores"]))
            md_parts.append(np.asarray(out_r["top_docs"]))
            totals = totals + np.asarray(out_r["count"])
        for seg, o in zip(streamed, outs_s):
            s_ = np.asarray(o["top_scores"])[:b]
            d_ = np.asarray(o["top_docs"])[:b]
            ms_parts.append(s_)
            md_parts.append(np.where(d_ >= 0, d_ + seg.doc_base, -1))
            totals = totals + np.asarray(o["count"])[:b]
        S = np.concatenate(ms_parts, axis=1)
        D = np.concatenate(md_parts, axis=1)
        S = np.where(D >= 0, S, -np.inf).astype(np.float32)
        order = np.argsort(-S, axis=1, kind="stable")[:, :k]
        ms = np.take_along_axis(S, order, axis=1)
        md = np.take_along_axis(D, order, axis=1)
        md = np.where(np.isfinite(ms), md, -1)
        return ms, md, totals

    def _apply_rescore(self, req: ParsedSearchRequest,
                       res: ShardQueryResult) -> None:
        """Re-rank the top window of this shard's hits per rescore pass
        (QueryRescorer.rescore: docs matching the rescore query combine
        primary×query_weight with secondary×rescore_query_weight; docs not
        matching keep primary×query_weight; only the window re-sorts)."""
        if not len(res.doc_ids):
            return
        scores = res.scores.astype(np.float32).copy()
        docs = res.doc_ids.copy()
        for spec in req.rescore:
            window = min(spec.window_size, len(docs))
            if window <= 0:
                continue
            per_seg = self._execute_query(spec.query)
            sec_scores = np.concatenate(
                [np.asarray(s) for s, _ in per_seg])
            sec_mask = np.concatenate([np.asarray(m) for _, m in per_seg])
            d = docs[:window]
            prim = scores[:window] * np.float32(spec.query_weight)
            sec = sec_scores[d] * np.float32(spec.rescore_query_weight)
            if spec.score_mode == "total":
                comb = prim + sec
            elif spec.score_mode == "multiply":
                comb = prim * sec
            elif spec.score_mode == "avg":
                comb = (prim + sec) / 2.0
            elif spec.score_mode == "max":
                comb = np.maximum(prim, sec)
            else:                          # min
                comb = np.minimum(prim, sec)
            comb = np.where(sec_mask[d], comb, prim).astype(np.float32)
            order = np.lexsort((d, -comb))  # score desc, doc-id tie-break
            docs[:window] = d[order]
            scores[:window] = comb[order]
        res.doc_ids = docs
        res.scores = scores
        res.max_score = float(scores[0]) if len(scores) else None

    def _collect_aggs(self, req: ParsedSearchRequest,
                      masks: list, scores: list) -> dict:
        """Run top-level agg collectors over the (pre-post_filter) mask —
        shared by the jit and eager query paths. ``masks``/``scores`` are
        per-segment DEVICE arrays: the device fast path (collect_device)
        segment-reduces on the accelerator with only bucket/scalar results
        crossing to host; ineligible nodes fall back to the numpy
        collectors, which materialize the host mask once, lazily."""
        if not req.aggs:
            return {}
        from elasticsearch_tpu.search.aggregations import (
            DEVICE_AGG_STATS, DeviceAggState, PIPELINE_AGGS, collect_device)
        state = DeviceAggState(self.reader, masks, scores)
        out = {}
        np_ctx = None
        for node in req.aggs:
            if node.type in PIPELINE_AGGS:
                continue
            partial = collect_device(node, state)
            if partial is None:
                DEVICE_AGG_STATS["host_fallbacks"] += 1
                if np_ctx is None:
                    np_ctx = ShardAggContext(
                        self.reader, self.mapper_service,
                        self._filter_masks_np, scores=state.np_scores(),
                        exec_ctx=self.ctx)
                partial = collect(node, state.np_mask(), np_ctx)
            out[node.name] = partial
        return out

    def _finish_score_order(self, k: int, total: int, seg_scores: list,
                            seg_docs: list, agg_partials: dict
                            ) -> ShardQueryResult:
        """Device merge of per-segment top-k → shard result (shared by the
        jit and eager query paths)."""
        if seg_scores:
            ms, md = topk_ops.merge_top_k(seg_scores, seg_docs, k)
            ms, md = np.asarray(ms), np.asarray(md)
            valid = md >= 0
            ms, md = ms[valid], md[valid]
        else:
            ms, md = np.zeros(0, np.float32), np.zeros(0, np.int32)
        max_sc = float(ms[0]) if ms.size else None
        return ShardQueryResult(self.shard_id, total, max_sc, md, ms, None,
                                agg_partials, self.reader)

    def _query_phase_eager(self, req: ParsedSearchRequest) -> ShardQueryResult:
        """Eager per-op fallback, same partial-results semantics as the jit
        path: terminate_after / timeout stop between segments (counts here
        are pre-min_score/post_filter — a coarser budget than the jit
        path's, acceptable for the fallback seam)."""
        k = max(req.from_ + req.size, 1)
        if req.rescore:
            k = max(k, max(s.window_size for s in req.rescore))
        terminated_early = timed_out = False
        task, deadline = _task_budget(req)
        per_seg = []
        segments = []
        running = 0
        for seg in self.reader.segments:
            _checkpoint(task)
            if deadline is not None and time.monotonic() > deadline:
                timed_out = True
                break
            ex = SegmentExecutor(seg, self.ctx)
            scores, mask = ex.execute(req.query)
            mask = mask & seg.live
            per_seg.append((scores, mask))
            segments.append(seg)
            if req.terminate_after is not None or deadline is not None:
                running += int(np.asarray(topk_ops.count_matches(mask)))
                if req.terminate_after is not None and \
                        running >= req.terminate_after:
                    terminated_early = True
                    break

        if req.min_score is not None:
            per_seg = [(s, m & (s >= np.float32(req.min_score)))
                       for s, m in per_seg]

        # aggregations run on the pre-post_filter mask (ES semantics);
        # unprocessed segments contribute empty masks; arrays stay on
        # device for the agg fast path
        masks = [m for _, m in per_seg]
        scores_l = [s for s, _ in per_seg]
        for seg in self.reader.segments[len(per_seg):]:
            masks.append(jnp.zeros(seg.padded_docs, bool))
            scores_l.append(jnp.zeros(seg.padded_docs, jnp.float32))
        agg_partials = self._collect_aggs(req, masks, scores_l)

        if req.post_filter is not None:
            post = [SegmentExecutor(seg, self.ctx).match_mask(req.post_filter)
                    for seg in segments]
            per_seg = [(s, m & pm) for (s, m), pm in zip(per_seg, post)]

        if req.search_after is not None and not req.sort:
            # score-ordered continuation: strictly worse than (score, doc)
            last_score = np.float32(float(req.search_after[0]))
            last_doc = int(req.search_after[1]) if len(req.search_after) > 1 else -1
            new = []
            for seg, (s, m) in zip(segments, per_seg):
                ids = jnp.arange(seg.padded_docs, dtype=jnp.int32) + seg.doc_base
                cont = (s < last_score) | ((s == last_score) & (ids > last_doc))
                new.append((s, m & cont))
            per_seg = new

        total = int(sum(int(np.asarray(topk_ops.count_matches(m)))
                        for _, m in per_seg)) if per_seg else 0
        if req.terminate_after is not None:
            total = min(total, req.terminate_after)

        if not _is_score_order(req.sort):
            if per_seg:
                res = self._sorted_query(req, per_seg, total, agg_partials,
                                         segments=segments)
            else:
                res = ShardQueryResult(self.shard_id, 0, None,
                                       np.zeros(0, np.int32),
                                       np.zeros(0, np.float32), [],
                                       agg_partials, self.reader)
        else:
            # score ordering: device top-k per segment, device merge
            seg_scores, seg_docs = [], []
            for seg, (s, m) in zip(segments, per_seg):
                ts, td = topk_ops.top_k(s, m, min(k, seg.padded_docs),
                                        seg.doc_base)
                seg_scores.append(ts)
                seg_docs.append(td)
            res = self._finish_score_order(k, total, seg_scores, seg_docs,
                                           agg_partials)
        res.terminated_early = terminated_early
        res.timed_out = timed_out
        if req.rescore and res.sort_values is None:
            self._apply_rescore(req, res)
        return res

    def _sorted_query(self, req, per_seg, total, agg_partials,
                      segments=None):
        """Sort-by-field path: host numpy argsort over doc-values columns
        (exact f64; matches Lucene FieldComparator semantics incl. missing).
        `segments` restricts to a processed PREFIX of the reader's segments
        (early termination) — concat order keeps global ids aligned."""
        segments = self.reader.segments if segments is None else segments
        mask = np.concatenate([np.asarray(m) for _, m in per_seg])
        scores = np.concatenate([np.asarray(s) for s, _ in per_seg])
        n = mask.shape[0]
        doc_ids = np.arange(n, dtype=np.int64)
        keys = []           # numeric sort keys per spec
        per_hit_out: list = []   # per spec: value to emit in hit["sort"]
        sort_specs = []
        for spec in req.sort:
            (fname, opts), = spec.items()
            order = opts.get("order", "asc")
            missing = opts.get("missing", "_last")
            sort_specs.append((fname, order, missing))
            if fname == "_score":
                vals = scores.astype(np.float64)
                out = vals
            elif fname == "_doc":
                # globally unique across shards AND indices so (.., _doc)
                # search_after cursors are unambiguous at the coordinator
                vals = (doc_ids + (self._doc_slot << 42)).astype(np.float64)
                out = vals
            else:
                vals, out = self._sort_column(fname, n, missing, order,
                                              segments)
            per_hit_out.append(out)
            keys.append(-vals if order == "desc" else vals)
        # np.lexsort: LAST key is primary → (docid tie-break, ..., spec1)
        order_idx = np.lexsort(tuple([doc_ids] + keys[::-1]))
        order_idx = order_idx[mask[order_idx]]
        if req.search_after is not None:
            order_idx = self._apply_search_after(req.search_after, sort_specs,
                                                 per_hit_out, order_idx)
        k = max(req.from_ + req.size, 1)
        top = order_idx[:k]
        sort_values = [[_sort_value_out(per_hit_out[i][d])
                        for i in range(len(req.sort))] for d in top]
        return ShardQueryResult(self.shard_id, total, None,
                                top.astype(np.int32), scores[top],
                                sort_values, agg_partials, self.reader)

    def _sort_column(self, fname: str, n: int, missing, order: str,
                     segments=None):
        """→ (numeric sort key [n] f64, per-hit output values [n] object)."""
        segments = self.reader.segments if segments is None else segments
        cols = []
        outs = []
        # union vocabulary across segments so keyword ordinals are comparable
        union: dict[str, int] | None = None
        if any(fname in seg.seg.keyword_fields for seg in segments):
            values: set[str] = set()
            for seg in segments:
                kcol = seg.seg.keyword_fields.get(fname)
                if kcol is not None:
                    values.update(kcol.vocab)
            union_vocab = sorted(values)
            union = {v: i for i, v in enumerate(union_vocab)}
        # one missing-fill per (field, order, missing) spec, shared by
        # missing DOCS and column-less SEGMENTS alike — a segment that
        # happens to hold no values for the field must rank its docs
        # exactly like a missing doc in a segment that has the column.
        # _last/_first place at the end/start of the list regardless of
        # direction; a custom value/TERM substitutes for comparison
        # (terms absent from the union vocab rank between neighbors).
        if missing in ("_last", "_first"):
            fill = np.inf if (missing == "_last") == (order == "asc") \
                else -np.inf
            out_fill = None if union is not None else fill
        elif union is not None:
            ms = str(missing)
            if ms in union:
                fill = float(union[ms])
            else:
                import bisect
                fill = bisect.bisect_left(union_vocab, ms) - 0.5
            out_fill = ms
        elif any(fname in seg.seg.numeric_fields for seg in segments):
            # numeric field: a non-numeric substitute is a caller error —
            # surface it (float raises), don't silently rank at 0
            fill = float(missing)
            out_fill = fill
        else:
            try:
                fill = float(missing)
                out_fill = fill
            except (TypeError, ValueError):
                # a string substitute on a field with NO column of either
                # kind anywhere in the shard: every doc is missing, so
                # all rank equal at the substitute
                fill = 0.0
                out_fill = str(missing)
        for seg in segments:
            col = seg.seg.numeric_fields.get(fname)
            if col is not None:
                vals = col.values.astype(np.float64).copy()
                vals[~col.exists] = fill
                cols.append(vals)
                outs.append(vals)
                continue
            kcol = seg.seg.keyword_fields.get(fname)
            if kcol is not None and union is not None:
                remap = np.array([union[v] for v in kcol.vocab] or [0],
                                 np.int64)
                first = kcol.ords[:, 0]
                have = first >= 0
                ranks = np.full(first.shape, fill, np.float64)
                ranks[have] = remap[first[have]]
                cols.append(ranks)
                out = np.full(first.shape, out_fill, dtype=object)
                out[have] = [union_vocab[int(r)] for r in ranks[have]]
                outs.append(out)
                continue
            cols.append(np.full(seg.padded_docs, np.float64(fill)))
            outs.append(np.full(seg.padded_docs, out_fill, dtype=object))
        if not cols:
            return np.full(n, np.inf), np.full(n, None, dtype=object)
        return np.concatenate(cols), np.concatenate(outs)

    def _apply_search_after(self, after: list, sort_specs, per_hit_out,
                            order_idx):
        """Keep docs strictly after the cursor in sort order. Cursor values
        are the emitted hit['sort'] values (numbers or keyword strings)."""
        keep = []
        for d in order_idx:
            cmp = 0
            for i, (fname, order, missing) in enumerate(sort_specs):
                if i >= len(after):
                    break
                a, b = per_hit_out[i][d], after[i]
                if a is None and b is None:
                    continue
                # missing docs sit first or last in *result order* per the
                # `missing` option (matches _sort_column's fill and the
                # coordinator merge) — no desc negation
                if a is None or b is None:
                    missing_after = missing != "_first"
                    if a is None:
                        cmp = 1 if missing_after else -1
                    else:
                        cmp = -1 if missing_after else 1
                    break
                if isinstance(a, str) or isinstance(b, str):
                    a, b = str(a), str(b)
                else:
                    a, b = float(a), float(b)
                if a == b:
                    continue
                c = 1 if a > b else -1
                cmp = c if order == "asc" else -c
                break
            if cmp > 0:
                keep.append(d)
        return np.asarray(keep, dtype=order_idx.dtype)

    # -- fetch phase ---------------------------------------------------------

    def fetch_phase(self, req: ParsedSearchRequest, result: ShardQueryResult,
                    index_name: str, positions: list[int]) -> list[dict]:
        from elasticsearch_tpu.index.engine import _segment_meta
        meta_wanted = [f for f in req.stored_fields
                       if f in ("_routing", "_parent", "_timestamp", "_ttl")]
        hits = []
        for pos in positions:
            gid = int(result.doc_ids[pos])
            seg, local = self.reader.resolve(gid)
            src = seg.seg.sources[local]
            meta = _segment_meta(seg.seg, local) or {}
            emit_score = result.sort_values is None or any(
                "_score" in spec for spec in req.sort)
            hit = {
                "_index": index_name,
                "_type": meta.get("_type", "_doc"),
                "_id": seg.seg.ids[local],
                "_score": (float(result.scores[pos]) if emit_score else None),
            }
            if req.version:
                # point-in-time version from the segment's _version
                # column (VersionFieldMapper doc-value) — the live map is
                # only a fallback for rows indexed before the column
                # existed; a live read could pair a newer version with
                # this snapshot's _source and defeat optimistic deletes
                v = meta.get("_version")
                if v is None and self.version_fn is not None:
                    v = self.version_fn(hit["_id"])
                if v is not None:
                    hit["_version"] = v
            # requested metadata fields render at the TOP level of the hit
            # (InternalSearchHit.toXContent puts metadata fields beside
            # _id, not under "fields" — the 2.x shape delete-by-query's
            # scroll relies on for _routing/_parent)
            for f in meta_wanted:
                if meta.get(f) is not None:
                    hit[f] = meta[f]
            if result.sort_values is not None:
                hit["sort"] = result.sort_values[pos]
            filtered = _filter_source(src, req.source_filter)
            if filtered is not None:
                hit["_source"] = filtered
            if req.highlight:
                hl = highlight_hit(req.highlight, src, self.mapper_service,
                                   req.query)
                if hl:
                    hit["highlight"] = hl
            if req.script_fields:
                hit["fields"] = self._script_fields(req.script_fields, seg, local)
            elif req.stored_fields or req.docvalue_fields:
                fields = {}
                for f in list(req.stored_fields) + list(
                        req.docvalue_fields):
                    v = src.get(f)
                    if v is None and "." in f:   # dotted path into objects
                        node = src
                        for part in f.split("."):
                            node = node.get(part) \
                                if isinstance(node, dict) else None
                            if node is None:
                                break
                        v = node
                    if v is not None and not isinstance(v, dict):
                        fields[f] = v if isinstance(v, list) else [v]
                if fields:
                    hit["fields"] = fields
            hits.append(hit)
        return hits

    def _script_fields(self, script_fields: dict, seg, local: int) -> dict:
        from elasticsearch_tpu.search.scripts import compile_script, ScriptContext
        from elasticsearch_tpu.search import jit_exec
        out = {}
        for name, spec in script_fields.items():
            script = spec.get("script", spec)
            lang = None
            if isinstance(script, dict):
                src = script.get("source", script.get("inline", ""))
                params = script.get("params", {})
                lang = script.get("lang")
            else:
                src, params = str(script), {}
            def run_interpreted(compile_fn):
                """Per-hit engine run (shared by the explicit-lang path
                and the expression-compile fallback)."""
                from elasticsearch_tpu.search.aggregations import (
                    _AggDocValues)
                dv = _AggDocValues(seg.seg)
                dv.doc = int(local)
                val = compile_fn(src).run({"doc": dv, "params": params})
                out[name] = val if isinstance(val, list) else [val]

            if lang not in (None, "expression"):
                # explicit lang → its registered engine, per hit
                # (ScriptService.compile dispatches by lang the same way)
                from elasticsearch_tpu.search.script_engines import (
                    resolve_engine)
                run_interpreted(resolve_engine(lang))
                continue
            def get_numeric(fld):
                col = seg.numeric.get(fld)
                if col is None:
                    return jnp.zeros(seg.padded_docs, jnp.float32), \
                        jnp.zeros(seg.padded_docs, bool)
                return col.hi, col.exists
            def get_vector(fld):
                col = seg.vector.get(fld)
                if col is None:
                    raise QueryParsingError(f"no vector field [{fld}]")
                # vecs are LAZY (host numpy until first use) — _fetch
                # materializes + caches the device copy once per reader
                return jit_exec._fetch(seg, col, "vecs"), col.exists
            try:
                compiled = compile_script(src)
            except QueryParsingError:
                # not an expression: run the general-purpose language per
                # hit (lang-groovy analog — loops/conditionals/collections)
                from elasticsearch_tpu.search.scriptlang import (
                    compile_groovylite)
                run_interpreted(compile_groovylite)
                continue
            ctx = ScriptContext(get_numeric, get_vector,
                                jnp.zeros(seg.padded_docs, jnp.float32),
                                params)
            vals = compiled.evaluate(ctx)
            arr = np.asarray(jnp.broadcast_to(jnp.asarray(vals),
                                              (seg.padded_docs,)))
            out[name] = [float(arr[local])]
        return out


def _filter_source(src: dict, spec) -> dict | None:
    """_source filtering with DOTTED-PATH globs (ref:
    FetchSourceContext/XContentMapValues.filter): an include pattern
    matching an object path keeps the whole subtree; patterns reach into
    nested objects ("obj.inner.field", "obj.*")."""
    if spec is True:
        return src
    if spec is False:
        return None
    if isinstance(spec, str):
        spec = [spec]
    if isinstance(spec, list):
        includes, excludes = spec, []
    else:
        includes = spec.get("includes", spec.get("include", []))
        excludes = spec.get("excludes", spec.get("exclude", []))
        if isinstance(includes, str):
            includes = [includes]
        if isinstance(excludes, str):
            excludes = [excludes]
    if not includes and not excludes:
        return src

    def prefixes(path: str) -> list[str]:
        parts = path.split(".")
        return [".".join(parts[:i + 1]) for i in range(len(parts))]

    def included(path: str) -> bool:
        if not includes:
            return True
        return any(fnmatch.fnmatch(p, pat)
                   for pat in includes for p in prefixes(path))

    def deeper_include(path: str) -> bool:
        """An include pattern may target something BELOW this object."""
        return any(pat.startswith(path + ".") or
                   fnmatch.fnmatch(path, ".".join(
                       pat.split(".")[:len(path.split("."))]))
                   for pat in includes)

    def excluded(path: str) -> bool:
        return any(fnmatch.fnmatch(p, pat)
                   for pat in excludes for p in prefixes(path))

    def filter_value(v, path: str):
        """→ (keep, filtered value) for one field value at `path` —
        arrays of objects filter element-wise (XContentMapValues reaches
        inside arrays; element indices don't count as path segments)."""
        if isinstance(v, dict):
            if included(path):
                return True, (walk(v, path) if excludes else v)
            if includes and deeper_include(path):
                sub = walk(v, path)
                return bool(sub), sub
            return False, None
        if isinstance(v, list) and any(isinstance(el, dict) for el in v):
            out = []
            for el in v:
                if isinstance(el, dict):
                    keep, sub = filter_value(el, path)
                    if keep:
                        out.append(sub)
                elif included(path):
                    out.append(el)
            return bool(out), out
        return included(path), v

    def walk(obj: dict, prefix: str) -> dict:
        out = {}
        for k, v in obj.items():
            path = f"{prefix}.{k}" if prefix else k
            if excluded(path):
                continue
            keep, sub = filter_value(v, path)
            if keep:
                out[k] = sub
        return out

    return walk(src, "")


def _maxsim_host(host: dict, qn: np.ndarray) -> np.ndarray:
    """Host (numpy) MaxSim over one segment's cached knn column — the
    eager lane's scorer and the kernel tests' oracle. ``qn``: [Qt, D]
    row-normalized query tokens."""
    vecs = host["vecs"].astype(np.float32)        # [N, T, D] (int8→f32)
    lens = host["lens"]
    sim = np.einsum("ntd,qd->nqt", vecs, qn.astype(np.float32))
    t = vecs.shape[1]
    pad = np.arange(t)[None, None, :] >= lens[:, None, None]
    sim = np.where(pad, -np.inf, sim)
    tokmax = sim.max(axis=2)                      # [N, Qt]
    if host["qcol"] is not None:
        tokmax = tokmax * np.float32(host["scale"]) \
            + np.float32(host["offset"]) * qn.sum(axis=1)[None, :] \
            .astype(np.float32)
    tokmax = np.where(np.isfinite(tokmax), tokmax, 0.0)
    return tokmax.sum(axis=1).astype(np.float32)


def fuse_host(ls, ld, ds, dd, boost: float, cfg, k: int):
    """Host-side hybrid fusion — the oracle the in-program fusion is
    bit-matched against (f32 arithmetic, (score desc, doc asc) ties).

    ls/ld: lexical candidates (scores f32, global doc ids) in rank
    order; ds/dd: knn lane; boost scales the knn contribution.
    → (scores [<=k] f32, docs [<=k], fused candidate count)."""
    ls = np.asarray(ls, np.float32)
    ds = np.asarray(ds, np.float32)
    fused: dict[int, np.float32] = {}
    if cfg.fusion_mode == "weighted":
        def norm(s):
            if not len(s):
                return s
            lo, hi = np.float32(s.min()), np.float32(s.max())
            rng = (hi - lo) if hi > lo else np.float32(1.0)
            return ((s - lo) / rng).astype(np.float32)
        for d, v in zip(ld, np.float32(cfg.lexical_weight) * norm(ls)):
            fused[int(d)] = fused.get(int(d), np.float32(0.0)) + v
        wd = np.float32(1.0 - cfg.lexical_weight) * np.float32(boost)
        for d, v in zip(dd, wd * norm(ds)):
            fused[int(d)] = fused.get(int(d), np.float32(0.0)) + v
    else:
        # strict f32 arithmetic mirroring the device body: the rank
        # denominators are small integers (exact in f32), the division
        # and the boost multiply run in f32, and each doc receives at
        # most one contribution per lane (lex first) — so the fused
        # score is BIT-IDENTICAL to the in-program reduction
        k0 = int(cfg.rank_constant)
        bf = np.float32(boost)
        for rank, d in enumerate(ld):
            c = np.float32(1.0) / np.float32(k0 + rank + 1)
            fused[int(d)] = fused.get(int(d), np.float32(0.0)) + c
        for rank, d in enumerate(dd):
            c = (np.float32(1.0) / np.float32(k0 + rank + 1)) * bf
            fused[int(d)] = fused.get(int(d), np.float32(0.0)) + c
    ranked = sorted(fused.items(), key=lambda kv: (-kv[1], kv[0]))[:k]
    return (np.asarray([s for _, s in ranked], np.float32),
            np.asarray([d for d, _ in ranked], np.int64), len(fused))


def _sort_value_out(v):
    if v is None or isinstance(v, str):
        return v
    v = float(v)
    if v in (np.inf, -np.inf):
        return None
    if v.is_integer():
        return int(v)
    return v
