"""Cost-driven query planner: one admission surface for the compiled
batch arms, one fallback taxonomy, one dispatch per plan.

Before this module, lane choice was a hardcoded N×N decline matrix:
the collective plane declined to the impact lane (``impact-preferred``)
and to the knn lane (``knn-lane``), and ``query_phase_batch_launch``
walked a fixed knn → impact → exact ladder with each arm screening the
next. Every new lane meant another row of pairwise rules. The planner
replaces that with plan composition:

* :func:`plan_batch` decomposes an admitted batch into candidate
  :class:`PlanNode` arms — each a lane-served sub-plan whose ``launch``
  thunk composes ALL of the request's work into ONE compiled dispatch
  (hybrid BM25+MaxSim+RRF fusion, impact candidate generation feeding a
  device-side rescore stage, knn ``filter`` masks resolved in-program).
* Each candidate is priced with
  :func:`~elasticsearch_tpu.observability.costs.estimate` (live EWMA
  when the lane has dispatched, XLA static analysis when cold — the
  typed ``cold`` flag rides the plan so pricing confidence is
  observable), and arms of equal admission specificity order by price.
* :func:`launch_plan` walks the priced arms, opens a ``plan.*`` span
  per node attempt (plane-lint's ``plan-node-spans`` family keeps every
  constructor site honest), and wraps the winning drain handle so
  :meth:`ShardSearcher.query_phase_batch_drain` can stamp
  predicted-vs-measured plan cost on profiled responses and flight-
  record mispriced plans.

Admission semantics are unchanged by pricing: arms keep their own
eligibility screens and tiers encode result-domain precedence (a knn
section can only be served by the vector lane; an impact-opted-in index
serves eligible shapes from the quantized columns deterministically —
cost never flips a batch between score DOMAINS, only between arms that
produce identical results). The cost signal decides the genuinely
interchangeable choices: mesh-vs-impact routing for the collective
plane (:func:`route_plane`) and equal-tier arm order.

Fallback taxonomy (the ``planner`` lane in ``search/lanes.py``):
``routed-impact`` / ``routed-knn`` replace the retired pairwise decline
edges, ``breaker-open`` covers candidates excluded because the device
is unhealthy or quarantined, ``no-plan`` is a batch with no admissible
compiled arm (the caller's serial path serves it), and ``plan-error``
is the planner's own defensive seam.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable

from elasticsearch_tpu.observability import costs, tracing

__all__ = ["PlanNode", "Plan", "plan_batch", "launch_plan",
           "finish_plan", "route_plane", "order_nodes",
           "prefer_mesh_serving"]


@dataclass
class PlanNode:
    """One lane-served sub-plan arm of a candidate plan.

    ``span`` is the ``plan.``-prefixed span name opened around the
    node's launch attempt and ``fallback`` the registered ``planner``
    reason noted if the node errors out of the plan — both must be
    string literals at every constructor site (plane-lint
    ``plan-node-spans``). ``launch`` returns a drain handle or None
    (the node's own admission screen declined; the next arm runs).
    ``tier`` encodes admission specificity: lower tiers serve shapes
    the later tiers cannot (or serve them in a different, opted-in
    score domain), so cost ordering applies only WITHIN a tier."""

    lane: str                                  # program lane it dispatches on
    span: str                                  # "plan.<...>" span name
    fallback: str                              # registered planner reason
    launch: Callable[[], Any] | None = None
    tier: int = 0
    cost: "costs.CostEstimate | None" = None
    detail: str = ""

    @property
    def cold(self) -> bool:
        return bool(self.cost is None or self.cost.cold)


@dataclass
class Plan:
    """An ordered list of candidate arms for one admitted batch."""

    nodes: list = field(default_factory=list)

    @property
    def cold(self) -> bool:
        """True when NO candidate was priced from a measured EWMA —
        the whole plan rests on static analysis (or no estimate at
        all); bench's cost-error leg splits accuracy on this."""
        return all(n.cold for n in self.nodes)

    @property
    def predicted_us(self) -> float | None:
        """The chosen (first) arm's priced cost, if any."""
        for n in self.nodes:
            if n.cost is not None:
                return float(n.cost)
        return None


def order_nodes(nodes: list) -> list:
    """Stable plan order: admission tier first, then price within the
    tier (unpriced arms after priced ones — an arm we cannot price at
    all never outranks one we can), original order breaking ties."""
    return sorted(
        nodes,
        key=lambda n: (n.tier,
                       float(n.cost) if n.cost is not None
                       else float("inf")))


def _priced(lane: str, node_id=None,
            mesh=None) -> "costs.CostEstimate | None":
    """Lane-level price: the dispatch-weighted measured mean when the
    lane has served traffic on this node, the static-analysis mean when
    it has only compiled (``cold=True``), None when the cost observatory
    has never seen the lane. Shape-exact pricing needs the compiled
    program key, which only exists after the arm commits — lane-level
    is the honest pre-dispatch signal. ``mesh`` scopes the price to
    one pod-slice geometry (costs.estimate's mesh axis)."""
    try:
        return costs.estimate(lane, node_id=node_id, mesh=mesh)
    except Exception:            # noqa: BLE001 — pricing must never veto
        return None


def prefer_mesh_serving(lane: str) -> bool:
    """Geometry routing: serve this batch on the pod-slice mesh lane
    (``impact-mesh`` / ``knn-mesh``) or the single-chip lane?

    Only meaningful when a serving mesh is installed (False
    otherwise). Same pricing discipline as :func:`route_plane`: the
    installed mesh is the operator's opt-in default, so it wins
    UNLESS both arms carry dispatch-backed estimates (``measured`` /
    ``lane-mean`` — a static roofline never overrides the opt-in) and
    the single-chip arm is strictly cheaper than the mesh arm priced
    at the serving geometry. Bit-identity between the arms is proven
    by the mesh-equality suite, so routing is purely a cost decision —
    it can never change a response."""
    from elasticsearch_tpu.search import jit_exec
    mesh = jit_exec.serving_mesh()
    if mesh is None:
        return False
    mesh_lane = {"impact": "impact-mesh", "knn": "knn-mesh"}.get(lane)
    if mesh_lane is None:
        return False
    m = _priced(mesh_lane, mesh=mesh)
    if lane == "impact":
        single = _priced("impact-pruned") or _priced("impact-eager")
    else:
        single = _priced("knn")
    backed = ("measured", "lane-mean")
    if m is not None and single is not None and \
            m.source in backed and single.source in backed and \
            float(single) < float(m):
        return False             # measured single-chip win
    return True


def plan_batch(shard, reqs: list, n_real: int | None = None
               ) -> Plan | None:
    """Decompose one admitted batch into priced candidate arms.

    ``shard`` is the owning :class:`~elasticsearch_tpu.search.phase.
    ShardSearcher`; the node thunks close over its private lane
    launchers so each arm keeps its own admission screen (declines
    return None and the next arm runs — bit-identity with the
    sequential per-lane ladder is structural, not re-proven per query).
    Returns None when the breaker/quarantine excludes every compiled
    arm (``breaker-open``) or the planner itself fails
    (``plan-error``)."""
    from elasticsearch_tpu.search import jit_exec
    try:
        if not jit_exec.plane_breaker.allow() or \
                jit_exec.plane_breaker.quarantined:
            # an open breaker (or watchdog quarantine) excludes every
            # device candidate — there is no plan to price; the serial
            # path re-screens under the same gate and lands eager
            jit_exec.note_planner_fallback("breaker-open")
            return None
        nodes: list[PlanNode] = []
        if all(r.knn is not None for r in reqs):
            # vector/hybrid shapes: only the knn lane can serve a knn
            # section (lexical arms would silently drop it) — tier 0,
            # and the ONLY arm (the exact screen rejects knn bodies)
            nodes.append(PlanNode(
                lane="knn", span="plan.knn", fallback="plan-error",
                launch=lambda: shard._knn_batch_launch(reqs,
                                                       n_real=n_real),
                tier=0, cost=_priced("knn"),
                detail="fused lexical+vector+RRF, in-program filter"))
        else:
            if any(r.rescore for r in reqs):
                # impact candidate generation feeding the exact-window
                # rescore as a device-side stage: one composed dispatch
                # instead of a primary dispatch + a host rescore pass
                nodes.append(PlanNode(
                    lane="impact-rescore", span="plan.rescore",
                    fallback="plan-error",
                    launch=lambda: shard._rescore_batch_launch(
                        reqs, n_real=n_real),
                    tier=1, cost=_priced("impact-rescore"),
                    detail="impact candidates + in-program rescore"))
            # quantized impact arm before the exact arm: the index
            # OPTED IN to the quantized score domain, so precedence is
            # deterministic (tier, not price — price must never flip a
            # request between score domains)
            nodes.append(PlanNode(
                lane="impact-pruned", span="plan.impact",
                fallback="plan-error",
                launch=lambda: shard._impact_batch_launch(
                    reqs, n_real=n_real),
                tier=2, cost=_priced("impact-pruned") or
                _priced("impact-eager"),
                detail="quantized impact columns (opt-in)"))
            nodes.append(PlanNode(
                lane="reader-batch", span="plan.exact",
                fallback="plan-error",
                launch=lambda: shard._exact_batch_launch(
                    reqs, n_real=n_real),
                tier=3, cost=_priced("reader-batch"),
                detail="exact batched scorer"))
        return Plan(nodes=order_nodes(nodes))
    except Exception:            # noqa: BLE001 — planner defensive seam
        jit_exec.note_planner_fallback("plan-error")
        return None


def launch_plan(plan: Plan):
    """Walk the plan's arms in order under per-node ``plan.*`` spans;
    the first arm whose launch admits the batch wins and its handle is
    wrapped as ``("plan", node, plan, t0)``+handle so the drain can
    stamp predicted-vs-measured plan cost. QueryParsingError propagates
    (a 400 is a request error on EVERY arm, never a fallback); any
    other arm explosion notes the node's fallback reason and the next
    arm runs — the plan absorbs a broken arm the way the old ladder
    absorbed a device error."""
    from elasticsearch_tpu.common.errors import QueryParsingError
    from elasticsearch_tpu.search import jit_exec
    for node in plan.nodes:
        t0 = time.perf_counter()
        with tracing.span(node.span, lane=node.lane,
                          predicted_us=None if node.cost is None
                          else round(float(node.cost), 1),
                          cold=node.cold):
            try:
                handle = node.launch()
            except QueryParsingError:
                raise
            except Exception as e:   # noqa: BLE001 — arm seam
                # the arm's own seam normally eats device errors and
                # returns None; anything escaping it is a planner-level
                # arm failure — note it and keep walking the plan
                jit_exec.note_fallback(e, reason="device-error")
                jit_exec.note_planner_fallback("plan-error")
                handle = None
        if handle is not None:
            jit_exec.note_planner_plan(len(plan.nodes), cold=plan.cold)
            return ("plan", node, plan, t0, handle)
    jit_exec.note_planner_fallback("no-plan")
    return None


#: measured/predicted ratio beyond which a served plan is flight-
#: recorded as mispriced (same spirit as the cost observatory's
#: dispatch-overrun anomaly threshold)
MISPRICE_RATIO = 4.0


def finish_plan(node: PlanNode, plan: Plan, t0: float) -> dict:
    """Drain-side accounting for a served plan: measured wall µs from
    launch to drained results vs the planner's predicted price, stamped
    on the drain-side ``plan.cost`` span (profiled responses carry it
    in the shard span tree) and flight-recorded as ``plan-mispriced``
    when a WARM prediction missed by :data:`MISPRICE_RATIO`."""
    measured_us = (time.perf_counter() - t0) * 1e6
    predicted = plan.predicted_us
    attrs = {"lane": node.lane, "cold": plan.cold,
             "measured_us": round(measured_us, 1)}
    if predicted is not None:
        attrs["predicted_us"] = round(predicted, 1)
        attrs["cost_error"] = round(
            measured_us / predicted if predicted > 0 else 0.0, 3)
    with tracing.span("plan.cost", **attrs):
        pass
    if predicted is not None and not plan.cold and predicted > 0 and \
            measured_us / predicted >= MISPRICE_RATIO:
        from elasticsearch_tpu.observability import flightrec
        flightrec.note("plan-mispriced", lane=node.lane,
                       predicted_us=round(predicted, 1),
                       measured_us=round(measured_us, 1))
    return attrs


def route_plane(indices, impact_eligible: bool, has_knn: bool
                ) -> str | None:
    """Collective-plane routing decision, replacing the pairwise
    ``impact-preferred`` / ``knn-lane`` decline edges: returns the lane
    the batch is routed onto (the plane declines) or None (the mesh
    keeps it).

    knn sections ALWAYS route — the mesh program has no vector or
    fusion lanes, so serving them there would drop the section. An
    impact-eligible batch routes to the impact lane (the opted-in
    sublinear arm) unless the cost observatory has MEASURED dispatch
    traffic on both arms (``measured`` / ``lane-mean`` estimates — a
    lane-level price is at best a dispatch-weighted mean, never an
    exact-shape EWMA) and the mesh is strictly cheaper — a static
    roofline estimate never overrides the opt-in default."""
    from elasticsearch_tpu.search import jit_exec
    if has_knn:
        jit_exec.note_planner_fallback("routed-knn")
        for index in indices:
            index.note_plane_fallback("routed-knn")
        return "knn"
    if impact_eligible:
        mesh = _priced("mesh")
        imp = _priced("impact-pruned") or _priced("impact-eager")
        backed = ("measured", "lane-mean")
        if mesh is not None and imp is not None and \
                mesh.source in backed and imp.source in backed and \
                float(mesh) < float(imp):
            return None          # measured mesh win: keep the plane
        jit_exec.note_planner_fallback("routed-impact")
        for index in indices:
            index.note_plane_fallback("routed-impact")
        return "impact"
    return None
