"""Percolator — reverse search: match a document against stored queries.

Reference: core/percolator/PercolatorService.java:107 — the doc is parsed
into a one-document in-memory index (Lucene MemoryIndex) and every
registered query runs against it; registrations live in
core/index/percolator/PercolatorQueriesRegistry.java as hidden
`.percolator`-type docs. Here registrations ride IndexMetadata (replicated
and persisted with the cluster state), and percolation executes on the
coordinating node against a scratch single-doc segment — no shard fan-out
needed since the registry is global, not per-shard.
"""

from __future__ import annotations

import numpy as np

from elasticsearch_tpu.analysis import AnalysisRegistry
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.device_reader import DeviceReader
from elasticsearch_tpu.index.engine import SearcherView
from elasticsearch_tpu.index.segment import SegmentBuilder
from elasticsearch_tpu.mapping import MapperService
from elasticsearch_tpu.search.phase import ShardSearcher
from elasticsearch_tpu.search.query_dsl import parse_query


def _filter_registrations(meta, queries: dict, reg_filter) -> dict:
    """Percolate-request `filter`/`query` constrains WHICH registered
    queries participate, by matching their registration documents (the
    reference queries the hidden .percolator docs themselves,
    PercolatorService.java percolatorTypeFilter + request filter). All
    registration docs go into ONE scratch segment; the filter runs once
    and the per-row match mask selects the surviving query ids."""
    q = parse_query(reg_filter)
    scratch = MapperService(AnalysisRegistry(Settings(meta.settings)))
    ids = list(queries)
    builder = SegmentBuilder(seg_id=0)
    for qid in ids:
        # registration metadata = every field of the registration doc
        # except the query itself
        probe = {k: v for k, v in queries[qid].items() if k != "query"}
        builder.add(scratch.document_mapper().parse(str(qid), probe))
    seg = builder.build()
    mask = np.zeros(seg.padded_docs, dtype=bool)
    mask[:seg.num_docs] = True
    reader = DeviceReader(SearcherView([seg], [mask], 1))
    searcher = ShardSearcher(0, reader, scratch, index_name=meta.name)
    matched = np.zeros(seg.num_docs, dtype=bool)
    for _, m in searcher._execute_query(q):
        arr = np.asarray(m)[:seg.num_docs]
        matched |= arr.astype(bool)
    return {qid: queries[qid] for i, qid in enumerate(ids) if matched[i]}


def percolate(meta, doc: dict, queries: dict | None = None,
              size: int | None = None, reg_filter: dict | None = None) -> dict:
    """Match `doc` against `meta.percolators` (or an explicit query map).
    → {"total": N, "matches": [{"_index", "_id"}...]}"""
    queries = meta.percolators if queries is None else queries
    if queries and reg_filter is not None:
        queries = _filter_registrations(meta, queries, reg_filter)
    if not queries:
        return {"total": 0, "matches": []}
    # scratch mapper: percolation must not mutate the live mapper registry
    # with dynamically inferred fields from probe docs
    scratch = MapperService(AnalysisRegistry(Settings(meta.settings)))
    for t, m in (meta.mappings or {}).items():
        scratch.merge(t, m)
    parsed = scratch.document_mapper().parse("_percolate_doc", doc)
    builder = SegmentBuilder(seg_id=0)
    builder.add(parsed)
    seg = builder.build()
    mask = np.zeros(seg.padded_docs, dtype=bool)
    mask[:seg.num_docs] = True
    reader = DeviceReader(SearcherView([seg], [mask], 1))
    searcher = ShardSearcher(0, reader, scratch, index_name=meta.name)
    matches = []
    for qid, body in queries.items():
        q = parse_query(body.get("query"))
        per_seg = searcher._execute_query(q)
        if any(bool(np.asarray(m).any()) for _, m in per_seg):
            matches.append({"_index": meta.name, "_id": qid})
    total = len(matches)
    if size is not None:
        matches = matches[:size]
    return {"total": total, "matches": matches}
