"""Percolator — reverse search as a batched device workload.

Reference: core/percolator/PercolatorService.java:107 — the doc is parsed
into a one-document in-memory index (Lucene MemoryIndex) and every
registered query runs against it; registrations live in
core/index/percolator/PercolatorQueriesRegistry.java as hidden
`.percolator`-type docs. Here registrations ride IndexMetadata (replicated
and persisted with the cluster state), and percolation executes on the
coordinating node against a scratch single-doc segment — no shard fan-out
needed since the registry is global, not per-shard.

The execution model inverts the reference's query-at-a-time loop
(thousands of standing queries × one doc is the ideal many-sparse-queries
batch — the BM25S/GPUSparse argument applied to reverse search):

* **Registry (persistent, per index)** — every registration is parsed and
  planned ONCE into a shape bucket: the PROGRAM side of a percolation is
  keyed by plan signature (the PR-3 program/data split), so queries
  differing only in terms/values share one compiled lane. The registry
  syncs INCREMENTALLY against cluster-state metadata — register/unregister
  touches exactly the affected shape bucket; a percolate call that finds
  the metadata unchanged rebuilds nothing (counter-verified in tier-1).
  The scratch MapperService (the part of the old per-call rebuild that
  actually cost milliseconds) is cached alongside, with probe-doc dynamic
  mappings restored after each call so inference stays per-probe fresh.
* **One-dispatch evaluation** — per probe doc, each bucket's members
  resolve against the one-doc segment (dictionary lookups, microseconds)
  and group by actual plan signature; every (segment × group) lane packs
  its stacked constants and ALL lanes run as one fused vmapped program
  (jit_exec.run_percolate_lanes) returning per-query (matched, score)
  pairs reduced in-program (ops/percolate.py). `percolate_many` packs
  many docs × many queries into the same single dispatch — the
  multi-index msearch packing discipline applied to _mpercolate.
* **Fallback lane** — shapes the fused path can't express (scripts,
  geo_shape, parent/child joins) run per-query through the eager
  executor, exactly like the old loop, so behavior never regresses; any
  device error on the fused path degrades to the same lane
  (jit_exec.note_fallback, reason-labeled).

Responses carry full fidelity on the same pass: per-match scores, size +
sort-by-score, highlight via the standard highlighters on the probe doc,
and aggregations over registration metadata (the hidden-doc fields the
reference's percolate aggs run on).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from elasticsearch_tpu.analysis import AnalysisRegistry
from elasticsearch_tpu.common.errors import QueryParsingError
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.device_reader import DeviceReader
from elasticsearch_tpu.index.engine import SearcherView
from elasticsearch_tpu.index.segment import SegmentBuilder
from elasticsearch_tpu.mapping import MapperService
from elasticsearch_tpu.search import lanes
from elasticsearch_tpu.search import query_dsl as q
from elasticsearch_tpu.search.execute import (ConstTable, ExecutionContext,
                                              SegmentResolver)
from elasticsearch_tpu.search.phase import ShardSearcher
from elasticsearch_tpu.search.query_dsl import parse_query


# ---------------------------------------------------------------------------
# eligibility: which shapes ride the fused vmapped lane
# ---------------------------------------------------------------------------

#: node types the fused lane does not express: scripts re-enter Python per
#: doc, geo_shape reads lazy ring columns, and parent/child joins need the
#: ShardSearcher rewrite pass — all run per-query on the eager executor.
_FALLBACK_NODES = (q.HasChildQuery, q.HasParentQuery, q.ScriptScoreQuery,
                   q.GeoShapeQuery)
_FALLBACK_FUNCTIONS = ("script_score", "random_score")


def _needs_fallback(ast) -> bool:
    if isinstance(ast, _FALLBACK_NODES):
        return True
    if isinstance(ast, q.FunctionScoreQuery) and any(
            f.kind in _FALLBACK_FUNCTIONS for f in ast.functions):
        return True
    import dataclasses
    if not dataclasses.is_dataclass(ast):
        return False
    for f in dataclasses.fields(ast):
        v = getattr(ast, f.name, None)
        if isinstance(v, q.Query):
            if _needs_fallback(v):
                return True
        elif isinstance(v, (list, tuple)):
            for el in v:
                if isinstance(el, q.Query) and _needs_fallback(el):
                    return True
                if isinstance(el, q.ScoreFunction) and \
                        el.filter_query is not None and \
                        _needs_fallback(el.filter_query):
                    return True
    return False


def _synthetic_doc(mappings: dict | None) -> dict:
    """A doc holding every mapped field with a placeholder value — the
    canonical probe the registry plans registrations against to derive
    their shape bucket (field columns must EXIST for the plan to take the
    same structural branches a real probe doc takes)."""
    def fill(props: dict, out: dict) -> None:
        for name, spec in (props or {}).items():
            typ = spec.get("type")
            if "properties" in spec and typ in (None, "object"):
                fill(spec["properties"], out.setdefault(name, {}))
                continue
            if typ == "nested":
                sub: dict = {}
                fill(spec.get("properties", {}), sub)
                out[name] = [sub]
            elif typ in ("long", "integer", "short", "byte", "double",
                         "float", "half_float", "scaled_float", "date"):
                out[name] = 0
            elif typ == "boolean":
                out[name] = True
            elif typ == "geo_point":
                out[name] = {"lat": 0.0, "lon": 0.0}
            elif typ == "dense_vector":
                out[name] = [0.0] * int(spec.get("dims", 1) or 1)
            elif typ == "geo_shape":
                continue                     # fallback lane anyway
            else:                            # text / keyword / string / ip
                out[name] = "a"
    doc: dict = {}
    for _t, m in (mappings or {}).items():
        fill(m.get("properties", {}), doc)
    return doc


class _Entry:
    """One registration: the AST parsed once plus its lane classification."""

    __slots__ = ("ast", "shape", "fallback", "body")

    def __init__(self, ast, shape, fallback: bool, body: dict):
        self.ast = ast
        self.shape = shape           # bucket key (None for fallback lane)
        self.fallback = fallback
        self.body = body


class PercolatorRegistry:
    """Per-index persistent compiled-query registry.

    Thread-safe: sync/diff and bucket maintenance run under the registry
    lock; evaluation works on snapshots taken under it."""

    def __init__(self, meta):
        self.name = meta.name
        self.uuid = meta.uuid
        # keys (and meanings) live in the lane registry so plane-lint's
        # counter-discipline rule can prove every surfaced key is bumped
        self.stats = {k: 0 for k in lanes.PERCOLATE_COUNTERS}
        self.stats["builds"] = 1         # this construction is the first
        self.stats["time_ms"] = 0.0      # float accumulator
        self._lock = threading.RLock()
        self._snap: dict | None = None   # meta.percolators as last synced
        self._version = -1
        self._map_fp: str | None = None
        self._mapper: MapperService | None = None
        self._canon = None               # (DeviceSegment, ExecutionContext)
        self._entries: dict[str, _Entry] = {}
        self._order: list[str] = []      # registration order (response order)
        self._buckets: dict = {}         # shape → {qid: _Entry}
        self._bucket_gen: dict = {}      # shape → invalidation generation
        self._reg_gen = 0                # bumps on any registration change
        self._reg_env = None             # (ids, searcher) over registration docs
        self._settings = Settings(meta.settings)

    # ---- sync (the cluster/index-metadata registration seam) --------------

    def sync(self, meta) -> None:
        with self._lock:
            map_fp = repr(meta.mappings)
            if self._map_fp != map_fp:
                self._rebuild_mapper(meta, map_fp)
                # shapes are planned against the mapping-derived canonical
                # segment — a mapping change re-buckets everything
                for qid in list(self._entries):
                    self._remove(qid, count=False)
                self._snap = None
            new = meta.percolators
            if self._version == meta.version and new is self._snap:
                return
            old = self._snap or {}
            if new is not old:
                added = [qid for qid in new
                         if qid not in old or new[qid] != old[qid]]
                removed = [qid for qid in old if qid not in new]
                changed = [qid for qid in added if qid in old]
                if added or removed:
                    self.stats["syncs"] += 1
                touched = set()
                for qid in removed + changed:
                    touched.add(self._remove(qid))
                for qid in added:
                    touched.add(self._add(qid, new[qid]))
                touched.discard(None)
                self.stats["bucket_invalidations"] += len(touched)
                for shape in touched:
                    self._bucket_gen[shape] = \
                        self._bucket_gen.get(shape, 0) + 1
                if added or removed:
                    self._reg_gen += 1
                    self._reg_env = None     # registration-doc segment stale
            self._snap = new
            self._version = meta.version

    def _rebuild_mapper(self, meta, map_fp: str) -> None:
        self.stats["mapper_rebuilds"] += 1
        self._settings = Settings(meta.settings)
        scratch = MapperService(AnalysisRegistry(self._settings))
        for t, m in (meta.mappings or {}).items():
            scratch.merge(t, m)
        scratch.default_similarity = self._settings.get(
            "index.similarity.default.type")
        self._mapper = scratch
        self._map_fp = map_fp
        # canonical one-doc env for registration-time shape planning
        try:
            parsed = self._parse_probe(_synthetic_doc(meta.mappings))
        except Exception:                # noqa: BLE001 — canonical is advisory
            parsed = self._parse_probe({})
        seg, reader = _probe_reader(parsed)
        self._canon = (reader.segments[0],
                       ExecutionContext(reader=reader,
                                        mapper_service=scratch,
                                        index_name=self.name))

    def _add(self, qid: str, body: dict):
        """Parse + plan one registration; → its shape bucket key (None for
        the fallback lane)."""
        ast = parse_query((body or {}).get("query"))
        self.stats["adds"] += 1
        if _needs_fallback(ast):
            entry = _Entry(ast, None, True, body)
        else:
            shape = self._shape_of(ast)
            entry = _Entry(ast, shape, shape is None, body)
        self._entries[qid] = entry
        if qid not in self._order:
            self._order.append(qid)
        if entry.shape is not None:
            self._buckets.setdefault(entry.shape, {})[qid] = entry
        return entry.shape

    def _remove(self, qid: str, count: bool = True):
        entry = self._entries.pop(qid, None)
        if entry is None:
            return None
        if count:
            self.stats["removes"] += 1
        self._order.remove(qid)
        if entry.shape is not None:
            bucket = self._buckets.get(entry.shape)
            if bucket is not None:
                bucket.pop(qid, None)
                if not bucket:
                    del self._buckets[entry.shape]
        return entry.shape

    def _shape_of(self, ast):
        """Plan the AST once against the canonical mapping-derived segment:
        the resulting signature is the registration's shape bucket. Plans
        the canonical env can't express land on the fallback lane (None) —
        correctness never depends on the bucket, only dispatch shape."""
        seg, ctx = self._canon
        try:
            ct = ConstTable()
            SegmentResolver(seg, ctx, ct).resolve(ast)
            return (ct.signature(), frozenset(ct.positions_needed),
                    frozenset(ct.vectors_needed))
        except Exception:                # noqa: BLE001 — fallback lane
            return None

    # ---- probe-doc environment -------------------------------------------

    def _parse_probe(self, doc: dict):
        """Parse with the CACHED scratch mapper, then restore any
        dynamically inferred mappers — each probe doc must see the same
        inference a fresh per-call mapper would (the old rebuild-per-call
        semantics) without paying the rebuild."""
        dm = self._mapper.document_mapper()
        before = set(dm.mappers)
        parsed = dm.parse("_percolate_doc", doc)
        self._probe_dynamic = [k for k in dm.mappers if k not in before]
        return parsed

    def _restore_probe_mappers(self) -> None:
        dm = self._mapper.document_mapper()
        for k in getattr(self, "_probe_dynamic", ()):  # keep through eval,
            dm.mappers.pop(k, None)                    # drop before next doc

    # ---- registration-doc environment (filter + aggs) ---------------------

    def _registration_env(self):
        """Scratch segment over the registration METADATA docs (every field
        of a registration except the query itself) — the percolate-request
        `filter`/`query` constraint and the aggs surface both run against
        it (the reference queries the hidden .percolator docs the same
        way). Cached until registrations change: this is DATA-layer state
        rebuilt only on register/unregister, never per call."""
        with self._lock:
            if self._reg_env is not None:
                return self._reg_env
            scratch = MapperService(AnalysisRegistry(self._settings))
            ids = list(self._order)
            builder = SegmentBuilder(seg_id=0)
            dm = scratch.document_mapper()
            for qid in ids:
                probe = {k: v for k, v in
                         (self._entries[qid].body or {}).items()
                         if k != "query"}
                builder.add(dm.parse(str(qid), probe))
            seg = builder.build()
            mask = np.zeros(seg.padded_docs, dtype=bool)
            mask[:seg.num_docs] = True
            reader = DeviceReader(SearcherView([seg], [mask], 1))
            searcher = ShardSearcher(0, reader, scratch,
                                     index_name=self.name)
            self._reg_env = (ids, seg, searcher)
            return self._reg_env

    def _filter_qids(self, reg_filter) -> set:
        """Which registered query ids a percolate-request filter keeps."""
        ids, seg, searcher = self._registration_env()
        if not ids:
            return set()
        ast = parse_query(reg_filter)
        matched = np.zeros(seg.num_docs, dtype=bool)
        for _, m in searcher._execute_query(ast):
            matched |= np.asarray(m)[:seg.num_docs].astype(bool)
        return {qid for i, qid in enumerate(ids) if matched[i]}

    def _collect_aggs(self, aggs_body: dict, matched_qids) -> dict | None:
        """Aggregations over the registration metadata of the MATCHED
        queries (PercolatorService aggs phase: buckets over the hidden
        .percolator docs that matched)."""
        from elasticsearch_tpu.search.aggregations import (parse_aggs,
                                                           reduce_aggs,
                                                           ShardAggContext,
                                                           collect)
        nodes = parse_aggs(aggs_body)
        if not nodes:
            return None
        ids, seg, searcher = self._registration_env()
        mask = np.zeros(seg.padded_docs, dtype=bool)
        for i, qid in enumerate(ids):
            if qid in matched_qids:
                mask[i] = True
        ctx = ShardAggContext(searcher.reader, searcher.mapper_service,
                              searcher._filter_masks_np,
                              exec_ctx=searcher.ctx)
        partials = {n.name: collect(n, mask, ctx) for n in nodes
                    if n.type not in _pipeline_aggs()}
        return reduce_aggs(nodes, [partials])

    # ---- evaluation --------------------------------------------------------

    def run(self, meta, items: list[dict]) -> list[dict]:
        """Evaluate a batch of percolate requests (one per probe doc) with
        every fused lane of every item packed into ONE device dispatch.
        → per item: a result dict, or {"_exception": exc} for a per-item
        failure (the _mpercolate contract; `percolate` re-raises)."""
        from elasticsearch_tpu.search import jit_exec
        t0 = time.perf_counter()
        with self._lock:
            order = list(self._order)
            buckets = {shape: dict(members)
                       for shape, members in self._buckets.items()}
            fallback_entries = {qid: e for qid, e in self._entries.items()
                                if e.fallback}
            bm25 = ExecutionContext(
                reader=None, mapper_service=self._mapper).bm25
        lanes: list[dict] = []
        lane_owner: list[tuple[int, list[str]]] = []   # lane → (item, qids)
        per_item: list[dict] = []                      # item scratch state
        for it_idx, item in enumerate(items):
            state = {"err": None, "matched": {}, "participating": None,
                     "fused_qids": []}
            per_item.append(state)
            try:
                doc = item.get("doc")
                if doc is None:
                    from elasticsearch_tpu.common.errors import \
                        IllegalArgumentError
                    raise IllegalArgumentError(
                        "percolate requires a [doc]")
                participating = None
                if item.get("reg_filter") is not None and order:
                    participating = self._filter_qids(item["reg_filter"])
                state["participating"] = participating
                if not order or (participating is not None
                                 and not participating):
                    continue
                with self._lock:
                    parsed = self._parse_probe(doc)
                    try:
                        seg, reader = _probe_reader(parsed)
                        ctx = ExecutionContext(reader=reader,
                                               mapper_service=self._mapper,
                                               index_name=self.name)
                        dseg = reader.segments[0]
                        # fused lanes: per bucket, resolve members against
                        # the probe segment (microseconds — dictionary
                        # lookups) and group by ACTUAL plan signature;
                        # multi-term expansions may split a bucket per
                        # probe, which only adds a lane, never wrongness
                        for shape in buckets:
                            groups: dict = {}
                            for qid, entry in buckets[shape].items():
                                if participating is not None and \
                                        qid not in participating:
                                    continue
                                ct = ConstTable()
                                emit = SegmentResolver(
                                    dseg, ctx, ct).resolve(entry.ast)
                                gkey = (ct.signature(),
                                        frozenset(ct.positions_needed),
                                        frozenset(ct.vectors_needed))
                                groups.setdefault(gkey, []).append(
                                    (qid, emit, ct.values))
                            for (sig, pos, vecs), rows in groups.items():
                                lanes.append(jit_exec.make_percolate_lane(
                                    dseg, rows[0][1], sig, pos, vecs,
                                    [r[2] for r in rows], bm25))
                                lane_owner.append(
                                    (it_idx, [r[0] for r in rows]))
                                state["fused_qids"].extend(
                                    r[0] for r in rows)
                        # fallback lane: per-query eager execution, the
                        # old loop's exact semantics (incl. join rewrite)
                        fb = [(qid, e) for qid, e in
                              fallback_entries.items()
                              if participating is None
                              or qid in participating]
                        if fb:
                            searcher = ShardSearcher(
                                0, reader, self._mapper,
                                index_name=self.name)
                            for qid, entry in fb:
                                hit, best = _eager_match(searcher,
                                                         entry.ast)
                                if hit:
                                    state["matched"][qid] = best
                            self.stats["fallback_queries"] += len(fb)
                    finally:
                        self._restore_probe_mappers()
            except Exception as e:       # noqa: BLE001 — per-item contract
                state["err"] = e
        # ---- the one dispatch ------------------------------------------
        if lanes and not jit_exec.plane_breaker.allow():
            # open plane breaker: the device is known-unhealthy — serve
            # every fused query on the eager lane instead of re-paying
            # the failing dispatch per percolate call
            jit_exec.note_breaker_skip()
            jit_exec.note_percolate_fallback("breaker-open")
            with self._lock:
                self.stats["breaker_skips"] += 1
            self._eager_rescue(items, per_item)
        elif lanes:
            try:
                outs = jit_exec.run_percolate_lanes(lanes)
                for (it_idx, qids), out in zip(lane_owner, outs):
                    state = per_item[it_idx]
                    if out.shape[0] == 1 and len(qids) > 1:
                        out = np.broadcast_to(out, (len(qids), 2))
                    for qi, qid in enumerate(qids):
                        if out[qi, 0] > 0.5:
                            state["matched"][qid] = float(out[qi, 1])
                # under the registry lock like every other stats bump —
                # += on a shared dict value is read-modify-write, and
                # concurrent percolates race it (flagged by plane-lint
                # lock-unguarded-state)
                with self._lock:
                    self.stats["fused_queries"] += sum(
                        len(qids) for _, qids in lane_owner)
                jit_exec.plane_breaker.record_success()
            except QueryParsingError:
                raise
            except Exception as e:       # noqa: BLE001 — fallback seam
                jit_exec.note_fallback(e, reason="device-error")
                jit_exec.note_device_error(e)
                jit_exec.note_percolate_fallback("device-error")
                self._eager_rescue(items, per_item)
        # ---- per-item rendering ------------------------------------------
        results = []
        for item, state in zip(items, per_item):
            if state["err"] is not None:
                results.append({"_exception": state["err"]})
                continue
            try:
                results.append(self._render(meta, item, state, order))
            except Exception as e:       # noqa: BLE001 — per-item contract
                results.append({"_exception": e})
        dt = (time.perf_counter() - t0) * 1000.0
        with self._lock:
            self.stats["count"] += len(items)
            self.stats["time_ms"] += dt
        from elasticsearch_tpu.observability import histograms
        for _ in items:
            histograms.observe_lane("percolate", dt / max(len(items), 1))
        return results

    def _eager_rescue(self, items, per_item) -> None:
        """Device-error fallback: re-evaluate every fused-lane query of
        every item on the eager executor (same emit closures — the
        compiled path's parity oracle), never failing the request."""
        for item, state in zip(items, per_item):
            if state["err"] is not None or not state["fused_qids"]:
                continue
            with self._lock:
                parsed = self._parse_probe(item["doc"])
                try:
                    _seg, reader = _probe_reader(parsed)
                    searcher = ShardSearcher(0, reader, self._mapper,
                                             index_name=self.name)
                    for qid in state["fused_qids"]:
                        entry = self._entries.get(qid)
                        if entry is None:
                            continue
                        hit, best = _eager_match(searcher, entry.ast)
                        if hit:
                            state["matched"][qid] = best
                finally:
                    self._restore_probe_mappers()

    def _render(self, meta, item: dict, state: dict,
                order: list[str]) -> dict:
        matched = state["matched"]
        want_score = bool(item.get("score") or item.get("sort")
                          or item.get("track_scores"))
        qids = [qid for qid in order if qid in matched]
        if item.get("sort"):
            qids.sort(key=lambda qid: -matched[qid])
        total = len(qids)
        size = item.get("size")
        if size is not None:
            qids = qids[:int(size)]
        matches = []
        for qid in qids:
            m = {"_index": meta.name, "_id": qid}
            if want_score:
                m["_score"] = matched[qid]
            if item.get("highlight"):
                entry = self._entries.get(qid)
                if entry is not None:
                    from elasticsearch_tpu.search.highlight import \
                        highlight_hit
                    hl = highlight_hit(item["highlight"], item["doc"],
                                       self._mapper, entry.ast)
                    if hl:
                        m["highlight"] = hl
            matches.append(m)
        out = {"total": total, "matches": matches}
        if item.get("aggs"):
            aggregations = self._collect_aggs(item["aggs"], set(matched))
            if aggregations is not None:
                out["aggregations"] = aggregations
        return out

    # ---- introspection -----------------------------------------------------

    def bucket_generations(self) -> dict:
        with self._lock:
            return dict(self._bucket_gen)

    def stats_dict(self) -> dict:
        with self._lock:
            return {**{k: (round(v, 3) if isinstance(v, float) else v)
                       for k, v in self.stats.items()},
                    "registered": len(self._entries),
                    "shape_buckets": len(self._buckets)}


def _pipeline_aggs():
    from elasticsearch_tpu.search.aggregations import PIPELINE_AGGS
    return PIPELINE_AGGS


def _probe_reader(parsed):
    """One-doc scratch segment + device reader for a probe document."""
    builder = SegmentBuilder(seg_id=0)
    builder.add(parsed)
    seg = builder.build()
    mask = np.zeros(seg.padded_docs, dtype=bool)
    mask[:seg.num_docs] = True
    return seg, DeviceReader(SearcherView([seg], [mask], 1))


def _eager_match(searcher: ShardSearcher, ast) -> tuple[bool, float]:
    """Per-query eager evaluation (the old loop's semantics): → (matched,
    best matching score)."""
    best = -np.inf
    hit = False
    for s, m in searcher._execute_query(ast):
        mnp = np.asarray(m).astype(bool)
        if mnp.any():
            hit = True
            best = max(best, float(np.asarray(s)[mnp].max()))
    return hit, (best if np.isfinite(best) else 0.0)


# ---------------------------------------------------------------------------
# module registry cache (per index, shared by every node in-process — the
# registry is a pure function of replicated IndexMetadata)
# ---------------------------------------------------------------------------

_REGISTRIES: dict[str, PercolatorRegistry] = {}
_REG_LOCK = threading.Lock()
_REG_CAP = 64


def registry_for(meta) -> PercolatorRegistry:
    with _REG_LOCK:
        reg = _REGISTRIES.get(meta.name)
        if reg is None or reg.uuid != meta.uuid:
            reg = PercolatorRegistry(meta)
            _REGISTRIES[meta.name] = reg
            while len(_REGISTRIES) > _REG_CAP:
                _REGISTRIES.pop(next(iter(_REGISTRIES)))
    reg.sync(meta)
    return reg


def registry_stats(name: str) -> dict | None:
    """Observability hook for index `_stats` / the node rollup; None when
    the index has never percolated (or holds no registrations)."""
    with _REG_LOCK:
        reg = _REGISTRIES.get(name)
    return reg.stats_dict() if reg is not None else None


def all_registry_stats() -> dict:
    """{index name: stats_dict} over every live registry — the
    OpenMetrics exporter's per-index percolate counter source."""
    with _REG_LOCK:
        regs = dict(_REGISTRIES)
    return {name: reg.stats_dict() for name, reg in sorted(regs.items())}


def clear_registries() -> None:
    with _REG_LOCK:
        _REGISTRIES.clear()


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def percolate(meta, doc: dict, queries: dict | None = None,
              size: int | None = None, reg_filter: dict | None = None,
              score: bool = False, sort: bool = False,
              highlight: dict | None = None,
              aggs: dict | None = None) -> dict:
    """Match `doc` against `meta.percolators` (or an explicit query map).
    → {"total": N, "matches": [{"_index", "_id"[, "_score", "highlight"]}
    ...][, "aggregations"]}"""
    if queries is not None:
        # explicit query map: no registry to key on — the serial path is
        # also the in-test oracle for the batched one
        return percolate_serial(meta, doc, queries, size=size,
                                reg_filter=reg_filter, score=score,
                                sort=sort, highlight=highlight)
    out = percolate_many(meta, [{
        "doc": doc, "size": size, "reg_filter": reg_filter,
        "score": score, "sort": sort, "highlight": highlight,
        "aggs": aggs}])[0]
    if "_exception" in out:
        raise out["_exception"]
    return out


def percolate_many(meta, items: list[dict]) -> list[dict]:
    """Batch percolation: every item's fused lanes pack into one device
    dispatch (the _mpercolate data plane). Items: {"doc", "size",
    "reg_filter", "score", "sort", "highlight", "aggs"}. Per-item errors
    come back as {"_exception": exc} — callers render or re-raise."""
    reg = registry_for(meta)
    return reg.run(meta, items)


def percolate_serial(meta, doc: dict, queries: dict | None = None,
                     size: int | None = None,
                     reg_filter: dict | None = None, score: bool = False,
                     sort: bool = False,
                     highlight: dict | None = None) -> dict:
    """The pre-registry per-query loop — kept as the explicit-query-map
    path AND as the oracle the fuzzer checks the batched registry against
    (same emit closures, eager dispatch, fresh scratch mapper)."""
    queries = meta.percolators if queries is None else queries
    if queries and reg_filter is not None:
        queries = _filter_registrations(meta, queries, reg_filter)
    if not queries:
        return {"total": 0, "matches": []}
    # scratch mapper: percolation must not mutate the live mapper registry
    # with dynamically inferred fields from probe docs
    settings = Settings(meta.settings)
    scratch = MapperService(AnalysisRegistry(settings))
    for t, m in (meta.mappings or {}).items():
        scratch.merge(t, m)
    scratch.default_similarity = settings.get(
        "index.similarity.default.type")
    parsed = scratch.document_mapper().parse("_percolate_doc", doc)
    _seg, reader = _probe_reader(parsed)
    searcher = ShardSearcher(0, reader, scratch, index_name=meta.name)
    matched: dict[str, float] = {}
    asts = {}
    for qid, body in queries.items():
        ast = parse_query(body.get("query"))
        asts[qid] = ast
        hit, best = _eager_match(searcher, ast)
        if hit:
            matched[qid] = best
    want_score = bool(score or sort)
    qids = [qid for qid in queries if qid in matched]
    if sort:
        qids.sort(key=lambda qid: -matched[qid])
    total = len(qids)
    if size is not None:
        qids = qids[:int(size)]
    matches = []
    for qid in qids:
        m = {"_index": meta.name, "_id": qid}
        if want_score:
            m["_score"] = matched[qid]
        if highlight:
            from elasticsearch_tpu.search.highlight import highlight_hit
            hl = highlight_hit(highlight, doc, scratch, asts[qid])
            if hl:
                m["highlight"] = hl
        matches.append(m)
    return {"total": total, "matches": matches}


def _filter_registrations(meta, queries: dict, reg_filter) -> dict:
    """Percolate-request `filter`/`query` constrains WHICH registered
    queries participate, by matching their registration documents (the
    reference queries the hidden .percolator docs themselves,
    PercolatorService.java percolatorTypeFilter + request filter). All
    registration docs go into ONE scratch segment; the filter runs once
    and the per-row match mask selects the surviving query ids."""
    ast = parse_query(reg_filter)
    scratch = MapperService(AnalysisRegistry(Settings(meta.settings)))
    ids = list(queries)
    builder = SegmentBuilder(seg_id=0)
    for qid in ids:
        # registration metadata = every field of the registration doc
        # except the query itself
        probe = {k: v for k, v in queries[qid].items() if k != "query"}
        builder.add(scratch.document_mapper().parse(str(qid), probe))
    seg = builder.build()
    mask = np.zeros(seg.padded_docs, dtype=bool)
    mask[:seg.num_docs] = True
    reader = DeviceReader(SearcherView([seg], [mask], 1))
    searcher = ShardSearcher(0, reader, scratch, index_name=meta.name)
    matched = np.zeros(seg.num_docs, dtype=bool)
    for _, m in searcher._execute_query(ast):
        arr = np.asarray(m)[:seg.num_docs]
        matched |= arr.astype(bool)
    return {qid: queries[qid] for i, qid in enumerate(ids) if matched[i]}
