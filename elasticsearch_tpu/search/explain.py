"""Score explanations — the _explain API and `explain: true` hits.

Reference: core/action/explain/TransportExplainAction.java (a single-shard
read that runs the query against one doc and returns Lucene's
`Explanation` tree) and the fetch-phase explain sub-phase
(core/search/fetch/explain/). Lucene builds the tree inside its scorers;
here the query tree is re-evaluated per clause against the (already
computed) segment score arrays, reading each clause's value at the target
doc — same numbers the batch kernel produced, organized as a tree.
"""

from __future__ import annotations

import numpy as np

from elasticsearch_tpu.search import query_dsl as q


def _eval(searcher, query: q.Query, gdoc: int) -> tuple[float, bool]:
    """(score at gdoc, matched) for an arbitrary sub-query."""
    per_seg = searcher._execute_query(query)
    scores = np.concatenate([np.asarray(s) for s, _ in per_seg])
    mask = np.concatenate([np.asarray(m) for _, m in per_seg])
    return float(scores[gdoc]), bool(mask[gdoc])


def _describe(query: q.Query) -> str:
    name = type(query).__name__.replace("Query", "").lower()
    field = getattr(query, "field", None)
    if name == "match":
        return f"match [{query.field}:{query.text}]"
    if name == "term":
        return f"term [{query.field}:{query.value}]"
    if name == "matchphrase":
        return f"phrase [{query.field}:\"{query.text}\"]"
    if field is not None:
        return f"{name} [{field}]"
    return name


def explain_query(searcher, query: q.Query, gdoc: int) -> dict:
    """Explanation tree for one global doc id on one shard searcher."""
    value, matched = _eval(searcher, query, gdoc)
    node = {"value": round(value, 6), "matched": matched,
            "description": _describe(query), "details": []}
    if isinstance(query, q.BoolQuery):
        for label, clauses in (("must", query.must),
                               ("should", query.should),
                               ("filter", query.filter)):
            for c in clauses:
                d = explain_query(searcher, c, gdoc)
                d["description"] = f"{label}: {d['description']}"
                node["details"].append(d)
        for c in query.must_not:
            sub_v, sub_m = _eval(searcher, c, gdoc)
            node["details"].append({
                "value": 0.0, "matched": not sub_m,
                "description": f"must_not: {_describe(c)}", "details": []})
    elif isinstance(query, (q.MultiMatchQuery,)):
        for f in query.fields:
            sub = q.MatchQuery(field=f.split("^")[0], text=query.text)
            node["details"].append(explain_query(searcher, sub, gdoc))
    elif isinstance(query, q.FunctionScoreQuery):
        node["details"].append(explain_query(searcher, query.query, gdoc))
    elif isinstance(query, q.ConstantScoreQuery):
        node["details"].append(
            explain_query(searcher, query.filter_query, gdoc))
    elif isinstance(query, (q.MatchQuery, q.MatchPhraseQuery)):
        # per-term BM25 contributions
        mapper = searcher.mapper_service.document_mapper().mappers.get(
            query.field)
        analyzer = getattr(mapper, "search_analyzer", None) or \
            getattr(mapper, "analyzer", None)
        terms = [t.term for t in analyzer.analyze(str(query.text))] \
            if analyzer else str(query.text).lower().split()
        if len(terms) > 1 and isinstance(query, q.MatchQuery):
            for t in terms:
                sub = q.TermQuery(field=query.field, value=t)
                node["details"].append(explain_query(searcher, sub, gdoc))
    return node


def strip_matched(node: dict) -> dict:
    """ES Explanation wire shape has no `matched` inside details."""
    out = {"value": node["value"], "description": node["description"],
           "details": [strip_matched(d) for d in node["details"]]}
    return out
