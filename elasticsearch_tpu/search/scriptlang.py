"""GroovyLite — the general-purpose script language (lang-groovy analog).

Plays the role of the reference's default script engine
(core/script/ScriptService.java:227; plugins/lang-groovy): a brace-syntax
imperative language with local variables, conditionals, loops, list/map
literals and method calls, interpreted per document / per invocation on
the host. The vectorized expression engine (scripts.py) stays the fast
path for arithmetic score/agg expressions; this engine exists for the
scripts expressions cannot express — update scripts that branch, scripted
metrics with loops and state, script fields building collections.

Surface syntax (the Groovy/Painless common subset the reference's docs
and test suites actually use):

    def total = 0;
    for (x in ctx._source.values) { if (x > 0) { total += x } }
    ctx._source.total = total;
    if (total == 0) { ctx.op = 'none' }

Sandboxing, by construction rather than by filter:
  * the parser only builds nodes the interpreter knows — there is no
    escape into Python eval;
  * names resolve against script-local scopes and the caller-provided
    bindings only; no builtins, no imports, no dunder access;
  * methods dispatch through closed per-type tables (list/map/str/num);
  * every interpreter step debits an op budget — runaway loops raise
    instead of hanging a shard (the reference counts loop iterations in
    compiled Groovy the same way).
"""

from __future__ import annotations

import math
import re

from elasticsearch_tpu.common.errors import (
    ElasticsearchTpuError, QueryParsingError)


class ScriptException(ElasticsearchTpuError):
    status = 400
    error_type = "script_exception"


DEFAULT_OP_BUDGET = 500_000

# ---- tokenizer -------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+|//[^\n]*|/\*.*?\*/)
  | (?P<num>\d+\.\d+(?:[eE][+-]?\d+)?|\d+[lLfFdD]?)
  | (?P<str>'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*")
  | (?P<name>[A-Za-z_$][A-Za-z0-9_$]*)
  | (?P<op>\+\+|--|\*\*|==|!=|<=|>=|&&|\|\||\+=|-=|\*=|/=|%=|\?:
        |[-+*/%<>=!?:.,;(){}\[\]])
""", re.VERBOSE | re.DOTALL)

_KEYWORDS = {"def", "if", "else", "for", "while", "in", "return", "break",
             "continue", "true", "false", "null", "new", "int", "long",
             "double", "float", "boolean", "String", "var"}


def _tokenize(src: str) -> list[tuple[str, str]]:
    out, pos = [], 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise ScriptException(
                f"unexpected character {src[pos]!r} at {pos}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        text = m.group()
        if kind == "name" and text in _KEYWORDS:
            kind = text
        out.append((kind, text))
    out.append(("eof", ""))
    return out


# ---- parser ---------------------------------------------------------------
# AST: plain tuples ("kind", ...) — the interpreter owns the vocabulary.

_BIN_PRECEDENCE = {
    "||": 1, "&&": 2,
    "==": 3, "!=": 3,
    "<": 4, "<=": 4, ">": 4, ">=": 4, "in": 4,
    "+": 5, "-": 5,
    "*": 6, "/": 6, "%": 6,
    "**": 7,
}


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self, k: int = 0):
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, text: str) -> bool:
        if self.peek()[1] == text and self.peek()[0] != "str":
            self.next()
            return True
        return False

    def expect(self, text: str):
        if not self.accept(text):
            raise ScriptException(
                f"expected {text!r}, found {self.peek()[1]!r}")

    # -- statements ----------------------------------------------------------

    def program(self):
        stmts = []
        while self.peek()[0] != "eof":
            before = self.i
            stmts.append(self.statement())
            if self.i == before:                 # e.g. a stray '}'
                raise ScriptException(
                    f"unexpected token {self.peek()[1]!r}")
        return ("block", stmts)

    def block(self):
        if self.accept("{"):
            stmts = []
            while not self.accept("}"):
                stmts.append(self.statement())
            return ("block", stmts)
        return self.statement()

    def statement(self):   # noqa: C901 — one dispatch table, flat cases
        while self.peek() == ("op", ";"):        # empty statement(s)
            self.next()
        kind, text = self.peek()
        if kind == "eof" or text == "}":
            return ("block", [])
        if kind in ("def", "var", "int", "long", "double", "float",
                    "boolean", "String"):
            self.next()
            name = self.next()[1]
            value = ("null",)
            if self.accept("="):
                value = self.expr()
            self.accept(";")
            return ("declare", name, value)
        if kind == "if":
            self.next()
            self.expect("(")
            cond = self.expr()
            self.expect(")")
            then = self.block()
            otherwise = None
            if self.accept("else"):
                otherwise = self.block()
            return ("if", cond, then, otherwise)
        if kind == "while":
            self.next()
            self.expect("(")
            cond = self.expr()
            self.expect(")")
            return ("while", cond, self.block())
        if kind == "for":
            return self._for()
        if kind == "return":
            self.next()
            value = ("null",)
            if self.peek()[1] not in (";", "}") or self.peek()[0] == "str":
                value = self.expr()
            self.accept(";")
            return ("return", value)
        if kind == "break":
            self.next()
            self.accept(";")
            return ("break",)
        if kind == "continue":
            self.next()
            self.accept(";")
            return ("continue",)
        stmt = self.simple()
        self.accept(";")
        return stmt

    def _for(self):
        self.next()
        self.expect("(")
        # for (x in expr)  |  for (def x in expr)  |  for (init; cond; step)
        save = self.i
        for kw in ("def", "var", "int", "long", "double"):
            self.accept(kw)
        if self.peek()[0] == "name" and self.peek(1)[1] == "in":
            var = self.next()[1]
            self.next()                          # 'in'
            seq = self.expr()
            self.expect(")")
            return ("foreach", var, seq, self.block())
        self.i = save
        init = None if self.peek()[1] == ";" else self.statement()
        self.accept(";")
        cond = ("true",) if self.peek()[1] == ";" else self.expr()
        self.expect(";")
        step = None if self.peek()[1] == ")" else self.simple()
        self.expect(")")
        return ("cfor", init, cond, step, self.block())

    def simple(self):
        """assignment / aug-assignment / ++ / -- / bare expression."""
        target = self.expr()
        kind, text = self.peek()
        if text in ("=", "+=", "-=", "*=", "/=", "%=") and kind == "op":
            self.next()
            value = self.expr()
            self._check_assignable(target)
            return ("assign", text, target, value)
        if text in ("++", "--"):
            self.next()
            self._check_assignable(target)
            one = ("num", 1)
            return ("assign", "+=" if text == "++" else "-=", target, one)
        return ("exprstmt", target)

    @staticmethod
    def _check_assignable(target):
        if target[0] not in ("name", "getattr", "getitem"):
            raise ScriptException(
                f"cannot assign to {target[0]} expression")

    # -- expressions (Pratt) -------------------------------------------------

    def expr(self, min_prec: int = 0):
        """Precedence climbing; ternary/elvis bind loosest and only at the
        top level (parenthesize to nest them inside operands)."""
        left = self.unary()
        while True:
            kind, text = self.peek()
            if min_prec == 0 and kind == "op" and text == "?":
                self.next()
                then = self.expr()
                self.expect(":")
                left = ("ternary", left, then, self.expr())
                continue
            if min_prec == 0 and text == "?:":
                self.next()
                left = ("elvis", left, self.expr())
                continue
            if kind == "str" or text not in _BIN_PRECEDENCE or \
                    _BIN_PRECEDENCE[text] < min_prec:
                return left
            self.next()
            prec = _BIN_PRECEDENCE[text]
            # left-assoc: recurse one level tighter ('**' right-assoc)
            right = self.expr(prec if text == "**" else prec + 1)
            left = ("binop", text, left, right)

    def unary(self):
        kind, text = self.peek()
        if text == "!" and kind == "op":
            self.next()
            return ("not", self.unary())
        if text == "-" and kind == "op":
            self.next()
            return ("neg", self.unary())
        if text == "+" and kind == "op":
            self.next()
            return self.unary()
        return self.postfix()

    def postfix(self):
        node = self.atom()
        while True:
            if self.accept("."):
                name = self.next()[1]
                if self.accept("("):
                    args = self._args()
                    node = ("method", node, name, args)
                else:
                    node = ("getattr", node, name)
            elif self.accept("["):
                index = self.expr()
                self.expect("]")
                node = ("getitem", node, index)
            elif self.peek()[1] == "(" and node[0] == "name":
                self.next()
                node = ("call", node[1], self._args())
            else:
                return node

    def _args(self):
        args = []
        if self.accept(")"):
            return args
        args.append(self.expr())
        while self.accept(","):
            args.append(self.expr())
        self.expect(")")
        return args

    def atom(self):   # noqa: C901 — flat literal dispatch
        kind, text = self.next()
        if kind == "num":
            clean = text.rstrip("lLfFdD")
            return ("num", float(clean) if "." in clean or "e" in clean
                    or "E" in clean else int(clean))
        if kind == "str":
            body = text[1:-1]
            return ("str", re.sub(
                r"\\(.)", lambda m: {"n": "\n", "t": "\t"}.get(
                    m.group(1), m.group(1)), body))
        if kind == "true":
            return ("true",)
        if kind == "false":
            return ("false",)
        if kind == "null":
            return ("null",)
        if kind == "new":
            tname = self.next()[1]
            self.expect("(")
            args = self._args()
            return ("new", tname, args)
        if kind == "name":
            return ("name", text)
        if text == "(":
            e = self.expr()
            self.expect(")")
            return e
        if text == "[":
            return self._bracket_literal()
        raise ScriptException(f"unexpected token {text!r}")

    def _bracket_literal(self):
        """[a, b] list  |  [k: v, ...] map  |  [:] empty map."""
        if self.accept(":"):
            self.expect("]")
            return ("map", [])
        if self.accept("]"):
            return ("list", [])
        first = self.expr()
        if self.accept(":"):
            pairs = [(first, self.expr())]
            while self.accept(","):
                k = self.expr()
                self.expect(":")
                pairs.append((k, self.expr()))
            self.expect("]")
            return ("map", pairs)
        items = [first]
        while self.accept(","):
            items.append(self.expr())
        self.expect("]")
        return ("list", items)


# ---- interpreter -----------------------------------------------------------

class _Break(Exception):
    pass


class _Continue(Exception):
    pass


class _Return(Exception):
    def __init__(self, value):
        self.value = value


_LIST_METHODS = {
    "add": lambda L, *a: (L.insert(int(a[0]), a[1])
                          if len(a) == 2 else L.append(a[0])),
    "addAll": lambda L, other: L.extend(other),
    "size": lambda L: len(L),
    "isEmpty": lambda L: len(L) == 0,
    "contains": lambda L, x: x in L,
    "get": lambda L, i: L[int(i)],
    "indexOf": lambda L, x: L.index(x) if x in L else -1,
    "remove": lambda L, i: L.pop(int(i)),
    "clear": lambda L: L.clear(),
    "sort": lambda L: L.sort(),
    "sum": lambda L: sum(L),
    "each": None,                    # rejected with a clear message below
}

_MAP_METHODS = {
    "put": lambda M, k, v: M.__setitem__(k, v),
    "get": lambda M, k, *d: M.get(k, d[0] if d else None),
    "getOrDefault": lambda M, k, d: M.get(k, d),
    "containsKey": lambda M, k: k in M,
    "containsValue": lambda M, v: v in M.values(),
    "remove": lambda M, k: M.pop(k, None),
    "size": lambda M: len(M),
    "isEmpty": lambda M: len(M) == 0,
    "keySet": lambda M: list(M.keys()),
    "values": lambda M: list(M.values()),
    "clear": lambda M: M.clear(),
}

_STR_METHODS = {
    "length": lambda s: len(s),
    "size": lambda s: len(s),
    "contains": lambda s, x: x in s,
    "startsWith": lambda s, p: s.startswith(p),
    "endsWith": lambda s, p: s.endswith(p),
    "indexOf": lambda s, x: s.find(x),
    "substring": lambda s, a, *b: s[int(a):int(b[0]) if b else None],
    "toLowerCase": lambda s: s.lower(),
    "toUpperCase": lambda s: s.upper(),
    "trim": lambda s: s.strip(),
    "split": lambda s, sep: s.split(sep),
    "replace": lambda s, a, b: s.replace(a, b),
    "equals": lambda s, o: s == o,
    "isEmpty": lambda s: len(s) == 0,
}

_NUM_METHODS = {
    "intValue": lambda x: int(x),
    "longValue": lambda x: int(x),
    "doubleValue": lambda x: float(x),
    "floatValue": lambda x: float(x),
    "toString": lambda x: str(x),
}

_MATH = {
    "max": max, "min": min, "abs": abs, "floor": math.floor,
    "ceil": math.ceil, "sqrt": math.sqrt, "log": math.log,
    "log10": math.log10, "exp": math.exp, "pow": pow, "round": round,
    "random": None,                  # nondeterministic — rejected
    "PI": math.pi, "E": math.e,
}

_FREE_FUNCS = {
    "max": max, "min": min, "abs": abs, "sqrt": math.sqrt,
    "log": math.log, "log10": math.log10, "exp": math.exp, "pow": pow,
    "floor": math.floor, "ceil": math.ceil, "round": round,
}

_NEWABLE = {
    "ArrayList": list, "HashMap": dict, "LinkedList": list,
    "HashSet": list, "StringBuilder": str, "LinkedHashMap": dict,
}


class CompiledGroovyLite:
    def __init__(self, source: str):
        self.source = source
        try:
            self.tree = _Parser(_tokenize(source)).program()
        except ScriptException:
            raise
        except Exception as e:       # noqa: BLE001 — uniform compile error
            raise ScriptException(f"compile error: {e}") from e

    def run(self, bindings: dict, op_budget: int = DEFAULT_OP_BUDGET):
        """Execute with the given top-level bindings (ctx/params/doc/…).
        → the script's return value (or the last statement's value)."""
        interp = _Interp(bindings, op_budget)
        try:
            return interp.exec_block(self.tree, {})
        except _Return as r:
            return r.value
        except ScriptException:
            raise
        except (_Break, _Continue):
            raise ScriptException("break/continue outside loop")
        except ZeroDivisionError:
            raise ScriptException("division by zero") from None
        except (TypeError, ValueError, KeyError, IndexError,
                AttributeError) as e:
            raise ScriptException(f"runtime error: {e}") from e


class _Interp:
    def __init__(self, bindings: dict, op_budget: int):
        self.bindings = bindings
        self.budget = op_budget

    def _tick(self):
        self.budget -= 1
        if self.budget <= 0:
            raise ScriptException("script exceeded its operation budget")

    # -- statements ----------------------------------------------------------

    def exec_block(self, node, scope) -> object:
        last = None
        for stmt in node[1]:
            last = self.exec_stmt(stmt, scope)
        return last

    def exec_stmt(self, node, scope):   # noqa: C901 — flat dispatch
        self._tick()
        kind = node[0]
        if kind == "block":
            # blocks do NOT open a new scope (Groovy locals declared in a
            # loop body stay visible after it; tests rely on this)
            return self.exec_block(node, scope)
        if kind == "declare":
            scope[node[1]] = self.eval(node[2], scope)
            return None
        if kind == "assign":
            return self._assign(node, scope)
        if kind == "exprstmt":
            return self.eval(node[1], scope)
        if kind == "if":
            if _truthy(self.eval(node[1], scope)):
                return self.exec_stmt(node[2], scope)
            if node[3] is not None:
                return self.exec_stmt(node[3], scope)
            return None
        if kind == "while":
            while _truthy(self.eval(node[1], scope)):
                self._tick()
                try:
                    self.exec_stmt(node[2], scope)
                except _Break:
                    break
                except _Continue:
                    continue
            return None
        if kind == "foreach":
            seq = self.eval(node[2], scope)
            if isinstance(seq, dict):
                seq = list(seq.keys())
            for item in list(seq):
                self._tick()
                scope[node[1]] = item
                try:
                    self.exec_stmt(node[3], scope)
                except _Break:
                    break
                except _Continue:
                    continue
            return None
        if kind == "cfor":
            if node[1] is not None:
                self.exec_stmt(node[1], scope)
            while _truthy(self.eval(node[2], scope)):
                self._tick()
                try:
                    self.exec_stmt(node[4], scope)
                except _Break:
                    break
                except _Continue:
                    pass
                if node[3] is not None:
                    self.exec_stmt(node[3], scope)
            return None
        if kind == "return":
            raise _Return(self.eval(node[1], scope))
        if kind == "break":
            raise _Break()
        if kind == "continue":
            raise _Continue()
        raise ScriptException(f"unknown statement {kind}")

    def _assign(self, node, scope):
        _, op, target, value_node = node
        value = self.eval(value_node, scope)
        if op != "=":
            current = self.eval(target, scope)
            if current is None:
                # `ctx._source.views += 1` on a missing field seeds the
                # type's zero (the update-script counter idiom; the old
                # regex evaluator behaved this way too)
                current = "" if isinstance(value, str) else \
                    [] if isinstance(value, list) else 0
            value = _binop(op[0], current, value)
        tk = target[0]
        if tk == "name":
            name = target[1]
            if name in scope:
                scope[name] = value
            elif name in self.bindings and not isinstance(
                    self.bindings[name], (dict, list)):
                self.bindings[name] = value
            else:
                scope[name] = value
        elif tk == "getattr":
            obj = self.eval(target[1], scope)
            if not isinstance(obj, dict):
                raise ScriptException(
                    f"cannot set property on {type(obj).__name__}")
            obj[target[2]] = value
        elif tk == "getitem":
            obj = self.eval(target[1], scope)
            key = self.eval(target[2], scope)
            if isinstance(obj, list):
                obj[int(key)] = value
            elif isinstance(obj, dict):
                obj[key] = value
            else:
                raise ScriptException(
                    f"cannot index-assign {type(obj).__name__}")
        return value

    # -- expressions ---------------------------------------------------------

    def eval(self, node, scope):   # noqa: C901 — flat dispatch
        self._tick()
        kind = node[0]
        if kind in ("num", "str"):
            return node[1]
        if kind == "true":
            return True
        if kind == "false":
            return False
        if kind == "null":
            return None
        if kind == "name":
            return self._lookup(node[1], scope)
        if kind == "binop":
            op = node[1]
            if op == "&&":
                return _truthy(self.eval(node[2], scope)) and \
                    _truthy(self.eval(node[3], scope))
            if op == "||":
                return _truthy(self.eval(node[2], scope)) or \
                    _truthy(self.eval(node[3], scope))
            return _binop(op, self.eval(node[2], scope),
                          self.eval(node[3], scope))
        if kind == "not":
            return not _truthy(self.eval(node[1], scope))
        if kind == "neg":
            return -self.eval(node[1], scope)
        if kind == "ternary":
            return self.eval(node[2], scope) \
                if _truthy(self.eval(node[1], scope)) \
                else self.eval(node[3], scope)
        if kind == "elvis":
            v = self.eval(node[1], scope)
            # Groovy truth: 0 / empty collections fall through to the
            # default, exactly as `a ?: b` behaves in the reference
            return v if _truthy(v) else self.eval(node[2], scope)
        if kind == "list":
            return [self.eval(e, scope) for e in node[1]]
        if kind == "map":
            return {self._map_key(k, scope): self.eval(v, scope)
                    for k, v in node[1]}
        if kind == "getattr":
            return self._getattr(self.eval(node[1], scope), node[2])
        if kind == "getitem":
            obj = self.eval(node[1], scope)
            key = self.eval(node[2], scope)
            if isinstance(obj, list):
                return obj[int(key)]
            if isinstance(obj, dict):
                return obj.get(key)
            if isinstance(obj, str):
                return obj[int(key)]
            if hasattr(obj, "__scriptlang_getitem__"):
                return obj.__scriptlang_getitem__(key)
            raise ScriptException(f"cannot index {type(obj).__name__}")
        if kind == "method":
            return self._method(node, scope)
        if kind == "call":
            fn = _FREE_FUNCS.get(node[1])
            if fn is None:
                raise ScriptException(f"unknown function [{node[1]}]")
            return fn(*[self.eval(a, scope) for a in node[2]])
        if kind == "new":
            ctor = _NEWABLE.get(node[1])
            if ctor is None:
                raise ScriptException(f"cannot instantiate [{node[1]}]")
            args = [self.eval(a, scope) for a in node[2]]
            return ctor(args[0]) if args else ctor()
        raise ScriptException(f"unknown expression {kind}")

    def _map_key(self, k, scope):
        # Groovy map literals treat bare names as string keys
        if k[0] == "name":
            return k[1]
        return self.eval(k, scope)

    def _lookup(self, name: str, scope):
        if name in scope:
            return scope[name]
        if name in self.bindings:
            return self.bindings[name]
        if name == "Math":
            return _MATH
        raise ScriptException(f"unknown variable [{name}]")

    def _getattr(self, obj, name: str):
        if name.startswith("__"):
            raise ScriptException(f"forbidden property [{name}]")
        if isinstance(obj, dict):
            return obj.get(name)
        if hasattr(obj, "__scriptlang_getattr__"):
            return obj.__scriptlang_getattr__(name)
        if isinstance(obj, str) and name == "length":
            return len(obj)
        raise ScriptException(
            f"no property [{name}] on {type(obj).__name__}")

    def _method(self, node, scope):
        obj = self.eval(node[1], scope)
        name = node[3 - 1]  # node = ("method", obj, name, args)
        args = [self.eval(a, scope) for a in node[3]]
        if name.startswith("__"):
            raise ScriptException(f"forbidden method [{name}]")
        if obj is _MATH:
            fn = _MATH.get(name)
            if not callable(fn):
                raise ScriptException(f"unknown Math method [{name}]")
            return fn(*args)
        table = None
        if isinstance(obj, list):
            table = _LIST_METHODS
        elif isinstance(obj, dict):
            table = _MAP_METHODS
        elif isinstance(obj, str):
            table = _STR_METHODS
        elif isinstance(obj, (int, float)):
            table = _NUM_METHODS
        elif hasattr(obj, "__scriptlang_method__"):
            return obj.__scriptlang_method__(name, args)
        if table is None or name not in table:
            raise ScriptException(
                f"no method [{name}] on {type(obj).__name__}")
        fn = table[name]
        if fn is None:
            raise ScriptException(
                f"[{name}] requires closures, which GroovyLite does not "
                "support — use a for loop")
        return fn(obj, *args)


def _truthy(v) -> bool:
    # Groovy truth: null/false/empty-ish are false
    if v is None or v is False:
        return False
    if isinstance(v, (str, list, dict)):
        return len(v) > 0
    return bool(v)


def _binop(op: str, a, b):   # noqa: C901 — operator table
    if op == "+":
        if isinstance(a, str) or isinstance(b, str):
            return _to_str(a) + _to_str(b)
        if isinstance(a, list):
            return a + (b if isinstance(b, list) else [b])
        return a + b
    if op == "-":
        return a - b
    if op == "*":
        return a * b
    if op == "/":
        return a / b
    if op == "%":
        return a % b
    if op == "**":
        return a ** b
    if op == "==":
        return a == b
    if op == "!=":
        return a != b
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    if op == ">=":
        return a >= b
    if op == "in":
        return a in b
    raise ScriptException(f"unknown operator {op}")


def _to_str(v) -> str:
    if v is None:
        return "null"
    if v is True:
        return "true"
    if v is False:
        return "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


# The `doc` binding lives in aggregations.py (_AggDocValues): it reads
# the columnar segments directly and handles .keyword subfield fallback.

_COMPILE_CACHE: dict[str, CompiledGroovyLite] = {}


def compile_groovylite(source: str) -> CompiledGroovyLite:
    c = _COMPILE_CACHE.get(source)
    if c is None:
        if len(_COMPILE_CACHE) > 512:
            _COMPILE_CACHE.clear()
        c = CompiledGroovyLite(source)
        _COMPILE_CACHE[source] = c
    return c
