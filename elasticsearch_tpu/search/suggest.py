"""Suggesters — term, phrase, completion.

Reference: core/search/suggest/ — TermSuggester (per-token edit-distance
candidates from the shard's term dictionary, DirectSpellChecker-driven),
PhraseSuggester (candidate generators + language-model scoring over the
whole input), CompletionSuggester (FST prefix lookup over a dedicated
completion field). Shard partials reduce at the coordinator
(Suggest.reduce, used by SearchPhaseController.java:398).

TPU framing: suggestion collection is a host-side dictionary problem
(string edit distances over the term dict), not an MXU problem — it runs
on host arrays next to the segment metadata, like the reference runs it
on Lucene's terms enum, leaving the device path to scoring.
"""

from __future__ import annotations

import numpy as np

from elasticsearch_tpu.common.errors import QueryParsingError


# ---- parsing ---------------------------------------------------------------

class SuggestSpec:
    __slots__ = ("name", "text", "kind", "field", "params")

    def __init__(self, name: str, text: str, kind: str, field: str,
                 params: dict):
        self.name = name
        self.text = text
        self.kind = kind                         # term | phrase | completion
        self.field = field
        self.params = params

    def to_wire(self) -> dict:
        return {"name": self.name, "text": self.text, "kind": self.kind,
                "field": self.field, "params": self.params}

    @staticmethod
    def from_wire(d: dict) -> "SuggestSpec":
        return SuggestSpec(d["name"], d["text"], d["kind"], d["field"],
                           d["params"])


def parse_suggest(body: dict | None) -> list[SuggestSpec]:
    """The `suggest` section: {name: {text|prefix, term|phrase|completion:
    {field, ...}}} (RestSearchAction suggest parsing)."""
    if not body:
        return []
    out = []
    global_text = body.get("text")
    for name, spec in body.items():
        if name == "text":
            continue
        if not isinstance(spec, dict):
            raise QueryParsingError(f"suggest [{name}] must be an object")
        text = spec.get("text", spec.get("prefix", global_text))
        for kind in ("term", "phrase", "completion"):
            if kind in spec:
                params = dict(spec[kind])
                field = params.pop("field", None)
                if field is None:
                    raise QueryParsingError(
                        f"suggest [{name}] requires a field")
                if text is None:
                    raise QueryParsingError(
                        f"suggest [{name}] requires text/prefix")
                out.append(SuggestSpec(name, str(text), kind, field, params))
                break
        else:
            raise QueryParsingError(
                f"suggest [{name}] has no term/phrase/completion section")
    return out


# ---- edit distance ---------------------------------------------------------

def _damerau(a: str, b: str, max_d: int) -> int:
    """Bounded Damerau-Levenshtein (transposition-aware, like Lucene's
    DirectSpellChecker internal distance); returns max_d+1 when exceeded."""
    la, lb = len(a), len(b)
    if abs(la - lb) > max_d:
        return max_d + 1
    prev2: list[int] = []
    prev = list(range(lb + 1))
    for i in range(1, la + 1):
        cur = [i] + [0] * lb
        best = cur[0]
        for j in range(1, lb + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            v = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
            if cost and i > 1 and j > 1 and a[i - 1] == b[j - 2] \
                    and a[i - 2] == b[j - 1]:
                v = min(v, prev2[j - 2] + 1)
            cur[j] = v
            best = min(best, v)
        if best > max_d:
            return max_d + 1
        prev2, prev = prev, cur
    return prev[lb]


# ---- per-shard collection ---------------------------------------------------

class ShardSuggester:
    """Runs suggest specs against one shard's segments."""

    def __init__(self, reader, mapper_service):
        self.reader = reader
        self.mapper_service = mapper_service

    # term dictionary of a text field: term → df summed over live segments
    def _term_stats(self, field: str) -> dict[str, int]:
        stats: dict[str, int] = {}
        for s in self.reader.segments:
            col = s.seg.text_fields.get(field)
            if col is None:
                continue
            df = np.asarray(col.df)
            for tid, term in enumerate(col.terms):
                stats[term] = stats.get(term, 0) + int(df[tid])
        return stats

    def _analyze(self, field: str, text: str) -> list[str]:
        mapper = self.mapper_service.document_mapper().mappers.get(field)
        if mapper is not None and getattr(mapper, "analyzer", None):
            return [t.term for t in mapper.analyzer.analyze(text)]
        return text.lower().split()

    def collect(self, spec: SuggestSpec) -> dict:
        if spec.kind == "term":
            return self._collect_term(spec)
        if spec.kind == "phrase":
            return self._collect_phrase(spec)
        if spec.kind == "completion":
            return self._collect_completion(spec)
        raise QueryParsingError(f"unknown suggester [{spec.kind}]")

    # ---- term ---------------------------------------------------------------

    def _candidates(self, token: str, stats: dict[str, int],
                    params: dict) -> list[dict]:
        max_edits = int(params.get("max_edits", 2))
        prefix_len = int(params.get("prefix_length", 1))
        min_len = int(params.get("min_word_length", 4))
        max_terms = int(params.get("max_term_freq", 0)) or None
        size = int(params.get("size", 5))
        out = []
        tok_df = stats.get(token, 0)
        suggest_mode = params.get("suggest_mode", "missing")
        if suggest_mode == "missing" and tok_df > 0:
            return []
        prefix = token[:prefix_len]
        for term, df in stats.items():
            if term == token or not term.startswith(prefix):
                continue
            if len(term) < min_len and len(token) >= min_len:
                continue
            if suggest_mode == "popular" and df <= tok_df:
                continue
            d = _damerau(token, term, max_edits)
            if d > max_edits:
                continue
            score = 1.0 - d / max(len(token), len(term))
            out.append({"text": term, "freq": df, "score": round(score, 6),
                        "distance": d})
        out.sort(key=lambda c: (-c["score"], -c["freq"], c["text"]))
        if max_terms:
            out = [c for c in out if c["freq"] <= max_terms]
        return out[:size]

    def _collect_term(self, spec: SuggestSpec) -> dict:
        stats = self._term_stats(spec.field)
        entries = []
        offset = 0
        for token in self._analyze(spec.field, spec.text):
            start = spec.text.lower().find(token, offset)
            if start < 0:
                start = offset
            entries.append({
                "text": token, "offset": start, "length": len(token),
                "options": self._candidates(token, stats, spec.params)})
            offset = start + len(token)
        return {"kind": "term", "entries": entries}

    # ---- phrase -------------------------------------------------------------

    def _collect_phrase(self, spec: SuggestSpec) -> dict:
        stats = self._term_stats(spec.field)
        total = max(sum(stats.values()), 1)
        tokens = self._analyze(spec.field, spec.text)
        gen_params = {**spec.params, "suggest_mode": "always",
                      "size": int(spec.params.get(
                          "num_candidates", 5))}
        per_tok: list[list[tuple[str, float]]] = []
        rwel = float(spec.params.get("real_word_error_likelihood", 0.95))
        for tok in tokens:
            opts = [(tok, (stats.get(tok, 0) / total) * rwel
                     if stats.get(tok) else 1e-9)]
            for c in self._candidates(tok, stats, gen_params):
                opts.append((c["text"],
                             (c["freq"] / total) * c["score"]))
            per_tok.append(opts)
        # beam over combinations (the reference scores candidates with a
        # smoothed word LM; unigram product with error likelihood here)
        beam: list[tuple[list[str], float]] = [([], 1.0)]
        width = int(spec.params.get("beam_width", 8))
        for opts in per_tok:
            nxt = [(path + [w], p * wp) for path, p in beam
                   for w, wp in opts]
            nxt.sort(key=lambda e: -e[1])
            beam = nxt[:width]
        size = int(spec.params.get("size", 5))
        options = []
        seen = set()
        for path, p in beam:
            text = " ".join(path)
            if text in seen:
                continue
            seen.add(text)
            if text == " ".join(tokens) and len(beam) > 1:
                continue                         # identity isn't a suggestion
            opt = {"text": text, "score": p}
            hl = spec.params.get("highlight")
            if hl:
                pre, post = hl.get("pre_tag", ""), hl.get("post_tag", "")
                opt["highlighted"] = " ".join(
                    f"{pre}{w}{post}" if w != t else w
                    for w, t in zip(path, tokens))
            options.append(opt)
        return {"kind": "phrase",
                "entries": [{"text": spec.text, "offset": 0,
                             "length": len(spec.text),
                             "options": options[:size]}]}

    # ---- completion ---------------------------------------------------------

    def _collect_completion(self, spec: SuggestSpec) -> dict:
        base = spec.text.lower()
        fm = self.mapper_service.field_mapper(spec.field)
        cfg = getattr(fm, "context_config", None)
        prefixes: list[tuple[str, int]] = [(base, 0)]
        if cfg:
            # context-filtered completion: the index keys are
            # "{ctx}\x1f{input}" (ContextMappings) — every requested
            # context value scans its own key range, options strip the key
            from elasticsearch_tpu.mapping.mapper import (
                completion_context_keys)
            keys = completion_context_keys(cfg,
                                           spec.params.get("context") or {})
            prefixes = [(f"{k}\x1f{base}", len(k) + 1) for k in keys] \
                or prefixes
        counts: dict[str, int] = {}
        strip_of: dict[str, int] = {}
        for s in self.reader.segments:
            col = s.seg.keyword_fields.get(spec.field)
            if col is None:
                continue
            vocab = col.vocab                    # sorted → prefix range scan
            import bisect
            ords = live = None
            for prefix, strip in prefixes:
                lo = bisect.bisect_left(vocab, prefix)
                hi = bisect.bisect_left(vocab, prefix + "￿")
                if hi <= lo:
                    continue
                if ords is None:
                    ords = np.asarray(col.ords)
                    live = np.asarray(s.live)[:ords.shape[0]]
                for o in range(lo, hi):
                    n = int((((ords == o).any(axis=1)) & live).sum())
                    if n:
                        counts[vocab[o]] = counts.get(vocab[o], 0) + n
                        strip_of[vocab[o]] = strip
        def display(t: str) -> str:
            t = t[strip_of.get(t, 0):]
            return t.split("\x1e", 1)[1] if "\x1e" in t else t
        options = [{"text": display(t), "score": float(n)}
                   for t, n in sorted(counts.items(),
                                      key=lambda kv: (-kv[1], kv[0]))]
        size = int(spec.params.get("size", 5))
        return {"kind": "completion",
                "entries": [{"text": spec.text, "offset": 0,
                             "length": len(spec.text),
                             "options": options[:size]}]}


# ---- reduce ----------------------------------------------------------------

def reduce_suggest(specs: list[SuggestSpec], parts: list[dict]) -> dict:
    """Merge per-shard partials: entries align by (offset, length); options
    merge by text — term/completion sum freq/score across shards, phrase
    keeps the max score (Suggest.reduce semantics)."""
    out: dict = {}
    for spec in specs:
        shard_results = [p[spec.name] for p in parts if spec.name in p]
        if not shard_results:
            out[spec.name] = []
            continue
        by_key: dict[tuple, dict] = {}
        order: list[tuple] = []
        for r in shard_results:
            for e in r["entries"]:
                key = (e["offset"], e["length"], e["text"])
                if key not in by_key:
                    by_key[key] = {"text": e["text"], "offset": e["offset"],
                                   "length": e["length"], "_opts": {}}
                    order.append(key)
                opts = by_key[key]["_opts"]
                for o in e["options"]:
                    cur = opts.get(o["text"])
                    if cur is None:
                        opts[o["text"]] = dict(o)
                    elif r["kind"] == "phrase":
                        cur["score"] = max(cur["score"], o["score"])
                    elif r["kind"] == "completion":
                        # score = live doc count → additive across shards
                        cur["score"] += o["score"]
                    else:                        # term: df sums, the edit-
                        cur["freq"] = cur.get("freq", 0) + o.get("freq", 0)
                        cur["score"] = max(cur["score"], o["score"])
        size = int(spec.params.get("size", 5))
        entries = []
        for key in order:
            e = by_key[key]
            opts = sorted(e.pop("_opts").values(),
                          key=lambda o: (-o["score"], -o.get("freq", 0),
                                         o["text"]))
            e["options"] = opts[:size]
            entries.append(e)
        out[spec.name] = entries
    return out
