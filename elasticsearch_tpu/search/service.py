"""SearchService — per-index search orchestration + scroll contexts.

Reference: core/search/SearchService.java — the stateful `activeContexts`
registry keyed by context id (:533-558) with a keep-alive reaper (:1113),
and the query/fetch phase entry points driven by the coordinator
(TransportSearchTypeAction fan-out, §3.2 of SURVEY.md).

Here the shard fan-out is a host loop over shard searchers (the distributed
version runs the same phases under shard_map — parallel/distributed.py);
scroll is implemented as search_after continuation: the context stores the
request + last sort tuple, so each page is a fresh device query with a
continuation mask — no long-lived per-shard cursors pinning memory (the
TPU-friendly redesign of ScrollContext/MinDocQuery,
core/search/query/QueryPhase.java:161-186).
"""

from __future__ import annotations

import base64
import itertools
import json
import threading
import time

from elasticsearch_tpu.common.errors import SearchContextMissingError
from elasticsearch_tpu.common.settings import parse_time_value
from elasticsearch_tpu.index.device_reader import device_reader_for
from elasticsearch_tpu.search.controller import merge_responses
from elasticsearch_tpu.search.phase import (
    ParsedSearchRequest, ShardSearcher, parse_search_request)


class ScrollContext:
    def __init__(self, index: str, body: dict, keep_alive_s: float):
        self.index = index
        self.body = dict(body)
        self.keep_alive_s = keep_alive_s
        self.expires_at = time.monotonic() + keep_alive_s
        self.last_sort_key: list | None = None
        self.finished = False

    def touch(self, keep_alive_s: float | None = None):
        if keep_alive_s is not None:
            self.keep_alive_s = keep_alive_s
        self.expires_at = time.monotonic() + self.keep_alive_s


class SearchService:
    def __init__(self):
        self._contexts: dict[str, ScrollContext] = {}
        self._ctx_ids = itertools.count(1)
        self._lock = threading.Lock()

    # ------------------------------------------------------------- search

    def _searchers(self, index) -> list[ShardSearcher]:
        out = []
        for shard_id, engine in enumerate(index.shard_engines):
            reader = device_reader_for(engine)
            out.append(ShardSearcher(shard_id, reader,
                                     index.mapper_service,
                                     index_name=index.name))
        return out

    def search(self, index, body: dict | None, scroll: str | None = None) -> dict:
        t0 = time.perf_counter()
        if scroll is not None:
            # scroll pages continue via search_after cursors, which need a
            # total order: append a unique (_score desc, _doc asc) or
            # (..., _doc asc) tie-break to the requested sort
            body = dict(body or {})
            sort = body.get("sort")
            if not sort:
                sort = [{"_score": {"order": "desc"}}]
            elif isinstance(sort, (str, dict)):
                sort = [sort]
            else:
                sort = list(sort)
            if not any((s == "_doc") or (isinstance(s, dict) and "_doc" in s)
                       for s in sort):
                sort = sort + [{"_doc": {"order": "asc"}}]
            body["sort"] = sort
        req = parse_search_request(body)
        searchers = self._searchers(index)
        results = [s.query_phase(req) for s in searchers]
        resp = merge_responses(index.name, req, results, searchers,
                               (time.perf_counter() - t0) * 1e3, req.aggs)
        if scroll is not None:
            resp["_scroll_id"] = self._open_scroll(index.name, body,
                                                   scroll, resp, req)
        return resp

    def count(self, index, body: dict | None) -> dict:
        body = dict(body or {})
        body["size"] = 0
        resp = self.search(index, body)
        return {"count": resp["hits"]["total"],
                "_shards": resp["_shards"]}

    # ------------------------------------------------------------- scroll

    def _open_scroll(self, index_name: str, body: dict, scroll: str,
                     first_page: dict, req: ParsedSearchRequest) -> str:
        keep = parse_time_value(scroll, "scroll")
        ctx = ScrollContext(index_name, body, keep)
        self._note_page(ctx, first_page, req)
        with self._lock:
            cid = f"ctx{next(self._ctx_ids)}"
            self._contexts[cid] = ctx
        return base64.b64encode(json.dumps({"id": cid}).encode()).decode()

    def _note_page(self, ctx: ScrollContext, page: dict,
                   req: ParsedSearchRequest):
        hits = page["hits"]["hits"]
        if not hits:
            ctx.finished = True
            return
        ctx.last_sort_key = hits[-1].get("sort")

    def scroll(self, indices_service, scroll_id: str,
               scroll: str | None = None) -> dict:
        try:
            cid = json.loads(base64.b64decode(scroll_id))["id"]
        except Exception:
            raise SearchContextMissingError(f"invalid scroll id") from None
        with self._lock:
            ctx = self._contexts.get(cid)
        if ctx is None or ctx.expires_at < time.monotonic():
            self._contexts.pop(cid, None)
            raise SearchContextMissingError(f"No search context found for id [{cid}]")
        ctx.touch(parse_time_value(scroll, "scroll") if scroll else None)
        index = indices_service.index(ctx.index)
        if ctx.finished:
            body = dict(ctx.body)
            body["size"] = 0
            resp = self.search(index, body)
            resp["hits"]["hits"] = []
            resp["_scroll_id"] = scroll_id
            return resp
        body = dict(ctx.body)   # already carries the _doc-tie-broken sort
        # aggregations are computed once on the first page only (ES behavior;
        # re-running them every page would repeat the full collection)
        body.pop("aggs", None)
        body.pop("aggregations", None)
        if ctx.last_sort_key is not None:
            body["search_after"] = ctx.last_sort_key
        req = parse_search_request(body)
        searchers = self._searchers(index)
        t0 = time.perf_counter()
        results = [s.query_phase(req) for s in searchers]
        resp = merge_responses(index.name, req, results, searchers,
                               (time.perf_counter() - t0) * 1e3, req.aggs)
        self._note_page(ctx, resp, req)
        resp["_scroll_id"] = scroll_id
        return resp

    def clear_scroll(self, scroll_id: str | None) -> int:
        with self._lock:
            if scroll_id is None:
                n = len(self._contexts)
                self._contexts.clear()
                return n
            try:
                cid = json.loads(base64.b64decode(scroll_id))["id"]
            except Exception:
                return 0
            return 1 if self._contexts.pop(cid, None) is not None else 0

    def reap_expired(self) -> int:
        """Keep-alive reaper (SearchService.java:1113)."""
        now = time.monotonic()
        with self._lock:
            dead = [cid for cid, c in self._contexts.items()
                    if c.expires_at < now]
            for cid in dead:
                del self._contexts[cid]
        return len(dead)

    @property
    def active_contexts(self) -> int:
        return len(self._contexts)
