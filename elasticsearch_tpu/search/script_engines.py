"""Script engine registry — the ScriptService engines map
(core/script/ScriptService.java:227: one ScriptEngineService per lang,
plugins register more through the normal SPI).

An engine is ``compile(source) -> compiled`` where ``compiled.run
(bindings) -> value``; bindings carry ``doc`` (per-hit doc values view),
``params``, and context-specific extras (``_score``, ``ctx`` for
updates, agg ``state``). The registry holds only the per-hit
interpreters — groovy/groovylite built-in, plus whatever plugins add
(plugin_pack/lang_python registers "python"); the vectorized expression
engine and mustache templates have their own batch/render calling
conventions and are dispatched by their callers directly.
"""

from __future__ import annotations

ENGINES: dict = {}


def register_engine(lang: str, compile_fn) -> None:
    ENGINES[lang] = compile_fn


def resolve_engine(lang: str | None):
    """Explicit lang → its engine, RAISING when not installed (a silent
    GroovyLite fallback would interpret the script under the wrong
    language's semantics); None → the GroovyLite default."""
    from elasticsearch_tpu.common.errors import QueryParsingError
    from elasticsearch_tpu.search.scriptlang import compile_groovylite
    if lang is None:
        return compile_groovylite
    fn = ENGINES.get(str(lang))
    if fn is None:
        raise QueryParsingError(
            f"script lang [{lang}] is not installed")
    return fn


def _register_builtins() -> None:
    from elasticsearch_tpu.search.scriptlang import compile_groovylite
    ENGINES.setdefault("groovy", compile_groovylite)
    ENGINES.setdefault("groovylite", compile_groovylite)


_register_builtins()
