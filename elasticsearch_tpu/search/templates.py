"""Search templates — parameterized request bodies.

Reference: core/script/Template.java:54 + mustache rendering
(core/script/mustache/MustacheScriptEngineService.java), used by
`parseTemplate` (core/search/SearchService.java:576) and the
/_search/template REST API. Stored scripts/templates live in cluster
state here (the reference stores them in a hidden .scripts index —
metadata storage gives the same durability with the machinery we already
replicate; see search/percolator.py for the same reasoning).

The template language is the mustache subset search templates actually
use: `{{var}}` substitution (dotted paths), `{{#var}}...{{/var}}`
conditional sections, and `{{^var}}...{{/var}}` inverted sections
(defaults). JSON-aware: a `{{var}}` standing alone inside quotes renders
as the JSON value; `{{#toJson}}var{{/toJson}}` embeds structures.
"""

from __future__ import annotations

import json
import re

from elasticsearch_tpu.common.errors import IllegalArgumentError


def _lookup(params: dict, path: str):
    node = params
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node


_SECTION = re.compile(r"\{\{([#^])([\w.]+)\}\}(.*?)\{\{/\2\}\}", re.S)
_TOJSON = re.compile(r"\{\{#toJson\}\}([\w.]+)\{\{/toJson\}\}")
_QUOTED_VAR = re.compile(r'"\{\{([\w.]+)\}\}"')
_VAR = re.compile(r"\{\{([\w.]+)\}\}")


def render_template(source: str, params: dict) -> str:
    """Mustache-subset render of a template string with `params`."""
    params = params or {}

    def do_section(m: re.Match) -> str:
        kind, name, body = m.group(1), m.group(2), m.group(3)
        val = _lookup(params, name)
        truthy = bool(val) and val not in (0, "")
        if kind == "#":
            return render_template(body, params) if truthy else ""
        return render_template(body, params) if not truthy else ""

    out = _SECTION.sub(do_section, source)
    out = _TOJSON.sub(lambda m: json.dumps(_lookup(params, m.group(1))), out)

    def quoted(m: re.Match) -> str:
        val = _lookup(params, m.group(1))
        if val is None:
            return "null"
        return json.dumps(val)

    out = _QUOTED_VAR.sub(quoted, out)
    out = _VAR.sub(lambda m: str(_lookup(params, m.group(1)) or ""), out)
    return out


def render_search_template(spec: dict, stored_lookup) -> dict:
    """{"inline"/"source"/"id"/"file", "params"} → rendered search body.
    `stored_lookup(id)` resolves stored templates (cluster state)."""
    params = spec.get("params", {})
    source = spec.get("inline", spec.get("source", spec.get("template")))
    if isinstance(source, dict) and "id" in source and \
            not any(k in source for k in ("query", "inline", "source")):
        # {"template": {"id": ...}} names a stored template, it is not an
        # inline body (RestSearchTemplateAction id form)
        spec = {**spec, "id": source["id"]}
        source = None
    if source is None and "id" in spec:
        source = stored_lookup(spec["id"])
        if source is None:
            raise IllegalArgumentError(
                f"stored template [{spec['id']}] not found")
    if source is None:
        raise IllegalArgumentError(
            "search template needs inline/source or id")
    if isinstance(source, dict):
        source = json.dumps(source)
    rendered = render_template(source, params)
    try:
        return json.loads(rendered)
    except json.JSONDecodeError as e:
        raise IllegalArgumentError(
            f"template rendered to invalid JSON: {e}") from None
