"""Highlighting — plain highlighter.

Reference: core/search/highlight/HighlightPhase.java with the plain
highlighter re-analyzing stored field text and wrapping matched terms.
Host-side fetch-phase work (runs only on the final k hits), so no device
involvement — same as the reference, where highlighting is fetch-phase CPU.
"""

from __future__ import annotations

import re

from elasticsearch_tpu.search import query_dsl as q


def _query_terms_for_field(query, field: str, mapper_service) -> set[str]:
    """Extractable terms of the query affecting `field` (analyzed)."""
    terms: set[str] = set()

    def walk(node):
        if isinstance(node, (q.MatchQuery, q.MatchPhraseQuery)):
            if node.field == field or field == "*":
                fm = mapper_service.field_mapper(node.field)
                analyzer = fm.search_analyzer if fm is not None and \
                    getattr(fm, "kind", None) == "text" \
                    else mapper_service.analysis.get("standard")
                terms.update(t.term for t in analyzer.analyze(node.text))
        elif isinstance(node, q.TermQuery):
            if node.field == field or field == "*":
                terms.add(str(node.value).lower())
        elif isinstance(node, q.TermsQuery):
            if node.field == field or field == "*":
                terms.update(str(v).lower() for v in node.values)
        elif isinstance(node, q.MultiMatchQuery):
            for fspec in node.fields:
                fname = fspec.split("^")[0]
                if fname == field or field == "*":
                    analyzer = mapper_service.analysis.get("standard")
                    terms.update(t.term for t in analyzer.analyze(node.text))
        elif isinstance(node, q.BoolQuery):
            for sub in (*node.must, *node.should, *node.filter):
                walk(sub)
        elif isinstance(node, q.FunctionScoreQuery):
            walk(node.query)
        elif isinstance(node, (q.ConstantScoreQuery,)):
            walk(node.filter_query)
        elif isinstance(node, q.ScriptScoreQuery):
            walk(node.query)

    walk(query)
    terms.discard("")
    return terms


def highlight_field(text: str, terms: set[str], analyzer,
                    pre_tag: str, post_tag: str,
                    fragment_size: int, number_of_fragments: int) -> list[str]:
    if not terms:
        return []
    tokens = analyzer.analyze(text)
    spans = [(t.start_offset, t.end_offset) for t in tokens if t.term in terms]
    if not spans:
        return []
    # merge overlapping spans, build highlighted full text
    spans.sort()
    merged = [spans[0]]
    for s, e in spans[1:]:
        if s <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(e, merged[-1][1]))
        else:
            merged.append((s, e))
    out = []
    last = 0
    for s, e in merged:
        out.append(text[last:s])
        out.append(pre_tag + text[s:e] + post_tag)
        last = e
    out.append(text[last:])
    full = "".join(out)
    if number_of_fragments == 0:
        return [full]
    # fragmenting: split around highlights
    fragments = []
    for s, e in merged[:number_of_fragments]:
        lo = max(0, s - fragment_size // 2)
        hi = min(len(text), e + fragment_size // 2)
        frag = text[lo:s] + pre_tag + text[s:e] + post_tag + text[e:hi]
        fragments.append(frag)
    return fragments


def highlight_hit(spec: dict, source: dict, mapper_service, query) -> dict:
    pre = (spec.get("pre_tags") or ["<em>"])[0]
    post = (spec.get("post_tags") or ["</em>"])[0]
    out = {}
    for fname, fspec in (spec.get("fields") or {}).items():
        fspec = fspec or {}
        fragment_size = int(fspec.get("fragment_size",
                                      spec.get("fragment_size", 100)))
        nfrags = int(fspec.get("number_of_fragments",
                               spec.get("number_of_fragments", 5)))
        value = _get_path(source, fname)
        if value is None:
            continue
        fm = mapper_service.field_mapper(fname)
        analyzer = fm.analyzer if fm is not None and \
            getattr(fm, "kind", None) == "text" \
            else mapper_service.analysis.get("standard")
        terms = _query_terms_for_field(query, fname, mapper_service)
        values = value if isinstance(value, list) else [value]
        frags: list[str] = []
        for v in values:
            frags.extend(highlight_field(str(v), terms, analyzer, pre, post,
                                         fragment_size, nfrags))
        if frags:
            out[fname] = frags[:nfrags] if nfrags > 0 else frags
    return out


def _get_path(source: dict, path: str):
    node = source
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node
