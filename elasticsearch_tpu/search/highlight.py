"""Highlighting — plain + postings-class highlighters, phrase-accurate.

Reference: core/search/highlight/ — HighlightPhase drives one of three
implementations: the plain highlighter (QueryScorer over re-analyzed
text), PostingsHighlighter (passage scoring from postings offsets) and
FastVectorHighlighter (term-vector phrase-accurate fragments). All three
are phrase-accurate: a match_phrase "quick fox" only highlights "quick"
adjacent to "fox", never stray occurrences.

This module implements the same contract host-side at fetch time (runs
only on the final k hits, same as the reference where highlighting is
fetch-phase CPU):

* query **units** are extracted per field — single terms and positional
  units (phrases / span-near chains with slop + order);
* the stored text is analyzed once into position/offset-annotated
  tokens; positional units match against token POSITIONS (the
  re-analysis equivalent of postings/term-vector positions, exact
  because analyzers are deterministic), so phrase highlighting marks
  only real phrase occurrences;
* ``type: plain`` (default) wraps matches and emits char-window
  fragments; ``type: postings`` / ``fvh`` / ``unified`` build
  sentence-broken PASSAGES, score them (unit weight × occurrence count,
  longer/rarer units heavier — the PassageScorer discipline), keep the
  top ``number_of_fragments`` and emit them in document order, with
  ``no_match_size`` returning the leading passage when nothing matched
  (PostingsHighlighter semantics).
"""

from __future__ import annotations

import re

from elasticsearch_tpu.search import query_dsl as q


# ---------------------------------------------------------------------------
# query unit extraction
# ---------------------------------------------------------------------------

class _Units:
    """Extracted per-field highlight units."""

    def __init__(self):
        self.terms: set[str] = set()
        # (terms tuple, slop, in_order)
        self.phrases: list[tuple[tuple[str, ...], int, bool]] = []

    def empty(self) -> bool:
        return not self.terms and not self.phrases


def _analyzer_for(field: str, mapper_service, override: str | None = None,
                  for_index: bool = False):
    """``for_index=True`` → the INDEX analyzer (stored doc text must be
    re-analyzed the way it was indexed — an edge_ngram index analyzer
    with a standard search analyzer only highlights if the doc side
    produces the ngrams the query terms are); False → the search
    analyzer (query text)."""
    if override:
        a = mapper_service.analysis.get(override)
        if a is not None:
            return a
    fm = mapper_service.field_mapper(field)
    if fm is not None and getattr(fm, "kind", None) == "text":
        return fm.analyzer if for_index else fm.search_analyzer
    return mapper_service.analysis.get("standard")


def _span_terms(node, field: str) -> list[str] | None:
    """Flatten a span clause into its term sequence for `field` (None =
    not this field / unsupported shape, skip)."""
    if isinstance(node, q.SpanTermQuery):
        return [str(node.value).lower()] if node.field == field or \
            field == "*" else None
    if isinstance(node, q.FieldMaskingSpanQuery):
        return _span_terms(node.query, field)
    if isinstance(node, q.SpanFirstQuery):
        return _span_terms(node.match, field)
    return None


def _extract_units(query, field: str, mapper_service) -> _Units:
    units = _Units()

    def walk(node):
        if isinstance(node, q.MatchQuery):
            if node.field == field or field == "*":
                analyzer = _analyzer_for(node.field, mapper_service,
                                         node.analyzer)
                units.terms.update(
                    t.term for t in analyzer.analyze(node.text))
        elif isinstance(node, q.MatchPhraseQuery):
            if node.field == field or field == "*":
                analyzer = _analyzer_for(node.field, mapper_service,
                                         node.analyzer)
                terms = tuple(t.term
                              for t in analyzer.analyze(node.text))
                if len(terms) == 1:
                    units.terms.add(terms[0])
                elif terms:
                    units.phrases.append((terms, int(node.slop), True))
        elif isinstance(node, q.TermQuery):
            if node.field == field or field == "*":
                units.terms.add(str(node.value).lower())
        elif isinstance(node, q.TermsQuery):
            if node.field == field or field == "*":
                units.terms.update(str(v).lower() for v in node.values)
        elif isinstance(node, q.CommonTermsQuery):
            if node.field == field or field == "*":
                analyzer = _analyzer_for(node.field, mapper_service)
                units.terms.update(
                    t.term for t in analyzer.analyze(node.text))
        elif isinstance(node, q.MultiMatchQuery):
            for fspec in node.fields:
                fname = fspec.split("^")[0]
                if fname == field or field == "*":
                    analyzer = _analyzer_for(fname, mapper_service)
                    units.terms.update(
                        t.term for t in analyzer.analyze(node.text))
        elif isinstance(node, q.SpanNearQuery):
            seq: list[str] = []
            ok = True
            for cl in node.clauses:
                ts = _span_terms(cl, field)
                if ts is None:
                    ok = False
                    break
                seq.extend(ts)
            if ok and seq:
                if len(seq) == 1:
                    units.terms.add(seq[0])
                else:
                    units.phrases.append((tuple(seq), int(node.slop),
                                          bool(node.in_order)))
        elif isinstance(node, (q.SpanTermQuery, q.SpanFirstQuery,
                               q.FieldMaskingSpanQuery)):
            ts = _span_terms(node, field)
            if ts:
                units.terms.update(ts)
        elif isinstance(node, q.SpanOrQuery):
            for cl in node.clauses:
                walk(cl)
        elif isinstance(node, q.SpanNotQuery):
            walk(node.include)
        elif isinstance(node, (q.SpanContainingQuery, q.SpanWithinQuery)):
            walk(node.big)
            walk(node.little)
        elif isinstance(node, q.BoolQuery):
            for sub in (*node.must, *node.should, *node.filter):
                walk(sub)
        elif isinstance(node, q.DisMaxQuery):
            for sub in node.queries:
                walk(sub)
        elif isinstance(node, q.BoostingQuery):
            walk(node.positive)
        elif isinstance(node, q.FunctionScoreQuery):
            walk(node.query)
        elif isinstance(node, q.ConstantScoreQuery):
            walk(node.filter_query)
        elif isinstance(node, q.ScriptScoreQuery):
            walk(node.query)

    walk(query)
    units.terms.discard("")
    return units


# ---------------------------------------------------------------------------
# match finding (positional — phrase-accurate)
# ---------------------------------------------------------------------------

def _find_match_spans(tokens, units: _Units) -> list[tuple[int, int, int]]:
    """→ [(start_offset, end_offset, weight)] of real matches.

    Single terms match every occurrence at weight 1. Positional units
    match only token runs that satisfy the phrase/span semantics
    (adjacency for slop 0; width ≤ len+slop windows otherwise, order
    respected when in_order) at weight len(unit) — the specificity
    weighting of PassageScorer."""
    spans: list[tuple[int, int, int]] = []
    for t in tokens:
        if t.term in units.terms:
            spans.append((t.start_offset, t.end_offset, 1))
    if units.phrases:
        by_term: dict[str, list] = {}
        for t in tokens:
            by_term.setdefault(t.term, []).append(t)
        for terms, slop, in_order in units.phrases:
            occs = [by_term.get(term) for term in terms]
            if any(not o for o in occs):
                continue
            w = len(terms)
            if slop == 0 and in_order:
                # exact adjacency on positions
                for t0 in occs[0]:
                    run = [t0]
                    p = t0.position
                    ok = True
                    for nxt in occs[1:]:
                        p += 1
                        hit = next((t for t in nxt if t.position == p),
                                   None)
                        if hit is None:
                            ok = False
                            break
                        run.append(hit)
                    if ok:
                        for t in run:
                            spans.append((t.start_offset, t.end_offset,
                                          w))
            else:
                # sloppy window: pick one occurrence per clause inside a
                # window of width ≤ len+slop (order enforced if asked) —
                # greedy earliest-window sweep, the NearSpans discipline
                spans.extend(
                    (t.start_offset, t.end_offset, w)
                    for t in _sloppy_matches(occs, slop, in_order))
    return spans


def _sloppy_matches(occs: list, slop: int, in_order: bool) -> list:
    width = len(occs) + slop
    out = []
    for t0 in occs[0]:
        lo = t0.position
        chosen = [t0]
        ok = True
        prev = t0.position
        for nxt in occs[1:]:
            if in_order:
                cands = [t for t in nxt
                         if prev < t.position <= lo + width - 1]
            else:
                # a later clause's term may PRECEDE the anchor by up to
                # the full window (the final wmax-wmin check enforces
                # exactness) — bounding at lo - slop would miss
                # "quick fox" for span_near [fox, quick] slop 0
                cands = [t for t in nxt
                         if lo - (len(occs) - 1 + slop) <= t.position
                         <= lo + width - 1
                         and all(t.position != c.position
                                 for c in chosen)]
            if not cands:
                ok = False
                break
            hit = min(cands, key=lambda t: t.position)
            chosen.append(hit)
            prev = hit.position
        if ok:
            wmin = min(t.position for t in chosen)
            wmax = max(t.position for t in chosen)
            if wmax - wmin <= len(occs) - 1 + slop:
                out.extend(chosen)
    return out


def _merge_spans(spans: list[tuple[int, int, int]]
                 ) -> list[tuple[int, int, int]]:
    if not spans:
        return []
    spans.sort(key=lambda s: (s[0], -s[1]))
    merged = [spans[0]]
    for s, e, w in spans[1:]:
        ls, le, lw = merged[-1]
        if s <= le:
            merged[-1] = (ls, max(e, le), max(w, lw))
        else:
            merged.append((s, e, w))
    return merged


# ---------------------------------------------------------------------------
# plain highlighter (char-window fragments; now phrase-accurate)
# ---------------------------------------------------------------------------

def highlight_field(text: str, units: _Units, analyzer,
                    pre_tag: str, post_tag: str,
                    fragment_size: int,
                    number_of_fragments: int) -> list[str]:
    if units.empty():
        return []
    tokens = analyzer.analyze(text)
    merged = _merge_spans(_find_match_spans(tokens, units))
    if not merged:
        return []
    if number_of_fragments == 0:
        out = []
        last = 0
        for s, e, _ in merged:
            out.append(text[last:s])
            out.append(pre_tag + text[s:e] + post_tag)
            last = e
        out.append(text[last:])
        return ["".join(out)]
    # cluster nearby matches into one window each, then wrap EVERY
    # match inside the window (a phrase's second term must not appear
    # bare beside its highlighted first term)
    clusters: list[list[tuple[int, int, int]]] = [[merged[0]]]
    for sp in merged[1:]:
        if sp[0] - clusters[-1][0][0] <= fragment_size:
            clusters[-1].append(sp)
        else:
            clusters.append([sp])
    fragments = []
    for cluster in clusters[:number_of_fragments]:
        cs, ce = cluster[0][0], cluster[-1][1]
        lo = max(0, cs - fragment_size // 2)
        hi = min(len(text), ce + fragment_size // 2)
        out = []
        last = lo
        for s, e, _ in cluster:
            out.append(text[last:s])
            out.append(pre_tag + text[s:e] + post_tag)
            last = e
        out.append(text[last:hi])
        fragments.append("".join(out))
    return fragments


# ---------------------------------------------------------------------------
# postings-class highlighter (passage scoring + best fragments)
# ---------------------------------------------------------------------------

_SENTENCE_BREAK = re.compile(r"(?<=[.!?。！？\n])\s*")


def _passages(text: str, max_len: int) -> list[tuple[int, int]]:
    """Sentence-broken passages, long sentences split at max_len —
    Java BreakIterator.getSentenceInstance behavior approximated."""
    out = []
    start = 0
    for m in _SENTENCE_BREAK.finditer(text):
        end = m.end()
        if end > start:
            out.append((start, end))
            start = end
    if start < len(text):
        out.append((start, len(text)))
    split: list[tuple[int, int]] = []
    for s, e in out:
        while e - s > max_len * 2:
            cut = text.rfind(" ", s, s + max_len)
            cut = cut if cut > s else s + max_len
            split.append((s, cut))
            s = cut
        split.append((s, e))
    return split


def _snap_bounds_to_spans(bounds: list[tuple[int, int]],
                          merged: list[tuple[int, int, int]]
                          ) -> list[tuple[int, int]]:
    """A sentence break falling INSIDE a match span (the '.' of a
    whitespace-analyzed token like "3.5") must not split the span
    across passages — it would fail containment in both and silently
    drop the highlight. Snap such boundaries to the span end."""
    if len(bounds) < 2 or not merged:
        return bounds
    start, endall = bounds[0][0], bounds[-1][1]
    out = []
    for b in (b_s for b_s, _ in bounds[1:]):
        for s, e, _ in merged:
            if s < b < e:
                b = e
                break
        b = min(b, endall)
        if b > start:
            out.append((start, b))
            start = b
    if start < endall:
        out.append((start, endall))
    return out


def highlight_field_passages(text: str, units: _Units, analyzer,
                             pre_tag: str, post_tag: str,
                             fragment_size: int,
                             number_of_fragments: int,
                             no_match_size: int = 0) -> list[str]:
    tokens = analyzer.analyze(text)
    merged = _merge_spans(_find_match_spans(tokens, units)) \
        if not units.empty() else []
    if not merged:
        if no_match_size > 0 and text:
            bounds = _passages(text, max(fragment_size, 1))
            s, e = bounds[0]
            return [text[s:min(e, s + no_match_size)]]
        return []
    bounds = _snap_bounds_to_spans(
        _passages(text, max(fragment_size, 1)), merged)
    scored = []
    for pi, (ps, pe) in enumerate(bounds):
        inside = [(s, e, w) for s, e, w in merged
                  if s >= ps and e <= pe]
        if not inside:
            continue
        # PassageScorer discipline: unit weight × count, longer
        # passages slightly penalized so tight matches win ties
        score = sum(w for _, _, w in inside) * \
            (1.0 + 1.0 / (1.0 + (pe - ps) / max(fragment_size, 1)))
        scored.append((score, pi, ps, pe, inside))
    scored.sort(key=lambda x: (-x[0], x[1]))
    top = sorted(scored[:max(number_of_fragments, 1)],
                 key=lambda x: x[1])          # document order
    frags = []
    for _, _, ps, pe, inside in top:
        out = []
        last = ps
        for s, e, _ in inside:
            out.append(text[last:s])
            out.append(pre_tag + text[s:e] + post_tag)
            last = e
        out.append(text[last:pe])
        frags.append("".join(out).strip())
    return frags


# ---------------------------------------------------------------------------
# fetch-phase entry
# ---------------------------------------------------------------------------

_PASSAGE_TYPES = ("postings", "fvh", "fast-vector-highlighter", "unified")


def highlight_hit(spec: dict, source: dict, mapper_service, query) -> dict:
    pre = (spec.get("pre_tags") or ["<em>"])[0]
    post = (spec.get("post_tags") or ["</em>"])[0]
    out = {}
    for fname, fspec in (spec.get("fields") or {}).items():
        fspec = fspec or {}
        fragment_size = int(fspec.get("fragment_size",
                                      spec.get("fragment_size", 100)))
        nfrags = int(fspec.get("number_of_fragments",
                               spec.get("number_of_fragments", 5)))
        htype = str(fspec.get("type", spec.get("type", "plain")))
        no_match = int(fspec.get("no_match_size",
                                 spec.get("no_match_size", 0)))
        value = _get_path(source, fname)
        if value is None:
            continue
        analyzer = _analyzer_for(fname, mapper_service, for_index=True)
        units = _extract_units(query, fname, mapper_service)
        values = value if isinstance(value, list) else [value]
        frags: list[str] = []
        for v in values:
            if htype in _PASSAGE_TYPES:
                frags.extend(highlight_field_passages(
                    str(v), units, analyzer, pre, post, fragment_size,
                    nfrags, no_match_size=no_match))
            else:
                frags.extend(highlight_field(
                    str(v), units, analyzer, pre, post, fragment_size,
                    nfrags))
        if frags:
            out[fname] = frags[:nfrags] if nfrags > 0 else frags
    return out


def _get_path(source: dict, path: str):
    node = source
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node
