"""Query execution: AST → (scores, mask) per device segment.

The analog of Lucene's Query.createWeight/scorer split as driven by
QueryPhase.execute (core/search/query/QueryPhase.java:99-314), re-designed
for XLA in two phases:

* **resolve** (:class:`SegmentResolver`) — host-side "createWeight": walk
  the AST resolving per-segment constants (term ids from the segment term
  dictionary, idf from reader-aggregated df, keyword ordinal bounds,
  double-double range bounds) into a :class:`ConstTable`, and return an
  *emit closure*. Resolution is dictionary lookups only — microseconds per
  query — so planning scales to batched/high-QPS dispatch.
* **emit** — the "scorer": pure jnp ops over the segment's columns, read
  through :class:`EmitCtx` so the SAME closure runs eagerly (numpy
  constants, real columns) or inside jit (traced constants, traced column
  views) — one implementation, no parity drift between the compiled path
  and its fallback oracle.

The ConstTable separates a query's *structure* (static signature tokens +
constant shapes) from its *constants* (values): queries sharing a signature
share one compiled XLA program, with constants as inputs — and a batch of
same-signature queries runs under ``jax.vmap`` with constants stacked on a
leading axis (jit_exec.run_segment_batch).

Term-to-ordinal resolution happens host-side, which is exactly the part of
Lucene's per-segment TermsEnum.seek that has no business running on an
accelerator.
"""

from __future__ import annotations

import fnmatch
import re
from dataclasses import dataclass
from typing import Any, Callable

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.common.errors import QueryParsingError
from elasticsearch_tpu.index.device_reader import (
    DeviceReader, DeviceSegment, dd_split)
from elasticsearch_tpu.mapping.mapper import parse_date, KIND_NUMERIC
from elasticsearch_tpu.ops import (
    lexical, phrase as phrase_ops, boolean as bool_ops, filters as filter_ops,
    vector as vector_ops, functionscore as fs_ops)
from elasticsearch_tpu.ops.similarity import BM25Params, idf as bm25_idf
from elasticsearch_tpu.search import query_dsl as q
from elasticsearch_tpu.search.scripts import ScriptContext, compile_script


class ConstTable:
    """A query plan's dynamic constants + structural signature.

    ``add`` registers a constant and returns its index (a *const ref*);
    emit closures fetch it back through ``EmitCtx.get`` — by index, so the
    scheme is insensitive to evaluation order. ``static`` records anything
    that changes the traced structure (field names, clause counts,
    modifiers, slop windows...) into the signature.
    """

    __slots__ = ("values", "sig", "positions_needed", "vectors_needed")

    def __init__(self):
        self.values: list[np.ndarray] = []
        self.sig: list = []
        # text fields whose POSITION matrix ([N, L] tokens) the plan
        # reads (phrase/span scoring). Everything else runs on the
        # forward-impact columns, and jit_exec excludes untouched token
        # matrices from the traced inputs — at 1M docs the tokens array
        # alone made XLA compile ~14x slower for plans that never read it
        self.positions_needed: set = set()
        # vector fields whose [N, D] vecs the plan reads — same
        # tree-shaking contract as positions_needed (the [N] bool exists
        # arrays are always traced; only vecs are lazy/shaken)
        self.vectors_needed: set = set()

    def add(self, v, dtype=None) -> int:
        arr = np.asarray(v, dtype=dtype)
        self.values.append(arr)
        self.sig.append(("c", arr.shape, str(arr.dtype)))
        return len(self.values) - 1

    def static(self, *tokens) -> None:
        self.sig.append(tokens)

    def signature(self) -> tuple:
        return tuple(self.sig)


class EmitCtx:
    """Hands emit closures their segment view and resolved constants.

    ``seg`` is either the real :class:`DeviceSegment` (eager) or the
    traced rebuild of it inside jit (jit_exec.seg_rebuild); ``consts`` are
    numpy arrays (eager) or traced arrays (jit). Emit closures MUST read
    every array through this object — never through the resolver's
    segment — or the compiled program would bake device buffers in as
    constants instead of taking them as inputs.
    """

    __slots__ = ("seg", "consts", "n")

    def __init__(self, seg: DeviceSegment, consts):
        self.seg = seg
        self.consts = consts
        self.n = seg.padded_docs

    def get(self, ref: int):
        return self.consts[ref]


# emit closure: EmitCtx → (scores [N] f32, mask [N] bool)
Emit = Callable[[EmitCtx], tuple]


@dataclass
class ExecutionContext:
    reader: DeviceReader
    mapper_service: Any
    bm25: BM25Params = BM25Params()
    # Optional global term statistics (DFS_QUERY_THEN_FETCH,
    # core/search/dfs/DfsPhase.java:45), produced by search/dfs.py:
    # {"df": {(field, term): int}, "doc_count": {field: int},
    # "avgdl": {field: float}}. When set, idf and avgdl come from here
    # instead of the shard-local reader, so every shard scores with
    # identical statistics.
    dfs_stats: dict | None = None
    # The shard's index name — resolves the `indices` query per shard
    # (IndicesQueryParser picks query vs no_match_query by index). None →
    # standalone searchers match the listed branch (single-index tests).
    index_name: str | None = None


def impact_terms(query: "q.Query", mapper_service,
                 max_terms: int = 64) -> tuple | None:
    """Impact-lane eligibility: can this query be scored from the
    quantized per-(term, doc) impact columns alone?

    The precomputed impacts bake idf·tfNorm for default-BM25
    OR-semantics term scoring — exactly the disjunctive match/term
    shapes, nothing else. → (field, analyzed terms, boost) when
    eligible, None otherwise (the exact scorer stays the default: any
    shape the quantized path can't reproduce — operators, msm,
    alternative similarities, compounds, functions — declines here).
    Mapping-only (no segment needed) so the collective-plane admission
    can consult the same screen."""
    t = type(query).__name__
    if t == "TermQuery":
        fm = mapper_service.field_mapper(query.field)
        if fm is None or getattr(fm, "kind", None) != "text":
            return None
        # term-on-text scores like a single-term match through the
        # keyword analyzer (the _res_TermQuery rewrite)
        query = q.MatchQuery(field=query.field, text=str(query.value),
                             analyzer="keyword", boost=query.boost)
        t = "MatchQuery"
    if t != "MatchQuery":
        return None
    field = query.field
    if field in ("*", "_all"):
        return None
    fm = mapper_service.field_mapper(field)
    if fm is None or getattr(fm, "kind", None) != "text":
        return None
    sim = fm.params.get("similarity") or \
        getattr(mapper_service, "default_similarity", None)
    if str(sim or "BM25").lower() not in ("bm25",):
        return None
    if query.operator == "and" or \
            query.minimum_should_match not in (None, 1):
        return None
    if not (query.boost >= 0):            # negative boost flips order —
        return None                       # block bounds would invert
    if query.analyzer:
        analyzer = mapper_service.analysis.get(query.analyzer)
    else:
        analyzer = fm.search_analyzer
    if analyzer is None:
        return None
    terms = [tok.term for tok in analyzer.analyze(query.text)]
    if not terms or len(terms) > max_terms:
        return None
    return field, terms, float(query.boost)


def fuzzy_kmax(value: str, fuzziness) -> int:
    """The AUTO edit-distance ladder (FuzzyQuery defaults): 0 below 3
    chars, 1 below 6, else 2."""
    if fuzziness == "AUTO":
        return 0 if len(value) < 3 else (1 if len(value) < 6 else 2)
    return int(fuzziness)


def multi_term_pred(inner):
    """term-predicate for a multi-term query node (prefix / wildcard /
    regexp / fuzzy) — the single rewrite seam shared by the _res_* arms
    and the span_multi expansion (Lucene's MultiTermQuery TermsEnum)."""
    it = type(inner).__name__
    if it == "PrefixQuery":
        val = inner.value
        return lambda term: term.startswith(val)
    if it == "WildcardQuery":
        rx = re.compile(fnmatch.translate(inner.pattern))
        return lambda term: rx.match(term) is not None
    if it == "RegexpQuery":
        rx = re.compile(inner.pattern)
        return lambda term: rx.fullmatch(term) is not None
    if it == "FuzzyQuery":
        v = inner.value
        kmax = fuzzy_kmax(v, inner.fuzziness)
        return lambda term: _edit_distance_le(term, v, kmax)
    return None


def _edit_distance_le(a: str, b: str, k: int) -> bool:
    """Banded Levenshtein ≤ k (fuzzy query vocab scan)."""
    if abs(len(a) - len(b)) > k:
        return False
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i] + [0] * len(b)
        lo = max(1, i - k)
        hi = min(len(b), i + k)
        if lo > 1:
            cur[lo - 1] = k + 1
        for j in range(lo, hi + 1):
            cost = 0 if ca == b[j - 1] else 1
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
        for j in range(hi + 1, len(b) + 1):
            cur[j] = k + 1
        prev = cur
        if min(prev) > k:
            return False
    return prev[len(b)] <= k


class SegmentResolver:
    """Host-side "createWeight": resolves query ASTs against one segment's
    dictionaries into emit closures + a ConstTable."""

    def __init__(self, seg: DeviceSegment, ctx: ExecutionContext,
                 ct: ConstTable | None = None):
        self.seg = seg
        self.ctx = ctx
        self.ct = ct if ct is not None else ConstTable()
        self.n = seg.padded_docs
        self.c = self.ct.add
        self.sig = self.ct.static

    # ------------------------------------------------------------------ util

    def _analyzer_for(self, field: str, override: str | None):
        ms = self.ctx.mapper_service
        if override:
            return ms.analysis.get(override)
        fm = ms.field_mapper(field)
        if fm is not None and getattr(fm, "kind", None) == "text":
            return fm.search_analyzer
        return ms.analysis.get("standard")

    def _similarity_for(self, field: str) -> str:
        """Per-field similarity module (ref: SimilarityModule — BM25 /
        classic (the 2.x "default" TF-IDF) / lm_dirichlet), from the
        field mapping's `similarity` or the index default."""
        fm = self.ctx.mapper_service.field_mapper(field)
        sim = None
        if fm is not None:
            sim = fm.params.get("similarity")
        if sim is None:
            sim = getattr(self.ctx.mapper_service, "default_similarity",
                          None)
        # NOTE: phrase/common/span queries score BM25 regardless — like
        # idf, the alt similarities apply to term-frequency scoring paths
        # (match, term-on-text, multi_match via its match subs)
        sim = str(sim or "BM25").lower()
        if sim in ("default", "classic", "tfidf", "tf/idf"):
            return "classic"
        if sim in ("lmdirichlet", "lm_dirichlet"):
            return "lm_dirichlet"
        return "bm25"

    def _ctf_frac(self, field: str, term: str) -> float:
        """Collection term frequency / collection tokens (LM Dirichlet's
        P(t|C)) — from global DFS statistics when present (like idf),
        else summed over this reader's segments and cached per reader."""
        dfs = self.ctx.dfs_stats
        if dfs is not None and (field, term) in dfs.get("ctf", {}):
            total = dfs.get("total_tokens", {}).get(field, 0)
            if total:
                return dfs["ctf"][(field, term)] / total
        cache = getattr(self.ctx.reader, "_ctf_cache", None)
        if cache is None:
            cache = self.ctx.reader.__dict__.setdefault("_ctf_cache", {})
        key = (field, term)
        if key in cache:
            return cache[key]
        ctf = 0
        total = 0
        for s in self.ctx.reader.segments:
            col = s.seg.text_fields.get(field)
            if col is None:
                continue
            total += int(col.total_tokens)
            t2 = col.tid(term)
            if t2 >= 0:
                ctf += col.ctf(t2)
        frac = ctf / total if total else 0.0
        cache[key] = frac
        return frac

    def _zeros(self) -> Emit:
        self.sig("zeros")
        return lambda em: (jnp.zeros(em.n, jnp.float32),
                           jnp.zeros(em.n, bool))

    def _all(self, boost: float) -> Emit:
        r_boost = self.c(boost, np.float32)
        return lambda em: (jnp.full(em.n, 1.0, jnp.float32) * em.get(r_boost),
                           jnp.ones(em.n, bool))

    def _numeric_value(self, field: str, value):
        fm = self.ctx.mapper_service.field_mapper(field)
        if fm is not None and fm.type == "date" and not isinstance(
                value, (int, float)):
            return parse_date(value)
        if fm is not None and fm.type == "ip" and isinstance(value, str):
            from elasticsearch_tpu.mapping.mapper import ip_to_long
            return float(ip_to_long(value))
        if isinstance(value, bool):
            return 1.0 if value else 0.0
        return float(value)

    def _term_stats(self, field: str, term: str) -> tuple[int, int]:
        """→ (df, doc_count), from global DFS statistics when present
        (aggregateDfs, core/search/controller/SearchPhaseController.java:105)
        else from the shard-local reader. A term the DFS round did not
        cover falls back to local stats (graceful, like a stale
        AggregatedDfs entry)."""
        dfs = self.ctx.dfs_stats
        if dfs is not None and (field, term) in dfs["df"]:
            doc_count = dfs["doc_count"].get(field)
            if doc_count is None:
                doc_count = max(self.ctx.reader.text_stats(field).doc_count,
                                1)
            return int(dfs["df"][(field, term)]), max(int(doc_count), 1)
        st = self.ctx.reader.text_stats(field)
        return self.ctx.reader.df(field, term), max(st.doc_count, 1)

    def _avgdl(self, field: str) -> float:
        dfs = self.ctx.dfs_stats
        if dfs is not None and field in dfs.get("avgdl", {}):
            return max(float(dfs["avgdl"][field]), 1e-9)
        return max(self.ctx.reader.text_stats(field).avgdl, 1e-9)

    # ------------------------------------------------------------- dispatch

    def resolve(self, query: q.Query) -> Emit:
        """→ emit closure producing (scores [N] f32, mask [N] bool);
        live-mask applied by the caller."""
        # cooperative cancellation checkpoint: plan resolution walks the
        # whole AST host-side, so a cancelled task aborts here before the
        # next device dispatch is even built (TaskManager wiring)
        from elasticsearch_tpu.tasks import raise_if_cancelled
        raise_if_cancelled()
        method = getattr(self, f"_res_{type(query).__name__}", None)
        if method is None:
            raise QueryParsingError(
                f"no executor for query type [{type(query).__name__}]")
        self.sig(type(query).__name__, getattr(query, "field", None))
        return method(query)

    def resolve_mask(self, query: q.Query) -> Callable[[EmitCtx], Any]:
        emit = self.resolve(query)
        return lambda em: emit(em)[1]

    # ----------------------------------------------------------------- leafs

    def _res_MatchAllQuery(self, query: q.MatchAllQuery) -> Emit:
        return self._all(query.boost)

    def _res_MatchNoneQuery(self, query: q.MatchNoneQuery) -> Emit:
        return self._zeros()

    def _match_terms(self, field: str, terms: list[str]):
        """Resolve analyzed terms to per-segment ids + idf (reader or DFS
        stats)."""
        col = self.seg.text.get(field)
        if col is None:
            return None
        tids, idfs = [], []
        for t in terms:
            tid = col.column.tid(t)
            df, doc_count = self._term_stats(field, t)
            tids.append(tid)
            idfs.append(bm25_idf(df, doc_count) if df > 0 else 0.0)
        return tids, idfs

    def _res_MatchQuery(self, query: q.MatchQuery) -> Emit:
        field = query.field
        if field in ("*", "_all"):
            # all-fields match (ES _all / query_string default): OR over every
            # text field present in the segment — iteration order is part of
            # the plan signature
            self.sig("all-fields", tuple(self.seg.text))
            subs = [self.resolve(q.MatchQuery(
                field=f, text=query.text, operator=query.operator,
                boost=query.boost)) for f in self.seg.text]
            if not subs:
                return self._zeros()

            def emit_all(em):
                scores = mask = None
                for sub in subs:
                    s, m = sub(em)
                    scores = s if scores is None else jnp.maximum(scores, s)
                    mask = m if mask is None else (mask | m)
                return scores, mask
            return emit_all
        if self.seg.text.get(field) is None and (
                field in self.seg.keyword or field in self.seg.numeric):
            # match on keyword/numeric doc values == exact term (ES behavior)
            return self.resolve(q.TermQuery(
                field=field, value=query.text, boost=query.boost))
        analyzer = self._analyzer_for(field, query.analyzer)
        terms = [t.term for t in analyzer.analyze(query.text)]
        if not terms:
            return self._zeros()
        resolved = self._match_terms(field, terms)
        if resolved is None:
            return self._zeros()
        tids, idfs = resolved
        if query.operator == "and":
            required = len(terms)
        elif query.minimum_should_match is not None:
            required = _resolve_msm(query.minimum_should_match, len(terms))
        else:
            required = 1
        n_terms = len(tids)
        similarity = self._similarity_for(field)
        if similarity != "bm25":
            # reuse the (df, doc_count) per term already gathered by
            # _match_terms — no second stats pass on the planning path
            stats = [self._term_stats(field, t) for t in terms]
            return self._match_alt_similarity(query, field, terms, tids,
                                              similarity, required, stats)
        r_tids = self.c(tids, np.int32)
        r_idfs = self.c(idfs, np.float32)
        r_avgdl = self.c(self._avgdl(field), np.float32)
        # required == 1 (the default OR semantics): a doc matches iff any
        # query term hits, and every present term has idf > 0, so
        # mask ≡ scores > 0 — the nmatch accumulation becomes dead code XLA
        # eliminates (T fewer [N, U] compare/reduce passes, the same
        # shortcut the standalone kernel gets for free)
        # guard for DFS-provided stats: a term present in this segment but
        # with global df 0 would have idf 0 — its matches score 0 and the
        # scores>0 shortcut would drop them, diverging from nmatch
        # semantics; fall back to nmatch counting in that (odd) case.
        # The test is the term's LOCAL df: local df 0 means no posting can
        # match here, so idf 0 is harmless — keeping msm1 makes the plan
        # signature independent of which query terms this shard happens to
        # hold (shards of one index must batch together, and the compile
        # cache keys on the signature)
        col_df = np.asarray(self.seg.text[field].column.df)
        all_idf_pos = all(
            idf > 0 or tid < 0 or col_df[tid] == 0
            for tid, idf in zip(tids, idfs))
        msm1 = required == 1 and all_idf_pos
        self.sig("msm1" if msm1 else "msm")
        r_req = None if msm1 else self.c(required, np.int32)
        r_boost = self.c(query.boost, np.float32)
        p = self.ctx.bm25

        def emit(em):
            col = em.seg.text[field]
            scores, nmatch = lexical.bm25_match(
                col.uterms, col.utf, col.doc_len,
                jnp.asarray(em.get(r_tids)), jnp.asarray(em.get(r_idfs)),
                jnp.ones(n_terms, jnp.float32), p.k1, p.b, em.get(r_avgdl))
            if msm1:
                # OR semantics: the bm25 sum is already 0 on non-matching
                # docs, so the mask is just scores > 0 and no re-zeroing
                # where-pass is needed (boost scales 0 to 0)
                mask = scores > 0
                return scores * em.get(r_boost), mask
            mask = nmatch >= em.get(r_req)
            return jnp.where(mask, scores * em.get(r_boost), 0.0), mask
        return emit

    def _match_alt_similarity(self, query, field: str, terms: list[str],
                              tids: list[int], similarity: str,
                              required: int,
                              stats: list[tuple[int, int]]) -> Emit:
        """Non-BM25 similarity scoring for match queries (classic TF-IDF
        and LM Dirichlet); the plan signature carries the module name so
        differently-scored fields never share a program."""
        self.sig("match-sim", similarity)
        n_terms = len(tids)
        r_tids = self.c(tids, np.int32)
        r_req = self.c(required, np.int32)
        r_boost = self.c(query.boost, np.float32)
        if similarity == "classic":
            idfs = []
            for df, doc_count in stats:
                idfs.append(1.0 + np.log(max(doc_count, 1)
                                         / (df + 1.0)) if df > 0 else 0.0)
            r_w = self.c(idfs, np.float32)

            def emit(em):
                col = em.seg.text[field]
                scores, nmatch = lexical.classic_match(
                    col.uterms, col.utf, col.doc_len,
                    jnp.asarray(em.get(r_tids)),
                    jnp.asarray(em.get(r_w)),
                    jnp.ones(n_terms, jnp.float32))
                mask = nmatch >= em.get(r_req)
                return jnp.where(mask, scores * em.get(r_boost), 0.0), mask
            return emit
        # lm_dirichlet
        fm = self.ctx.mapper_service.field_mapper(field)
        mu = float((fm.params.get("similarity_mu", 2000.0))
                   if fm is not None else 2000.0)
        fracs = [self._ctf_frac(field, t) for t in terms]
        r_frac = self.c(fracs, np.float32)
        r_mu = self.c(mu, np.float32)

        def emit(em):
            col = em.seg.text[field]
            scores, nmatch = lexical.lm_dirichlet_match(
                col.uterms, col.utf, col.doc_len,
                jnp.asarray(em.get(r_tids)),
                jnp.asarray(em.get(r_frac)),
                jnp.ones(n_terms, jnp.float32), em.get(r_mu))
            mask = nmatch >= em.get(r_req)
            return jnp.where(mask, scores * em.get(r_boost), 0.0), mask
        return emit

    def _res_MatchPhraseQuery(self, query: q.MatchPhraseQuery) -> Emit:
        field = query.field
        analyzer = self._analyzer_for(field, query.analyzer)
        toks = analyzer.analyze(query.text)
        if not toks:
            return self._zeros()
        if len(toks) == 1:
            return self.resolve(q.MatchQuery(
                field=field, text=query.text, analyzer=query.analyzer,
                boost=query.boost))
        col = self.seg.text.get(field)
        if col is not None and not col.column.has_positions:
            raise QueryParsingError(
                f"field [{field}] was not indexed with positions — "
                f"phrase queries need index_options [positions]")
        resolved = self._match_terms(field, [t.term for t in toks])
        if resolved is None:
            return self._zeros()
        tids, idfs = resolved
        deltas = [t.position - toks[0].position for t in toks]
        slop = query.slop
        self.sig("phrase", tuple(deltas), slop)
        self.ct.positions_needed.add(field)
        p = self.ctx.bm25
        r_tids = [self.c(t, np.int32) for t in tids]
        r_idfs = self.c(idfs, np.float32)
        r_sum_idf = self.c(sum(idfs), np.float32)
        r_avgdl = self.c(self._avgdl(field), np.float32)
        r_boost = self.c(query.boost, np.float32)

        def emit(em):
            col = em.seg.text[field]
            tid_scalars = [em.get(r) for r in r_tids]
            if slop > 0:
                scores, mask = phrase_ops.sloppy_phrase_score(
                    col.tokens, col.doc_len, tid_scalars, deltas, slop,
                    jnp.asarray(em.get(r_idfs)), p.k1, p.b, em.get(r_avgdl))
            else:
                scores, mask = phrase_ops.phrase_score(
                    col.tokens, col.doc_len, tid_scalars, deltas,
                    em.get(r_sum_idf), p.k1, p.b, em.get(r_avgdl))
            return scores * em.get(r_boost), mask
        return emit

    def _res_MultiMatchQuery(self, query: q.MultiMatchQuery) -> Emit:
        self.sig("multi_match", query.type, query.tie_breaker > 0,
                 len(query.fields))
        subs = []
        for fspec in query.fields:
            fname, _, fboost = fspec.partition("^")
            boost = float(fboost) if fboost else 1.0
            if query.type == "phrase":
                sub = q.MatchPhraseQuery(field=fname, text=query.text,
                                         boost=boost)
            else:
                sub = q.MatchQuery(field=fname, text=query.text,
                                   operator=query.operator, boost=boost)
            subs.append(self.resolve(sub))
        if not subs:
            return self._zeros()
        mm_type = query.type
        tie = query.tie_breaker
        r_tie = self.c(tie, np.float32) if tie > 0 else None
        r_boost = self.c(query.boost, np.float32)

        def emit(em):
            scores = mask = None
            for sub in subs:
                s, m = sub(em)
                if scores is None:
                    scores, mask = s, m
                    continue
                mask = mask | m
                if mm_type == "most_fields":
                    scores = scores + s
                else:  # best_fields: max + tie_breaker * others
                    mx = jnp.maximum(scores, s)
                    if r_tie is not None:
                        scores = mx + em.get(r_tie) * (scores + s - mx)
                    else:
                        scores = mx
            return jnp.where(mask, scores * em.get(r_boost), 0.0), mask
        return emit

    def _keyword_or_text_term_mask(self, field: str, value):
        """→ mask emit for an exact term on keyword/numeric/text columns."""
        fm = self.ctx.mapper_service.field_mapper(field)
        kcol = self.seg.keyword.get(field)
        if kcol is not None:
            self.sig("term-kw", field)
            r_ord = self.c(kcol.column.ord(str(value)), np.int32)
            return lambda em: filter_ops.keyword_term(
                em.seg.keyword[field].ords, em.get(r_ord))
        ncol = self.seg.numeric.get(field)
        if ncol is not None or (fm is not None and fm.kind == KIND_NUMERIC):
            if ncol is None:
                self.sig("term-none", field)
                return lambda em: jnp.zeros(em.n, bool)
            self.sig("term-num", field)
            hi, lo = dd_split(self._numeric_value(field, value))
            r_hi = self.c(hi, np.float32)
            r_lo = self.c(lo, np.float32)

            def emit(em):
                col = em.seg.numeric[field]
                return filter_ops.numeric_term(col.hi, col.lo, col.exists,
                                               em.get(r_hi), em.get(r_lo))
            return emit
        tcol = self.seg.text.get(field)
        if tcol is not None:
            self.sig("term-text", field)
            r_tid = self.c(tcol.column.tid(str(value)), np.int32)
            return lambda em: lexical.term_filter(
                em.seg.text[field].uterms, em.get(r_tid))
        self.sig("term-none", field)
        return lambda em: jnp.zeros(em.n, bool)

    def _res_TermQuery(self, query: q.TermQuery) -> Emit:
        # term on text fields scores BM25 like a single-term match (Lucene
        # TermQuery); on keyword/numeric doc values it is constant-score.
        fm = self.ctx.mapper_service.field_mapper(query.field)
        if fm is not None and fm.type == "ip" and \
                isinstance(query.value, str) and "/" in query.value:
            # CIDR term → numeric interval (IpFieldMapper termQuery)
            from elasticsearch_tpu.mapping.mapper import cidr_range
            lo, hi = cidr_range(query.value)
            return self.resolve(q.RangeQuery(field=query.field, gte=lo,
                                             lte=hi, boost=query.boost))
        tcol = self.seg.text.get(query.field)
        if tcol is not None and self.seg.keyword.get(query.field) is None:
            return self.resolve(q.MatchQuery(
                field=query.field, text=str(query.value), analyzer="keyword",
                boost=query.boost))
        mask_emit = self._keyword_or_text_term_mask(query.field, query.value)
        r_boost = self.c(query.boost, np.float32)
        return lambda em: bool_ops.constant_score(mask_emit(em),
                                                  em.get(r_boost))

    def _res_TermsQuery(self, query: q.TermsQuery) -> Emit:
        field = query.field
        kcol = self.seg.keyword.get(field)
        r_boost = self.c(query.boost, np.float32)
        if kcol is not None:
            self.sig("terms-kw", field)
            qords = [kcol.column.ord(str(v)) for v in query.values]
            r_ords = self.c(qords or [-1], np.int32)

            def emit(em):
                mask = filter_ops.keyword_terms(
                    em.seg.keyword[field].ords, jnp.asarray(em.get(r_ords)))
                return bool_ops.constant_score(mask, em.get(r_boost))
            return emit
        self.sig("terms-any", field, len(query.values))
        mask_emits = [self._keyword_or_text_term_mask(field, v)
                      for v in query.values]

        def emit(em):
            mask = jnp.zeros(em.n, bool)
            for me in mask_emits:
                mask = mask | me(em)
            return bool_ops.constant_score(mask, em.get(r_boost))
        return emit

    def _res_RangeQuery(self, query: q.RangeQuery) -> Emit:
        field = query.field
        r_boost = self.c(query.boost, np.float32)
        ncol = self.seg.numeric.get(field)
        if ncol is not None:
            # gte/gt (and lte/lt) apply independently; effective bound is
            # the tightest (ES RangeQueryParser applies each given bound).
            # Exclusivity is a comparison-strictness flag, not a
            # nextafter-bumped value — the f64 neighbor of a small bound
            # underflows the f32 dd split (gt:0 would become gte:0).
            lo_v, lo_strict = -np.inf, False
            if query.gte is not None:
                lo_v = np.float64(self._numeric_value(field, query.gte))
            if query.gt is not None:
                g = np.float64(self._numeric_value(field, query.gt))
                if g >= lo_v:
                    lo_v, lo_strict = g, True
            hi_v, hi_strict = np.inf, False
            if query.lte is not None:
                hi_v = np.float64(self._numeric_value(field, query.lte))
            if query.lt is not None:
                l_ = np.float64(self._numeric_value(field, query.lt))
                if l_ <= hi_v:
                    hi_v, hi_strict = l_, True
            self.sig("range-num", field)
            ghi, glo = dd_split(lo_v)
            lhi, llo = dd_split(hi_v)
            r_ghi = self.c(ghi, np.float32)
            r_glo = self.c(glo, np.float32)
            r_lhi = self.c(lhi, np.float32)
            r_llo = self.c(llo, np.float32)
            r_gx = self.c(np.float32(1.0 if lo_strict else 0.0))
            r_lx = self.c(np.float32(1.0 if hi_strict else 0.0))

            def emit(em):
                col = em.seg.numeric[field]
                mask = filter_ops.numeric_range(
                    col.hi, col.lo, col.exists,
                    em.get(r_ghi), em.get(r_glo),
                    em.get(r_lhi), em.get(r_llo),
                    lo_strict=em.get(r_gx), hi_strict=em.get(r_lx))
                return bool_ops.constant_score(mask, em.get(r_boost))
            return emit
        kcol = self.seg.keyword.get(field)
        if kcol is not None:
            self.sig("range-kw", field)
            vocab = kcol.column.vocab
            lo_ord = 0
            hi_ord = len(vocab)
            # tightest-bound combination, same discipline as the numeric
            # branch (each given bound applies; ordinal intervals make
            # gt/lt exact without strictness flags)
            if query.gte is not None:
                lo_ord = max(lo_ord, _bisect_left(vocab, str(query.gte)))
            if query.gt is not None:
                lo_ord = max(lo_ord, _bisect_right(vocab, str(query.gt)))
            if query.lte is not None:
                hi_ord = min(hi_ord, _bisect_right(vocab, str(query.lte)))
            if query.lt is not None:
                hi_ord = min(hi_ord, _bisect_left(vocab, str(query.lt)))
            r_lo = self.c(lo_ord, np.int32)
            r_hi = self.c(hi_ord, np.int32)

            def emit(em):
                mask = filter_ops.keyword_ord_range(
                    em.seg.keyword[field].ords, em.get(r_lo), em.get(r_hi))
                return bool_ops.constant_score(mask, em.get(r_boost))
            return emit
        return self._zeros()

    def _res_ExistsQuery(self, query: q.ExistsQuery) -> Emit:
        f = query.field
        r_boost = self.c(query.boost, np.float32)
        if f in self.seg.numeric:
            self.sig("exists", "num", f)
            mask_emit = lambda em: em.seg.numeric[f].exists   # noqa: E731
        elif f in self.seg.keyword:
            self.sig("exists", "kw", f)
            mask_emit = lambda em: (                          # noqa: E731
                em.seg.keyword[f].ords >= 0).any(axis=1)
        elif f in self.seg.text:
            self.sig("exists", "text", f)
            mask_emit = lambda em: em.seg.text[f].doc_len > 0  # noqa: E731
        elif f in self.seg.vector:
            self.sig("exists", "vec", f)   # reads only the [N] exists mask
            mask_emit = lambda em: em.seg.vector[f].exists    # noqa: E731
        elif f in self.seg.geo:
            self.sig("exists", "geo", f)
            mask_emit = lambda em: em.seg.geo[f].exists       # noqa: E731
        else:
            self.sig("exists", "none", f)
            mask_emit = lambda em: jnp.zeros(em.n, bool)      # noqa: E731
        return lambda em: bool_ops.constant_score(mask_emit(em),
                                                  em.get(r_boost))

    # --- vocab-scan leaf family (prefix/wildcard/regexp/fuzzy) -------------

    def _vocab_scan_mask(self, field: str, pred):
        """Expand a term predicate against per-segment vocabularies —
        Lucene's MultiTermQuery rewrite (TermsEnum scan) stays host-side.
        Matching term-id lists are padded to power-of-2 buckets so queries
        with different expansion counts share compiled programs."""
        kcol = self.seg.keyword.get(field)
        if kcol is not None:
            self.sig("scan-kw", field)
            qords = [i for i, v in enumerate(kcol.column.vocab) if pred(v)]
            if not qords:
                self.sig("scan-empty")
                return lambda em: jnp.zeros(em.n, bool)
            r_ords = self.c(_pad_pow2(qords, -1), np.int32)
            return lambda em: filter_ops.keyword_terms(
                em.seg.keyword[field].ords, jnp.asarray(em.get(r_ords)))
        tcol = self.seg.text.get(field)
        if tcol is not None:
            self.sig("scan-text", field)
            tids = [i for i, t in enumerate(tcol.column.terms) if pred(t)]
            if not tids:
                self.sig("scan-empty")
                return lambda em: jnp.zeros(em.n, bool)
            r_tids = self.c(_pad_pow2(tids, -1), np.int32)

            def emit(em):
                qt = jnp.asarray(em.get(r_tids))
                uterms = em.seg.text[field].uterms
                hit = (uterms[:, :, None] == qt[None, None, :]) & \
                    (qt[None, None, :] >= 0)
                return hit.any(axis=(1, 2))
            return emit
        self.sig("scan-none", field)
        return lambda em: jnp.zeros(em.n, bool)

    def _constant_mask_emit(self, mask_emit, boost: float) -> Emit:
        r_boost = self.c(boost, np.float32)
        return lambda em: bool_ops.constant_score(mask_emit(em),
                                                  em.get(r_boost))

    def _res_PrefixQuery(self, query: q.PrefixQuery) -> Emit:
        kcol = self.seg.keyword.get(query.field)
        if kcol is not None:   # sorted vocab → ordinal interval, no scan
            self.sig("prefix-kw", query.field)
            field = query.field
            vocab = kcol.column.vocab
            r_lo = self.c(_bisect_left(vocab, query.value), np.int32)
            r_hi = self.c(_bisect_left(vocab, query.value + "￿"),
                          np.int32)
            return self._constant_mask_emit(
                lambda em: filter_ops.keyword_ord_range(
                    em.seg.keyword[field].ords, em.get(r_lo), em.get(r_hi)),
                query.boost)
        return self._constant_mask_emit(
            self._vocab_scan_mask(query.field, multi_term_pred(query)),
            query.boost)

    def _res_WildcardQuery(self, query: q.WildcardQuery) -> Emit:
        return self._constant_mask_emit(
            self._vocab_scan_mask(query.field, multi_term_pred(query)),
            query.boost)

    def _res_RegexpQuery(self, query: q.RegexpQuery) -> Emit:
        return self._constant_mask_emit(
            self._vocab_scan_mask(query.field, multi_term_pred(query)),
            query.boost)

    def _res_FuzzyQuery(self, query: q.FuzzyQuery) -> Emit:
        return self._constant_mask_emit(
            self._vocab_scan_mask(query.field, multi_term_pred(query)),
            query.boost)

    def _res_ParentIdsQuery(self, query: q.ParentIdsQuery) -> Emit:
        """Join-result lookup: doc matches when its `field` value (_id or
        the _parent keyword column) keys `id_scores`; score = mapped value
        (host-computed by ShardSearcher._rewrite_joins)."""
        vals = np.zeros(self.n, np.float32)
        hits = np.zeros(self.n, bool)
        seg = self.seg.seg
        if query.field == "_id":
            for local, did in enumerate(seg.ids):
                s = query.id_scores.get(did)
                if s is not None:
                    vals[local] = s
                    hits[local] = True
        else:
            col = seg.keyword_fields.get(query.field)
            if col is not None:
                per_ord = np.array(
                    [query.id_scores.get(v, np.nan) for v in col.vocab],
                    np.float64)
                first = np.asarray(col.ords[:seg.num_docs, 0])
                ok = first >= 0
                looked = np.where(ok, per_ord[np.maximum(first, 0)],
                                  np.nan)
                hit = ~np.isnan(looked)
                hits[:seg.num_docs] = hit
                vals[:seg.num_docs] = np.where(hit, looked, 0.0)
        r_vals = self.c(vals)
        r_hits = self.c(hits)
        r_boost = self.c(query.boost, np.float32)
        return lambda em: (jnp.asarray(em.get(r_vals))
                           * em.get(r_boost),
                           jnp.asarray(em.get(r_hits)))

    def _res_IdsQuery(self, query: q.IdsQuery) -> Emit:
        wanted = set(query.values)
        hits = np.zeros(self.n, bool)
        for local, did in enumerate(self.seg.seg.ids):
            if did in wanted:
                hits[local] = True
        r_hits = self.c(hits)
        r_boost = self.c(query.boost, np.float32)
        return lambda em: bool_ops.constant_score(
            jnp.asarray(em.get(r_hits)), em.get(r_boost))

    # ------------------------------------------------------------- compound

    def _res_BoolQuery(self, query: q.BoolQuery) -> Emit:
        self.sig("bool", len(query.must), len(query.should),
                 len(query.must_not), len(query.filter))
        must = [self.resolve(sub) for sub in query.must]
        should = [self.resolve(sub) for sub in query.should]
        must_not = [self.resolve_mask(sub) for sub in query.must_not]
        filters = [self.resolve_mask(sub) for sub in query.filter]
        if query.minimum_should_match is not None:
            msm = _resolve_msm(query.minimum_should_match, len(query.should))
        else:
            msm = 1 if (query.should and not query.must and not query.filter) \
                else 0
        r_msm = self.c(msm, np.int32) if should else None
        r_boost = self.c(query.boost, np.float32)

        def emit(em):
            scores, mask = bool_ops.combine_bool(
                em.n,
                [e(em) for e in must], [e(em) for e in should],
                [e(em) for e in must_not], [e(em) for e in filters],
                em.get(r_msm) if r_msm is not None else 0)
            return scores * em.get(r_boost), mask
        return emit

    def _res_ConstantScoreQuery(self, query: q.ConstantScoreQuery) -> Emit:
        mask_emit = self.resolve_mask(query.filter_query)
        return self._constant_mask_emit(mask_emit, query.boost)

    def _res_DisMaxQuery(self, query: q.DisMaxQuery) -> Emit:
        self.sig("dis_max", len(query.queries), query.tie_breaker > 0)
        subs = [self.resolve(sub) for sub in query.queries]
        if not subs:
            return self._zeros()
        r_tie = self.c(query.tie_breaker, np.float32) \
            if query.tie_breaker > 0 else None
        r_boost = self.c(query.boost, np.float32)

        def emit(em):
            best = total = mask = None
            for sub in subs:
                s, m = sub(em)
                s = jnp.where(m, s, 0.0)
                if best is None:
                    best, total, mask = s, s, m
                    continue
                best = jnp.maximum(best, s)
                total = total + s
                mask = mask | m
            scores = best if r_tie is None else \
                best + em.get(r_tie) * (total - best)
            return jnp.where(mask, scores * em.get(r_boost), 0.0), mask
        return emit

    def _res_BoostingQuery(self, query: q.BoostingQuery) -> Emit:
        pos = self.resolve(query.positive or q.MatchAllQuery())
        neg = self.resolve_mask(query.negative or q.MatchNoneQuery())
        r_neg = self.c(query.negative_boost, np.float32)
        r_boost = self.c(query.boost, np.float32)

        def emit(em):
            scores, mask = pos(em)
            demote = jnp.where(neg(em), em.get(r_neg),
                               jnp.float32(1.0))
            return scores * demote * em.get(r_boost), mask
        return emit

    def _res_CommonTermsQuery(self, query: q.CommonTermsQuery) -> Emit:
        field = query.field
        analyzer = self._analyzer_for(field, query.analyzer)
        terms = [t.term for t in analyzer.analyze(query.text)]
        if not terms or self.seg.text.get(field) is None:
            return self._zeros()
        # split by document frequency (ExtendedCommonTermsQuery: ≥1 means
        # an absolute df cutoff, <1 a fraction of docCount)
        low, high = [], []
        for t in terms:
            df, doc_count = self._term_stats(field, t)
            cutoff = query.cutoff_frequency if query.cutoff_frequency >= 1 \
                else query.cutoff_frequency * doc_count
            idf = bm25_idf(df, doc_count) if df > 0 else 0.0
            tid = self.seg.text[field].column.tid(t)
            (high if df > cutoff else low).append((tid, idf))
        self.sig("common", len(low), len(high))
        msm_low = len(low) if query.low_freq_operator == "and" else \
            _resolve_msm(query.minimum_should_match_low, len(low)) \
            if query.minimum_should_match_low is not None else 1
        msm_high = len(high) if query.high_freq_operator == "and" else \
            _resolve_msm(query.minimum_should_match_high, len(high)) \
            if query.minimum_should_match_high is not None else 1
        r_avgdl = self.c(self._avgdl(field), np.float32)
        r_boost = self.c(query.boost, np.float32)
        p = self.ctx.bm25

        def group(pairs):
            if not pairs:
                return None
            return (self.c([t for t, _ in pairs], np.int32),
                    self.c([i for _, i in pairs], np.float32), len(pairs))
        g_low, g_high = group(low), group(high)
        r_msm_low = self.c(msm_low, np.int32) if g_low else None
        r_msm_high = self.c(msm_high, np.int32) if g_high else None

        def emit(em):
            col = em.seg.text[field]

            def score_group(g):
                r_tids, r_idfs, n = g
                return lexical.bm25_match(
                    col.uterms, col.utf, col.doc_len,
                    jnp.asarray(em.get(r_tids)), jnp.asarray(em.get(r_idfs)),
                    jnp.ones(n, jnp.float32), p.k1, p.b, em.get(r_avgdl))
            if g_low is not None:
                low_s, low_n = score_group(g_low)
                mask = low_n >= em.get(r_msm_low)
                scores = low_s
                if g_high is not None:
                    high_s, _ = score_group(g_high)
                    scores = scores + high_s
            else:
                high_s, high_n = score_group(g_high)
                mask = high_n >= em.get(r_msm_high)
                scores = high_s
            return jnp.where(mask, scores * em.get(r_boost), 0.0), mask
        return emit

    def _res_NestedQuery(self, query: q.NestedQuery) -> Emit:
        """Nested query: resolve the inner query against the path's CHILD
        segment; the emit scatter-reduces child matches onto parent rows
        ((.at[].max/add — a segment-reduce, XLA-native). Children of
        deleted parents are already dead in the child live mask
        (device_reader packing)."""
        path = query.path
        block = self.seg.nested.get(path)
        if block is None:
            return self._zeros()
        score_mode = query.score_mode
        self.sig("nested", path, score_mode)
        inner = SegmentResolver(block.child, self.ctx, self.ct).resolve(
            query.query or q.MatchAllQuery())
        r_boost = self.c(query.boost, np.float32)

        def emit(em):
            blk = em.seg.nested[path]
            child_em = EmitCtx(blk.child, em.consts)
            c_scores, c_mask = inner(child_em)
            ok = c_mask & blk.child.live & (blk.parent >= 0)
            idx = jnp.where(blk.parent >= 0, blk.parent, 0)
            matched = jnp.zeros(em.n, bool).at[idx].max(ok, mode="drop")
            if score_mode == "none":
                scores = matched.astype(jnp.float32)
            elif score_mode in ("max", "min"):
                fill = -jnp.inf if score_mode == "max" else jnp.inf
                red = jnp.full(em.n, fill, jnp.float32)
                contrib = jnp.where(ok, c_scores, fill)
                red = red.at[idx].max(contrib, mode="drop") \
                    if score_mode == "max" \
                    else red.at[idx].min(contrib, mode="drop")
                scores = jnp.where(matched, red, 0.0)
            else:
                ssum = jnp.zeros(em.n, jnp.float32).at[idx].add(
                    jnp.where(ok, c_scores, 0.0), mode="drop")
                if score_mode == "avg":
                    cnt = jnp.zeros(em.n, jnp.float32).at[idx].add(
                        ok.astype(jnp.float32), mode="drop")
                    scores = ssum / jnp.maximum(cnt, 1.0)
                else:                    # sum
                    scores = ssum
            return jnp.where(matched, scores * em.get(r_boost), 0.0), \
                matched
        return emit

    def _res_SpanTermQuery(self, query: q.SpanTermQuery) -> Emit:
        # a lone span_term scores like a single-term match (SpanWeight's
        # sloppyFreq over unit-width spans == term frequency)
        return self.resolve(q.MatchQuery(field=query.field,
                                         text=query.value,
                                         analyzer="keyword",
                                         boost=query.boost))

    def _res_SpanNearQuery(self, query: q.SpanNearQuery) -> Emit:
        if not all(type(c).__name__ == "SpanTermQuery"
                   for c in query.clauses):
            # composite clauses (or/not/multi/masking/nested near) run
            # through the span-algebra min-end framework (ordered only)
            return self._span_score_emit(query, query.boost)
        field = query.clauses[0].field
        col = self.seg.text.get(field)
        if col is None:
            return self._zeros()
        if not col.column.has_positions:
            raise QueryParsingError(
                f"field [{field}] was not indexed with positions — "
                f"span queries need index_options [positions]")
        self.ct.positions_needed.add(field)
        terms = [c.value for c in query.clauses]
        resolved = self._match_terms(field, terms)
        if resolved is None:
            return self._zeros()
        tids, idfs = resolved
        slop = query.slop
        self.sig("span_near", len(tids), slop, query.in_order, field)
        r_tids = [self.c(t, np.int32) for t in tids]
        r_sum_idf = self.c(sum(idfs), np.float32)
        r_avgdl = self.c(self._avgdl(field), np.float32)
        r_boost = self.c(query.boost, np.float32)
        in_order = query.in_order
        n_clauses = len(tids)
        p = self.ctx.bm25

        def emit(em):
            tcol = em.seg.text[field]
            tid_scalars = [em.get(r) for r in r_tids]
            if in_order:
                # ordered spans ≡ sloppy phrase with consecutive expected
                # positions; freq counts anchored matches (the 1/(1+d)
                # sloppyFreq weight is a documented simplification away)
                freq = phrase_ops.sloppy_phrase_count(
                    tcol.tokens, tid_scalars, list(range(n_clauses)), slop)
            else:
                freq = phrase_ops.span_near_freq_unordered(
                    tcol.tokens, tid_scalars, slop)
            scores, mask = phrase_ops.freq_score(
                freq, tcol.doc_len, em.get(r_sum_idf), p.k1, p.b,
                em.get(r_avgdl))
            return scores * em.get(r_boost), mask
        return emit

    # ---- span algebra (ops/spans.py min-end maps) -----------------------

    def _span_ends(self, query):
        """Resolve a span query to its min-end map.

        → (emit_ends(em) → [N, L] i32, sum_idf, field) or None when a
        required field/term is absent from the segment (no spans). The
        reported ``field`` supplies doc_len/avgdl for scoring (the masked
        field for field_masking_span, per FieldMaskingSpanQuery docs).
        """
        from elasticsearch_tpu.ops import spans as span_ops
        t = type(query).__name__
        self.sig("span", t)

        def leaf(field, tids, idfs, multi: bool):
            col = self.seg.text.get(field)
            if col is None or not tids:
                return None
            if not col.column.has_positions:
                raise QueryParsingError(
                    f"field [{field}] was not indexed with positions — "
                    f"span queries need index_options [positions]")
            self.ct.positions_needed.add(field)
            # span_multi expansions weight like ONE term (mean idf of the
            # rewritten set); explicit clauses sum like SpanWeight stats
            sum_idf = (sum(idfs) / len(idfs)) if multi else sum(idfs)
            if len(tids) == 1:
                r_tid = self.c(tids[0], np.int32)
                self.sig("span-term", field)
                return (lambda em: span_ops.term_ends(
                    em.seg.text[field].tokens, em.get(r_tid)),
                    sum_idf, field)
            r_tids = self.c(_pad_pow2(tids, -1), np.int32)
            self.sig("span-terms", field, len(_pad_pow2(tids, -1)))
            return (lambda em: span_ops.term_set_ends(
                em.seg.text[field].tokens, jnp.asarray(em.get(r_tids))),
                sum_idf, field)

        if t == "SpanTermQuery":
            resolved = self._match_terms(query.field, [query.value])
            if resolved is None:
                return None
            tids, idfs = resolved
            return leaf(query.field, tids, idfs, multi=False)

        if t == "SpanMultiQuery":
            inner = query.match
            field = getattr(inner, "field", "")
            col = self.seg.text.get(field)
            if col is None:
                return None
            pred = multi_term_pred(inner)
            if pred is None:
                raise QueryParsingError(
                    f"[span_multi] does not support inner query "
                    f"[{type(inner).__name__}]")
            tids = [i for i, term in enumerate(col.column.terms)
                    if pred(term)]
            if not tids:
                return None
            idfs = []
            for tid in tids:
                df, doc_count = self._term_stats(
                    field, col.column.terms[tid])
                idfs.append(bm25_idf(max(df, 1), doc_count))
            return leaf(field, tids, idfs, multi=True)

        if t == "FieldMaskingSpanQuery":
            plan = self._span_ends(query.query)
            if plan is None:
                return None
            if self.seg.text.get(query.field) is None:
                return None
            emit_e, sum_idf, _inner_field = plan
            self.sig("span-mask", query.field)
            return emit_e, sum_idf, query.field

        if t == "SpanOrQuery":
            plans = [self._span_ends(c) for c in query.clauses]
            plans = [p for p in plans if p is not None]
            if not plans:
                return None
            sum_idf = sum(p[1] for p in plans)
            field = plans[0][2]
            emits = [p[0] for p in plans]

            def emit(em):
                # pad to the widest CHILD map (children may span several
                # underlying token matrices via field_masking_span)
                maps = [e(em) for e in emits]
                L = max(m.shape[1] for m in maps)
                return span_ops.or_ends(
                    [span_ops.pad_ends(m, L) for m in maps])
            return emit, sum_idf, field

        if t == "SpanNearQuery":
            plans = [self._span_ends(c) for c in query.clauses]
            if any(p is None for p in plans) or not plans:
                return None
            sum_idf = sum(p[1] for p in plans)
            field = plans[0][2]
            slop = int(query.slop)
            in_order = bool(query.in_order)
            self.sig("span-near-ends", len(plans), slop, in_order)
            emits = [p[0] for p in plans]
            near = span_ops.near_ordered_ends if in_order \
                else span_ops.near_unordered_ends

            def emit(em):
                maps = [e(em) for e in emits]
                L = max(m.shape[1] for m in maps)
                return near([span_ops.pad_ends(m, L) for m in maps],
                            slop)
            return emit, sum_idf, field

        if t == "SpanNotQuery":
            inc = self._span_ends(query.include)
            if inc is None:
                return None
            exc = self._span_ends(query.exclude)
            if exc is None:
                return inc
            pre, post = int(query.pre), int(query.post)
            self.sig("span-not", pre, post)
            inc_e, sum_idf, field = inc
            exc_e = exc[0]

            def emit(em):
                inc_m, exc_m = inc_e(em), exc_e(em)
                L = max(inc_m.shape[1], exc_m.shape[1])
                return span_ops.not_ends(
                    span_ops.pad_ends(inc_m, L),
                    span_ops.pad_ends(exc_m, L), pre, post)
            return emit, sum_idf, field

        if t == "SpanFirstQuery":
            plan = self._span_ends(query.match)
            if plan is None:
                return None
            end = int(query.end)
            self.sig("span-first", end)
            inner_e, sum_idf, field = plan
            return (lambda em: span_ops.first_ends(inner_e(em), end),
                    sum_idf, field)

        if t in ("SpanContainingQuery", "SpanWithinQuery"):
            big = self._span_ends(query.big)
            little = self._span_ends(query.little)
            if big is None or little is None:
                return None
            big_e, big_idf, big_f = big
            lit_e, lit_idf, lit_f = little
            containing = t == "SpanContainingQuery"

            def emit(em):
                b, li = big_e(em), lit_e(em)
                L = max(b.shape[1], li.shape[1])
                b = span_ops.pad_ends(b, L)
                li = span_ops.pad_ends(li, L)
                return span_ops.containing_ends(b, li) if containing \
                    else span_ops.within_ends(li, b)
            return ((emit, big_idf, big_f) if containing
                    else (emit, lit_idf, lit_f))

        raise QueryParsingError(f"[{t}] is not a span query")

    def _span_score_emit(self, query, boost: float) -> Emit:
        """Top-level span query → scored emit: freq = spans per doc,
        BM25 over (freq, Σ idf) like the span_near scorer."""
        from elasticsearch_tpu.ops import spans as span_ops
        plan = self._span_ends(query)
        if plan is None:
            return self._zeros()
        emit_e, sum_idf, field = plan
        r_sum_idf = self.c(sum_idf, np.float32)
        r_avgdl = self.c(self._avgdl(field), np.float32)
        r_boost = self.c(boost, np.float32)
        p = self.ctx.bm25

        def emit(em):
            freq = span_ops.span_freq(emit_e(em))
            scores, mask = phrase_ops.freq_score(
                freq, em.seg.text[field].doc_len, em.get(r_sum_idf),
                p.k1, p.b, em.get(r_avgdl))
            return scores * em.get(r_boost), mask
        return emit

    def _res_SpanOrQuery(self, query: q.SpanOrQuery) -> Emit:
        return self._span_score_emit(query, query.boost)

    def _res_SpanNotQuery(self, query: q.SpanNotQuery) -> Emit:
        return self._span_score_emit(query, query.boost)

    def _res_SpanFirstQuery(self, query: q.SpanFirstQuery) -> Emit:
        return self._span_score_emit(query, query.boost)

    def _res_SpanContainingQuery(self, query) -> Emit:
        return self._span_score_emit(query, query.boost)

    def _res_SpanWithinQuery(self, query) -> Emit:
        return self._span_score_emit(query, query.boost)

    def _res_SpanMultiQuery(self, query: q.SpanMultiQuery) -> Emit:
        return self._span_score_emit(query, query.boost)

    def _res_FieldMaskingSpanQuery(self, query) -> Emit:
        return self._span_score_emit(query, query.boost)

    def _res_MoreLikeThisQuery(self, query: q.MoreLikeThisQuery) -> Emit:
        fields = query.fields or sorted(self.seg.text)
        self.sig("mlt", tuple(fields), query.include,
                 tuple(query.unlike_texts), len(query.unlike_docs))
        # gather like text per field: raw texts apply to every field;
        # liked docs contribute their own field values
        texts_by_field: dict[str, list[str]] = {f: list(query.like_texts)
                                                for f in fields}
        like_rows: list[tuple[int, int]] = []     # (segment idx, local row)
        for spec in query.like_docs:
            did = str(spec.get("_id", ""))
            for si, seg in enumerate(self.ctx.reader.segments):
                host = seg.seg
                for local, hid in enumerate(host.ids[:host.num_docs]):
                    if hid != did:
                        continue
                    like_rows.append((si, local))
                    src = host.sources[local]
                    for f in fields:
                        v = src.get(f)
                        if isinstance(v, str):
                            texts_by_field[f].append(v)
        # `unlike` terms are struck from the candidate set
        # (MoreLikeThisQuery setUnlikeText)
        unlike_terms: dict[str, set] = {}
        unlike_texts = list(query.unlike_texts)
        for spec in query.unlike_docs:
            did = str(spec.get("_id", ""))
            for seg in self.ctx.reader.segments:
                host = seg.seg
                for local, hid in enumerate(host.ids[:host.num_docs]):
                    if hid == did:
                        src = host.sources[local]
                        unlike_texts.extend(
                            v for v in src.values()
                            if isinstance(v, str))
        # significant-term selection: tf in the like text ≥ min_term_freq,
        # df ≥ min_doc_freq, ranked by idf (MoreLikeThis.createQueue)
        candidates: list[tuple[float, str, str, float]] = []
        for f in fields:
            analyzer = self._analyzer_for(f, None)
            if unlike_texts and f not in unlike_terms:
                unlike_terms[f] = {
                    tok.term for text in unlike_texts
                    for tok in analyzer.analyze(text)}
            tf: dict[str, int] = {}
            for text in texts_by_field[f]:
                for tok in analyzer.analyze(text):
                    tf[tok.term] = tf.get(tok.term, 0) + 1
            for term, n in tf.items():
                if term in unlike_terms.get(f, ()):
                    continue
                if n < query.min_term_freq:
                    continue
                df, doc_count = self._term_stats(f, term)
                if df < query.min_doc_freq or df <= 0:
                    continue
                idf = bm25_idf(df, doc_count)
                candidates.append((idf * n, f, term, idf))
        candidates.sort(key=lambda x: (-x[0], x[1], x[2]))
        picked = candidates[:query.max_query_terms]
        if not picked:
            return self._zeros()
        # one scoring group per field PRESENT in this segment (a field's
        # terms can't match where its column doesn't exist — same zeros
        # semantics as _match_terms; minimum_should_match still counts all
        # picked terms, so docs in such segments need the remaining fields)
        by_field: dict[str, list[tuple[int, float]]] = {}
        for _, f, term, idf in picked:
            col = self.seg.text.get(f)
            if col is None:
                continue
            by_field.setdefault(f, []).append((col.column.tid(term), idf))
        if not by_field:
            return self._zeros()
        msm = _resolve_msm(query.minimum_should_match, len(picked)) \
            if query.minimum_should_match is not None else 1
        self.sig("mlt-groups",
                 tuple((f, len(v)) for f, v in sorted(by_field.items())))
        groups = []
        for f in sorted(by_field):
            pairs = by_field[f]
            groups.append((f,
                           self.c([t for t, _ in pairs], np.int32),
                           self.c([i for _, i in pairs], np.float32),
                           len(pairs)))
        r_msm = self.c(msm, np.int32)
        r_boost = self.c(query.boost, np.float32)
        exclude = None
        if (like_rows or query.exclude_ids) and not query.include:
            my_idx = next((i for i, s in
                           enumerate(self.ctx.reader.segments)
                           if s is self.seg), None)
            hits = np.zeros(self.n, bool)
            for sj, local in like_rows:
                if sj == my_idx:
                    hits[local] = True
            if query.exclude_ids:
                wanted = set(query.exclude_ids)
                host = self.seg.seg
                for local, hid in enumerate(host.ids[:host.num_docs]):
                    if hid in wanted:
                        hits[local] = True
            if hits.any():
                exclude = self.c(hits)
        self.sig("mlt-excl", exclude is not None)
        r_avgdl = {f: self.c(self._avgdl(f), np.float32)
                   for f, *_ in groups}
        p = self.ctx.bm25

        def emit(em):
            scores = jnp.zeros(em.n, jnp.float32)
            nmatch = jnp.zeros(em.n, jnp.int32)
            for f, r_tids, r_idfs, n in groups:
                col = em.seg.text[f]
                s, nm = lexical.bm25_match(
                    col.uterms, col.utf, col.doc_len,
                    jnp.asarray(em.get(r_tids)), jnp.asarray(em.get(r_idfs)),
                    jnp.ones(n, jnp.float32), p.k1, p.b,
                    em.get(r_avgdl[f]))
                scores = scores + s
                nmatch = nmatch + nm
            mask = nmatch >= em.get(r_msm)
            if exclude is not None:
                mask = mask & ~jnp.asarray(em.get(exclude))
            return jnp.where(mask, scores * em.get(r_boost), 0.0), mask
        return emit

    def _res_FunctionScoreQuery(self, query: q.FunctionScoreQuery) -> Emit:
        self.sig("function_score", query.score_mode, query.boost_mode,
                 query.max_boost is not None, query.min_score is not None,
                 tuple((fn.kind, fn.weight is not None,
                        fn.filter_query is not None)
                       for fn in query.functions))
        base_emit = self.resolve(query.query or q.MatchAllQuery())
        fn_emits = []
        for fn in query.functions:
            factor_emit = self._function_factor(fn)
            if fn.weight is not None and fn.kind != "weight":
                r_w = self.c(fn.weight, np.float32)
                factor_emit = (lambda fe, rw: lambda em, s:
                               fe(em, s) * em.get(rw))(factor_emit, r_w)
            fmask_emit = self.resolve_mask(fn.filter_query) \
                if fn.filter_query else None
            r_wsum = self.c(fn.weight if fn.weight is not None else 1.0,
                            np.float32)
            fn_emits.append((factor_emit, fmask_emit, r_wsum))
        score_mode, boost_mode = query.score_mode, query.boost_mode
        r_max_boost = None if query.max_boost is None \
            else self.c(query.max_boost, np.float32)
        r_min_score = None if query.min_score is None \
            else self.c(query.min_score, np.float32)
        r_boost = self.c(query.boost, np.float32)

        def emit(em):
            base_scores, base_mask = base_emit(em)
            factors, masks, weights = [], [], []
            for factor_emit, fmask_emit, r_wsum in fn_emits:
                factors.append(factor_emit(em, base_scores))
                masks.append(fmask_emit(em) if fmask_emit is not None
                             else jnp.ones(em.n, bool))
                weights.append(em.get(r_wsum))
            combined = fs_ops.combine_functions(factors, masks, score_mode,
                                                weights=weights)
            if combined is None:
                scores = base_scores
            else:
                mb = None if r_max_boost is None else em.get(r_max_boost)
                scores = fs_ops.apply_boost_mode(base_scores, combined,
                                                 boost_mode, mb)
            mask = base_mask
            if r_min_score is not None:
                mask = mask & (scores >= em.get(r_min_score))
            return scores * em.get(r_boost), mask
        return emit

    def _function_factor(self, fn: q.ScoreFunction):
        """→ factor emit: (em, base_scores) → [N] f32."""
        params = fn.params
        if fn.kind == "weight":
            r_w = self.c(fn.weight or 1.0, np.float32)
            return lambda em, s: fs_ops.weight_factor(em.n, em.get(r_w))
        if fn.kind == "random_score":
            seed = int(params.get("seed", 0))
            self.sig("random", seed)
            r_base = self.c(self.seg.doc_base, np.uint32)
            return lambda em, s: fs_ops.random_score(em.n, seed,
                                                     em.get(r_base))
        if fn.kind == "field_value_factor":
            fname = params["field"]
            ncol = self.seg.numeric.get(fname)
            if ncol is None:
                self.sig("fvf-missing", fname)
                r_missing = self.c(params.get("missing", 1.0), np.float32)
                return lambda em, s: (jnp.full(em.n, 1.0, jnp.float32)
                                      * em.get(r_missing))
            modifier = params.get("modifier", "none")
            missing = params.get("missing")
            self.sig("fvf", fname, modifier, missing is None)
            r_factor = self.c(float(params.get("factor", 1.0)), np.float32)
            r_missing = None if missing is None \
                else self.c(float(missing), np.float32)

            def factor_emit(em, s):
                col = em.seg.numeric[fname]
                return fs_ops.field_value_factor(
                    col.hi, col.exists, factor=em.get(r_factor),
                    modifier=modifier,
                    missing=None if r_missing is None else em.get(r_missing))
            return factor_emit
        if fn.kind in ("gauss", "exp", "linear"):
            return self._decay_factor(fn, params)
        if fn.kind == "script_score":
            script = params.get("script", params)
            if isinstance(script, dict):
                src = script.get("source", script.get("inline", ""))
                sparams = script.get("params", {})
            else:
                src, sparams = str(script), {}
            return self._script_factor(src, sparams)
        raise QueryParsingError(f"unknown score function [{fn.kind}]")

    def _decay_factor(self, fn: q.ScoreFunction, params: dict):
        fname, spec = next(iter(params.items()))
        kind = fn.kind
        origin = spec.get("origin")
        fm = self.ctx.mapper_service.field_mapper(fname)
        geo_col = self.seg.geo.get(fname)
        if geo_col is not None:
            self.sig("decay-geo", fname, kind)
            # geo decay: distance to origin in meters
            if isinstance(origin, dict):
                olat, olon = float(origin["lat"]), float(origin["lon"])
            else:
                olat, olon = (float(x) for x in str(origin).split(","))
            r_olat = self.c(olat, np.float32)
            r_olon = self.c(olon, np.float32)
            r_scale = self.c(q.parse_distance(spec["scale"]), np.float32)
            r_offset = self.c(q.parse_distance(spec.get("offset", 0)),
                              np.float32)
            r_decay = self.c(float(spec.get("decay", 0.5)), np.float32)
            r_zero = self.c(0.0, np.float32)

            def factor_emit(em, s):
                col = em.seg.geo[fname]
                olat_t, olon_t = em.get(r_olat), em.get(r_olon)
                r = 6371008.8
                p1 = jnp.radians(col.lat)
                p2 = jnp.radians(olat_t)
                dphi = jnp.radians(col.lat - olat_t)
                dlmb = jnp.radians(col.lon - olon_t)
                a = jnp.sin(dphi / 2) ** 2 + jnp.cos(p1) * jnp.cos(p2) * \
                    jnp.sin(dlmb / 2) ** 2
                dist = 2 * r * jnp.arcsin(jnp.sqrt(a))
                return fs_ops.decay(dist, col.exists, em.get(r_zero),
                                    em.get(r_scale), em.get(r_offset),
                                    em.get(r_decay), kind)
            return factor_emit
        ncol = self.seg.numeric.get(fname)
        if ncol is None:
            self.sig("decay-missing", fname)
            return lambda em, s: jnp.ones(em.n, jnp.float32)
        self.sig("decay", fname, kind)
        if fm is not None and fm.type == "date":
            origin_v = parse_date(origin) if origin is not None else 0.0
            from elasticsearch_tpu.common.settings import parse_time_value
            scale = parse_time_value(spec["scale"]) * 1000.0
            offset = parse_time_value(spec.get("offset", 0)) * 1000.0
        else:
            origin_v = float(origin if origin is not None else 0.0)
            scale = float(spec["scale"])
            offset = float(spec.get("offset", 0))
        r_origin = self.c(origin_v, np.float32)
        r_scale = self.c(scale, np.float32)
        r_offset = self.c(offset, np.float32)
        r_decay = self.c(float(spec.get("decay", 0.5)), np.float32)

        def factor_emit(em, s):
            col = em.seg.numeric[fname]
            return fs_ops.decay(col.hi, col.exists, em.get(r_origin),
                                em.get(r_scale), em.get(r_offset),
                                em.get(r_decay), kind)
        return factor_emit

    def _feed_script_params(self, params: dict) -> dict:
        """Numeric script params become dynamic constants (vector params as
        f32 arrays); anything else is structural. Returns {key: value-or-
        const-ref marker} where refs are wrapped for emit-time lookup."""
        out = {}
        for key in sorted(params):
            v = params[key]
            if isinstance(v, bool) or isinstance(v, str):
                self.sig("sparam", key, v)
                out[key] = ("static", v)
            elif isinstance(v, (int, float)):
                self.sig("sparam", key, "num")
                out[key] = ("ref", self.c(float(v), np.float32))
            elif isinstance(v, (list, tuple)):
                self.sig("sparam", key, "vec", len(v))
                out[key] = ("ref", self.c(np.asarray(v, np.float32)))
            else:
                self.sig("sparam", key, repr(v))
                out[key] = ("static", v)
        return out

    def _script_factor(self, source: str, params: dict):
        """→ (em, base_scores) → [N] f32 evaluating the sandboxed script."""
        self.sig("script", source)
        param_spec = self._feed_script_params(params)
        compiled = compile_script(source)
        vf = compiled.vector_fields()
        # ScriptContext.get_vector pulls vector columns at emit time; a
        # non-literal field argument means "could be any of them"
        self.ct.vectors_needed.update(
            self.seg.vector if vf is None else vf)

        def factor_emit(em, scores):
            sparams = {k: (em.get(v) if tag == "ref" else v)
                       for k, (tag, v) in param_spec.items()}

            def get_numeric(field):
                ncol = em.seg.numeric.get(field)
                if ncol is None:
                    return (jnp.zeros(em.n, jnp.float32),
                            jnp.zeros(em.n, bool))
                return ncol.hi, ncol.exists

            def get_vector(field):
                vcol = em.seg.vector.get(field)
                if vcol is None:
                    raise QueryParsingError(f"no vector field [{field}]")
                return vcol.vecs, vcol.exists

            ctx = ScriptContext(get_numeric, get_vector, scores, sparams)
            out = compiled.evaluate(ctx)
            return jnp.broadcast_to(jnp.asarray(out, jnp.float32), (em.n,))
        return factor_emit

    def _res_ScriptScoreQuery(self, query: q.ScriptScoreQuery) -> Emit:
        base_emit = self.resolve(query.query or q.MatchAllQuery())
        factor_emit = self._script_factor(query.script, query.params)
        r_boost = self.c(query.boost, np.float32)

        def emit(em):
            base_scores, base_mask = base_emit(em)
            scores = factor_emit(em, base_scores)
            return jnp.where(base_mask, scores * em.get(r_boost), 0.0), \
                base_mask
        return emit

    def _res_KnnQuery(self, query: q.KnnQuery) -> Emit:
        field = query.field
        if self.seg.vector.get(field) is None:
            return self._zeros()
        self.ct.vectors_needed.add(field)
        r_qv = self.c(query.query_vector, np.float32)
        r_boost = self.c(query.boost, np.float32)

        def emit(em):
            col = em.seg.vector[field]
            qv = jnp.asarray(em.get(r_qv))
            scores = vector_ops.cosine_scores(col.vecs, col.exists, qv)
            return (scores + 1.0) * em.get(r_boost) * \
                col.exists.astype(jnp.float32), col.exists
        return emit

    def _res_GeoDistanceQuery(self, query: q.GeoDistanceQuery) -> Emit:
        field = query.field
        if self.seg.geo.get(field) is None:
            return self._zeros()
        r_lat = self.c(query.lat, np.float32)
        r_lon = self.c(query.lon, np.float32)
        r_dist = self.c(query.distance_m, np.float32)
        return self._constant_mask_emit(
            lambda em: filter_ops.geo_distance(
                em.seg.geo[field].lat, em.seg.geo[field].lon,
                em.seg.geo[field].exists,
                em.get(r_lat), em.get(r_lon), em.get(r_dist)),
            query.boost)

    def _res_GeoBoundingBoxQuery(self, query: q.GeoBoundingBoxQuery) -> Emit:
        field = query.field
        if self.seg.geo.get(field) is None:
            return self._zeros()
        r_top = self.c(query.top, np.float32)
        r_left = self.c(query.left, np.float32)
        r_bottom = self.c(query.bottom, np.float32)
        r_right = self.c(query.right, np.float32)
        return self._constant_mask_emit(
            lambda em: filter_ops.geo_bounding_box(
                em.seg.geo[field].lat, em.seg.geo[field].lon,
                em.seg.geo[field].exists,
                em.get(r_top), em.get(r_left),
                em.get(r_bottom), em.get(r_right)),
            query.boost)

    def _res_GeoPolygonQuery(self, query: q.GeoPolygonQuery) -> Emit:
        field = query.field
        if self.seg.geo.get(field) is None:
            return self._zeros()
        self.sig("geo-poly", len(query.lats))
        r_lats = self.c(np.asarray(query.lats, np.float32), np.float32)
        r_lons = self.c(np.asarray(query.lons, np.float32), np.float32)
        return self._constant_mask_emit(
            lambda em: filter_ops.geo_polygon(
                em.seg.geo[field].lat, em.seg.geo[field].lon,
                em.seg.geo[field].exists,
                jnp.asarray(em.get(r_lats)), jnp.asarray(em.get(r_lons))),
            query.boost)

    def _res_GeoDistanceRangeQuery(self,
                                   query: q.GeoDistanceRangeQuery) -> Emit:
        field = query.field
        if self.seg.geo.get(field) is None:
            return self._zeros()
        # None bounds encode as -1 (the op treats negatives as unbounded)
        enc = [(-1.0 if v is None else float(v))
               for v in (query.gte_m, query.gt_m, query.lte_m, query.lt_m)]
        refs = [self.c(v, np.float32) for v in enc]
        r_lat = self.c(query.lat, np.float32)
        r_lon = self.c(query.lon, np.float32)
        return self._constant_mask_emit(
            lambda em: filter_ops.geo_distance_range(
                em.seg.geo[field].lat, em.seg.geo[field].lon,
                em.seg.geo[field].exists, em.get(r_lat), em.get(r_lon),
                *(em.get(r) for r in refs)),
            query.boost)

    def _res_GeohashCellQuery(self, query: q.GeohashCellQuery) -> Emit:
        from elasticsearch_tpu.utils.geohash import (
            geohash_decode_bbox, geohash_neighbors)
        field = query.field
        if self.seg.geo.get(field) is None:
            return self._zeros()
        cells = [query.geohash]
        if query.neighbors:
            cells += geohash_neighbors(query.geohash)
        self.sig("geohash-cell", len(cells))
        boxes = []
        for gh in cells:
            lat_lo, lat_hi, lon_lo, lon_hi = geohash_decode_bbox(gh)
            boxes.append(tuple(self.c(v, np.float32)
                               for v in (lat_hi, lon_lo, lat_lo, lon_hi)))

        def mask_emit(em):
            g = em.seg.geo[field]
            out = None
            for top, left, bottom, right in boxes:
                m = filter_ops.geo_bounding_box(
                    g.lat, g.lon, g.exists, em.get(top), em.get(left),
                    em.get(bottom), em.get(right))
                out = m if out is None else out | m
            return out
        return self._constant_mask_emit(mask_emit, query.boost)

    def _res_GeoShapeQuery(self, query: q.GeoShapeQuery) -> Emit:
        from elasticsearch_tpu.ops import geoshape as shape_ops
        from elasticsearch_tpu.utils.geoshape import parse_shape_rings
        field = query.field
        if self.seg.shape.get(field) is None:
            return self._zeros()
        qlats, qlons, qrid, qarea = parse_shape_rings(query.shape)
        relation = query.relation
        if relation not in ("intersects", "disjoint", "within", "contains"):
            raise QueryParsingError(
                f"unknown geo_shape relation [{relation}]")
        # ring structure is static (part of the traced program); only
        # the vertex coordinates ride the const table
        qrid_np = np.asarray(qrid, np.int32)
        qarea_np = np.asarray(qarea, bool)
        self.sig("geo-shape", relation, len(qlats),
                 tuple(qrid), tuple(qarea))
        r_lats = self.c(np.asarray(qlats, np.float32), np.float32)
        r_lons = self.c(np.asarray(qlons, np.float32), np.float32)
        return self._constant_mask_emit(
            lambda em: shape_ops.shape_relation(
                em.seg.shape[field].lats, em.seg.shape[field].lons,
                em.seg.shape[field].nv, em.seg.shape[field].exists,
                em.seg.shape[field].rid, em.seg.shape[field].area,
                jnp.asarray(em.get(r_lats)), jnp.asarray(em.get(r_lons)),
                qrid_np, qarea_np, relation),
            query.boost)

    def _res_IndicesQuery(self, query: q.IndicesQuery) -> Emit:
        name = self.ctx.index_name
        # per-shard branch pick (IndicesQueryParser): a standalone
        # searcher with no index name takes the match branch
        if name is None or name in query.indices:
            picked = query.query or q.MatchAllQuery()
        else:
            picked = query.no_match_query or q.MatchAllQuery()
        self.sig("indices", name in query.indices if name else True)
        return self.resolve(picked)


class SegmentExecutor:
    """Eager facade: resolve + emit immediately against the real segment.

    The per-op fallback path and the parity oracle for the compiled path —
    both run the SAME emit closures, so they cannot drift."""

    def __init__(self, seg: DeviceSegment, ctx: ExecutionContext):
        self.seg = seg
        self.ctx = ctx
        self.n = seg.padded_docs

    def execute(self, query: q.Query):
        """→ (scores [N] f32, mask [N] bool); live-mask applied by caller."""
        ct = ConstTable()
        emit = SegmentResolver(self.seg, self.ctx, ct).resolve(query)
        # materialize any LAZY columns the plan touches (tokens / vecs stay
        # host-side numpy until first use — device_reader.DeviceSegment
        # .lazy_put) so the eager path doesn't re-transfer them per query
        from elasticsearch_tpu.search import jit_exec

        def materialize(seg):
            for f in ct.positions_needed:
                col = seg.text.get(f)
                if col is not None:       # nested-child fields live in the
                    jit_exec._fetch(seg, col, "tokens")   # child segment
            for f in ct.vectors_needed:
                col = seg.vector.get(f)
                if col is not None:
                    jit_exec._fetch(seg, col, "vecs")
            for blk in seg.nested.values():
                materialize(blk.child)
        materialize(self.seg)
        return emit(EmitCtx(self.seg, [jnp.asarray(v) for v in ct.values]))

    def match_mask(self, query: q.Query):
        return self.execute(query)[1]


def _resolve_msm(msm, num_clauses: int) -> int:
    """minimum_should_match: int, negative int, or percentage string."""
    if isinstance(msm, int):
        return msm if msm >= 0 else max(num_clauses + msm, 0)
    s = str(msm).strip()
    if s.endswith("%"):
        pct = float(s[:-1])
        val = int(num_clauses * pct / 100.0) if pct >= 0 \
            else num_clauses - int(num_clauses * -pct / 100.0)
        return max(val, 0)
    return int(s)


def _pad_pow2(ids: list[int], fill: int) -> list[int]:
    """Pad an id list to the next power-of-2 length so vocab-expansion
    queries (wildcard/fuzzy/regexp) share compiled programs per bucket."""
    n = max(len(ids), 1)
    target = 1 << (n - 1).bit_length()
    return ids + [fill] * (target - len(ids))


def _bisect_left(vocab: list[str], v: str) -> int:
    import bisect
    return bisect.bisect_left(vocab, v)


def _bisect_right(vocab: list[str], v: str) -> int:
    import bisect
    return bisect.bisect_right(vocab, v)
