"""Query execution: AST → (scores, mask) per device segment.

The analog of Lucene's Query.createWeight/scorer tree as driven by
QueryPhase.execute (core/search/query/QueryPhase.java:99-314), re-designed
for XLA: the executor walks the AST **host-side** resolving per-segment
constants (term ids, idf from reader-aggregated df, keyword ordinal bounds,
double-double range bounds), then emits pure jnp ops over the segment's
columns. The whole walk happens inside one traced function per
(segment shape × query plan) — see :class:`SegmentExecutor.jitted` — so XLA
fuses scoring, boolean algebra, function_score and top-k into one program.

Term-to-ordinal resolution happens OUTSIDE the traced function (host dict
lookups), which is exactly the part of Lucene's per-segment TermsEnum.seek
that has no business running on an accelerator.
"""

from __future__ import annotations

import fnmatch
import re
from dataclasses import dataclass
from typing import Any

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.common.errors import QueryParsingError
from elasticsearch_tpu.index.device_reader import (
    DeviceReader, DeviceSegment, dd_split)
from elasticsearch_tpu.mapping.mapper import parse_date, KIND_NUMERIC
from elasticsearch_tpu.ops import (
    lexical, phrase as phrase_ops, boolean as bool_ops, filters as filter_ops,
    vector as vector_ops, functionscore as fs_ops)
from elasticsearch_tpu.ops.similarity import BM25Params, idf as bm25_idf
from elasticsearch_tpu.search import query_dsl as q
from elasticsearch_tpu.search.scripts import ScriptContext, compile_script


@dataclass
class ExecutionContext:
    reader: DeviceReader
    mapper_service: Any
    bm25: BM25Params = BM25Params()


def _edit_distance_le(a: str, b: str, k: int) -> bool:
    """Banded Levenshtein ≤ k (fuzzy query vocab scan)."""
    if abs(len(a) - len(b)) > k:
        return False
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i] + [0] * len(b)
        lo = max(1, i - k)
        hi = min(len(b), i + k)
        if lo > 1:
            cur[lo - 1] = k + 1
        for j in range(lo, hi + 1):
            cost = 0 if ca == b[j - 1] else 1
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
        for j in range(hi + 1, len(b) + 1):
            cur[j] = k + 1
        prev = cur
        if min(prev) > k:
            return False
    return prev[len(b)] <= k


class SegmentExecutor:
    """Executes query ASTs against one device segment."""

    def __init__(self, seg: DeviceSegment, ctx: ExecutionContext):
        self.seg = seg
        self.ctx = ctx
        self.n = seg.padded_docs

    # ------------------------------------------------------------------ util

    def _analyzer_for(self, field: str, override: str | None):
        ms = self.ctx.mapper_service
        if override:
            return ms.analysis.get(override)
        fm = ms.field_mapper(field)
        if fm is not None and getattr(fm, "kind", None) == "text":
            return fm.search_analyzer
        return ms.analysis.get("standard")

    def _zeros(self):
        return jnp.zeros(self.n, jnp.float32), jnp.zeros(self.n, bool)

    def _all(self, boost: float):
        return (jnp.full(self.n, np.float32(boost)), jnp.ones(self.n, bool))

    def _numeric_value(self, field: str, value):
        fm = self.ctx.mapper_service.field_mapper(field)
        if fm is not None and fm.type == "date" and not isinstance(
                value, (int, float)):
            return parse_date(value)
        if isinstance(value, bool):
            return 1.0 if value else 0.0
        return float(value)

    # ------------------------------------------------------------- dispatch

    def execute(self, query: q.Query):
        """→ (scores [N] f32, mask [N] bool); live-mask applied by caller."""
        method = getattr(self, f"_exec_{type(query).__name__}", None)
        if method is None:
            raise QueryParsingError(
                f"no executor for query type [{type(query).__name__}]")
        return method(query)

    def match_mask(self, query: q.Query):
        return self.execute(query)[1]

    # ----------------------------------------------------------------- leafs

    def _exec_MatchAllQuery(self, query: q.MatchAllQuery):
        return self._all(query.boost)

    def _exec_MatchNoneQuery(self, query: q.MatchNoneQuery):
        return self._zeros()

    def _match_terms(self, field: str, terms: list[str]):
        """Resolve analyzed terms to per-segment ids + idf (reader stats)."""
        col = self.seg.text.get(field)
        if col is None:
            return None
        st = self.ctx.reader.text_stats(field)
        tids, idfs = [], []
        for t in terms:
            tid = col.column.tid(t)
            df = self.ctx.reader.df(field, t)
            tids.append(tid)
            idfs.append(bm25_idf(df, max(st.doc_count, 1)) if df > 0 else 0.0)
        return col, st, tids, idfs

    def _exec_MatchQuery(self, query: q.MatchQuery):
        if query.field in ("*", "_all"):
            # all-fields match (ES _all / query_string default): OR over every
            # text field present in the segment
            subs = [q.MatchQuery(field=f, text=query.text,
                                 operator=query.operator, boost=query.boost)
                    for f in self.seg.text]
            if not subs:
                return self._zeros()
            scores = None
            mask = None
            for sub in subs:
                s, m = self._exec_MatchQuery(sub)
                scores = s if scores is None else jnp.maximum(scores, s)
                mask = m if mask is None else (mask | m)
            return scores, mask
        if self.seg.text.get(query.field) is None and (
                query.field in self.seg.keyword
                or query.field in self.seg.numeric):
            # match on keyword/numeric doc values == exact term (ES behavior)
            return self._exec_TermQuery(q.TermQuery(
                field=query.field, value=query.text, boost=query.boost))
        analyzer = self._analyzer_for(query.field, query.analyzer)
        terms = [t.term for t in analyzer.analyze(query.text)]
        if not terms:
            return self._zeros()
        resolved = self._match_terms(query.field, terms)
        if resolved is None:
            return self._zeros()
        col, st, tids, idfs = resolved
        p = self.ctx.bm25
        scores, nmatch = lexical.bm25_match(
            col.uterms, col.utf, col.doc_len,
            jnp.asarray(tids, jnp.int32), jnp.asarray(idfs, jnp.float32),
            jnp.ones(len(tids), jnp.float32), p.k1, p.b,
            np.float32(max(st.avgdl, 1e-9)))
        if query.operator == "and":
            required = len(terms)
        elif query.minimum_should_match is not None:
            required = _resolve_msm(query.minimum_should_match, len(terms))
        else:
            required = 1
        mask = nmatch >= required
        return jnp.where(mask, scores * np.float32(query.boost), 0.0), mask

    def _exec_MatchPhraseQuery(self, query: q.MatchPhraseQuery):
        analyzer = self._analyzer_for(query.field, query.analyzer)
        toks = analyzer.analyze(query.text)
        if not toks:
            return self._zeros()
        if len(toks) == 1:
            return self._exec_MatchQuery(q.MatchQuery(
                field=query.field, text=query.text, analyzer=query.analyzer,
                boost=query.boost))
        resolved = self._match_terms(query.field, [t.term for t in toks])
        if resolved is None:
            return self._zeros()
        col, st, tids, idfs = resolved
        deltas = [t.position - toks[0].position for t in toks]
        p = self.ctx.bm25
        if query.slop > 0:
            mask = phrase_ops.sloppy_phrase_mask(
                col.tokens, [jnp.int32(t) for t in tids], deltas, query.slop)
            # sloppy scoring approximated by OR-scored masked BM25
            scores, _ = lexical.bm25_match(
                col.uterms, col.utf, col.doc_len,
                jnp.asarray(tids, jnp.int32), jnp.asarray(idfs, jnp.float32),
                jnp.ones(len(tids), jnp.float32), p.k1, p.b,
                np.float32(max(st.avgdl, 1e-9)))
            return jnp.where(mask, scores * np.float32(query.boost), 0.0), mask
        scores, mask = phrase_ops.phrase_score(
            col.tokens, col.doc_len, [jnp.int32(t) for t in tids], deltas,
            np.float32(sum(idfs)), p.k1, p.b, np.float32(max(st.avgdl, 1e-9)))
        return scores * np.float32(query.boost), mask

    def _exec_MultiMatchQuery(self, query: q.MultiMatchQuery):
        subs = []
        for fspec in query.fields:
            fname, _, fboost = fspec.partition("^")
            boost = float(fboost) if fboost else 1.0
            if query.type == "phrase":
                sub = q.MatchPhraseQuery(field=fname, text=query.text, boost=boost)
            else:
                sub = q.MatchQuery(field=fname, text=query.text,
                                   operator=query.operator, boost=boost)
            subs.append(self.execute(sub))
        if not subs:
            return self._zeros()
        scores = None
        mask = None
        for s, m in subs:
            if scores is None:
                scores, mask = s, m
                continue
            mask = mask | m
            if query.type == "most_fields":
                scores = scores + s
            else:  # best_fields: max + tie_breaker * others
                mx = jnp.maximum(scores, s)
                if query.tie_breaker > 0:
                    scores = mx + np.float32(query.tie_breaker) * \
                        (scores + s - mx)
                else:
                    scores = mx
        return jnp.where(mask, scores * np.float32(query.boost), 0.0), mask

    def _keyword_or_text_term_mask(self, field: str, value):
        fm = self.ctx.mapper_service.field_mapper(field)
        kcol = self.seg.keyword.get(field)
        if kcol is not None:
            return filter_ops.keyword_term(
                kcol.ords, jnp.int32(kcol.column.ord(str(value))))
        ncol = self.seg.numeric.get(field)
        if ncol is not None or (fm is not None and fm.kind == KIND_NUMERIC):
            if ncol is None:
                return jnp.zeros(self.n, bool)
            hi, lo = dd_split(self._numeric_value(field, value))
            return filter_ops.numeric_term(ncol.hi, ncol.lo, ncol.exists,
                                           jnp.float32(hi), jnp.float32(lo))
        tcol = self.seg.text.get(field)
        if tcol is not None:
            return lexical.term_filter(tcol.uterms,
                                       jnp.int32(tcol.column.tid(str(value))))
        return jnp.zeros(self.n, bool)

    def _exec_TermQuery(self, query: q.TermQuery):
        # term on text fields scores BM25 like a single-term match (Lucene
        # TermQuery); on keyword/numeric doc values it is constant-score.
        tcol = self.seg.text.get(query.field)
        if tcol is not None and self.seg.keyword.get(query.field) is None:
            return self._exec_MatchQuery(q.MatchQuery(
                field=query.field, text=str(query.value), analyzer="keyword",
                boost=query.boost))
        mask = self._keyword_or_text_term_mask(query.field, query.value)
        return bool_ops.constant_score(mask, query.boost)

    def _exec_TermsQuery(self, query: q.TermsQuery):
        kcol = self.seg.keyword.get(query.field)
        if kcol is not None:
            qords = [kcol.column.ord(str(v)) for v in query.values]
            mask = filter_ops.keyword_terms(
                kcol.ords, jnp.asarray(qords or [-1], jnp.int32))
            return bool_ops.constant_score(mask, query.boost)
        mask = jnp.zeros(self.n, bool)
        for v in query.values:
            mask = mask | self._keyword_or_text_term_mask(query.field, v)
        return bool_ops.constant_score(mask, query.boost)

    def _exec_RangeQuery(self, query: q.RangeQuery):
        ncol = self.seg.numeric.get(query.field)
        if ncol is not None:
            # gte/gt (and lte/lt) apply independently; effective bound is the
            # tightest (ES RangeQueryParser applies each given bound).
            lo_v = -np.inf
            if query.gte is not None:
                lo_v = self._numeric_value(query.field, query.gte)
            if query.gt is not None:
                lo_v = max(lo_v, np.nextafter(np.float64(
                    self._numeric_value(query.field, query.gt)), np.inf))
            hi_v = np.inf
            if query.lte is not None:
                hi_v = self._numeric_value(query.field, query.lte)
            if query.lt is not None:
                hi_v = min(hi_v, np.nextafter(np.float64(
                    self._numeric_value(query.field, query.lt)), -np.inf))
            ghi, glo = dd_split(lo_v)
            lhi, llo = dd_split(hi_v)
            mask = filter_ops.numeric_range(
                ncol.hi, ncol.lo, ncol.exists,
                jnp.float32(ghi), jnp.float32(glo),
                jnp.float32(lhi), jnp.float32(llo))
            return bool_ops.constant_score(mask, query.boost)
        kcol = self.seg.keyword.get(query.field)
        if kcol is not None:
            vocab = kcol.column.vocab
            lo_ord = 0
            hi_ord = len(vocab)
            if query.gte is not None:
                lo_ord = _bisect_left(vocab, str(query.gte))
            if query.gt is not None:
                lo_ord = _bisect_right(vocab, str(query.gt))
            if query.lte is not None:
                hi_ord = _bisect_right(vocab, str(query.lte))
            if query.lt is not None:
                hi_ord = _bisect_left(vocab, str(query.lt))
            mask = filter_ops.keyword_ord_range(kcol.ords, lo_ord, hi_ord)
            return bool_ops.constant_score(mask, query.boost)
        return self._zeros()

    def _exec_ExistsQuery(self, query: q.ExistsQuery):
        f = query.field
        if f in self.seg.numeric:
            mask = self.seg.numeric[f].exists
        elif f in self.seg.keyword:
            mask = (self.seg.keyword[f].ords >= 0).any(axis=1)
        elif f in self.seg.text:
            mask = self.seg.text[f].doc_len > 0
        elif f in self.seg.vector:
            mask = self.seg.vector[f].exists
        elif f in self.seg.geo:
            mask = self.seg.geo[f].exists
        else:
            mask = jnp.zeros(self.n, bool)
        return bool_ops.constant_score(mask, query.boost)

    # --- vocab-scan leaf family (prefix/wildcard/regexp/fuzzy) -------------

    def _vocab_scan_mask(self, field: str, pred):
        """Expand a term predicate against per-segment vocabularies —
        Lucene's MultiTermQuery rewrite (TermsEnum scan) stays host-side."""
        kcol = self.seg.keyword.get(field)
        if kcol is not None:
            qords = [i for i, v in enumerate(kcol.column.vocab) if pred(v)]
            if not qords:
                return jnp.zeros(self.n, bool)
            return filter_ops.keyword_terms(kcol.ords,
                                            jnp.asarray(qords, jnp.int32))
        tcol = self.seg.text.get(field)
        if tcol is not None:
            tids = [i for i, t in enumerate(tcol.column.terms) if pred(t)]
            if not tids:
                return jnp.zeros(self.n, bool)
            hit = (tcol.uterms[:, :, None] ==
                   jnp.asarray(tids, jnp.int32)[None, None, :])
            return hit.any(axis=(1, 2))
        return jnp.zeros(self.n, bool)

    def _exec_PrefixQuery(self, query: q.PrefixQuery):
        kcol = self.seg.keyword.get(query.field)
        if kcol is not None:   # sorted vocab → ordinal interval, no scan
            vocab = kcol.column.vocab
            lo = _bisect_left(vocab, query.value)
            hi = _bisect_left(vocab, query.value + "￿")
            mask = filter_ops.keyword_ord_range(kcol.ords, lo, hi)
            return bool_ops.constant_score(mask, query.boost)
        mask = self._vocab_scan_mask(query.field,
                                     lambda t: t.startswith(query.value))
        return bool_ops.constant_score(mask, query.boost)

    def _exec_WildcardQuery(self, query: q.WildcardQuery):
        rx = re.compile(fnmatch.translate(query.pattern))
        mask = self._vocab_scan_mask(query.field, lambda t: rx.match(t) is not None)
        return bool_ops.constant_score(mask, query.boost)

    def _exec_RegexpQuery(self, query: q.RegexpQuery):
        rx = re.compile(query.pattern)
        mask = self._vocab_scan_mask(query.field,
                                     lambda t: rx.fullmatch(t) is not None)
        return bool_ops.constant_score(mask, query.boost)

    def _exec_FuzzyQuery(self, query: q.FuzzyQuery):
        v = query.value
        if query.fuzziness == "AUTO":
            k = 0 if len(v) < 3 else (1 if len(v) < 6 else 2)
        else:
            k = int(query.fuzziness)
        mask = self._vocab_scan_mask(query.field,
                                     lambda t: _edit_distance_le(t, v, k))
        return bool_ops.constant_score(mask, query.boost)

    def _exec_IdsQuery(self, query: q.IdsQuery):
        wanted = set(query.values)
        hits = np.zeros(self.n, bool)
        for local, did in enumerate(self.seg.seg.ids):
            if did in wanted:
                hits[local] = True
        return bool_ops.constant_score(jnp.asarray(hits), query.boost)

    # ------------------------------------------------------------- compound

    def _exec_BoolQuery(self, query: q.BoolQuery):
        must = [self.execute(sub) for sub in query.must]
        should = [self.execute(sub) for sub in query.should]
        must_not = [self.match_mask(sub) for sub in query.must_not]
        filters = [self.match_mask(sub) for sub in query.filter]
        if query.minimum_should_match is not None:
            msm = _resolve_msm(query.minimum_should_match, len(query.should))
        else:
            msm = 1 if (query.should and not query.must and not query.filter) \
                else 0
        scores, mask = bool_ops.combine_bool(
            self.n, must, should, must_not, filters, msm)
        return scores * np.float32(query.boost), mask

    def _exec_ConstantScoreQuery(self, query: q.ConstantScoreQuery):
        mask = self.match_mask(query.filter_query)
        return bool_ops.constant_score(mask, query.boost)

    def _exec_FunctionScoreQuery(self, query: q.FunctionScoreQuery):
        base_scores, base_mask = self.execute(query.query or q.MatchAllQuery())
        factors, masks = [], []
        for fn in query.functions:
            factor = self._function_factor(fn, base_scores)
            if fn.weight is not None:
                factor = factor * np.float32(fn.weight) if fn.kind != "weight" \
                    else fs_ops.weight_factor(self.n, fn.weight)
            fmask = self.match_mask(fn.filter_query) if fn.filter_query \
                else jnp.ones(self.n, bool)
            factors.append(factor)
            masks.append(fmask)
        combined = fs_ops.combine_functions(factors, masks, query.score_mode)
        if combined is None:
            scores = base_scores
        else:
            scores = fs_ops.apply_boost_mode(base_scores, combined,
                                             query.boost_mode, query.max_boost)
        mask = base_mask
        if query.min_score is not None:
            mask = mask & (scores >= np.float32(query.min_score))
        return scores * np.float32(query.boost), mask

    def _function_factor(self, fn: q.ScoreFunction, base_scores):
        params = fn.params
        if fn.kind == "weight":
            return fs_ops.weight_factor(self.n, fn.weight or 1.0)
        if fn.kind == "random_score":
            return fs_ops.random_score(self.n, int(params.get("seed", 0)),
                                       self.seg.doc_base)
        if fn.kind == "field_value_factor":
            fname = params["field"]
            ncol = self.seg.numeric.get(fname)
            if ncol is None:
                missing = params.get("missing", 1.0)
                return jnp.full(self.n, np.float32(missing))
            return fs_ops.field_value_factor(
                ncol.hi, ncol.exists, factor=float(params.get("factor", 1.0)),
                modifier=params.get("modifier", "none"),
                missing=params.get("missing"))
        if fn.kind in ("gauss", "exp", "linear"):
            fname, spec = next(iter(params.items()))
            ncol = self.seg.numeric.get(fname)
            origin = spec.get("origin")
            fm = self.ctx.mapper_service.field_mapper(fname)
            geo_col = self.seg.geo.get(fname)
            if geo_col is not None:
                # geo decay: distance to origin in meters
                if isinstance(origin, dict):
                    olat, olon = float(origin["lat"]), float(origin["lon"])
                else:
                    olat, olon = (float(x) for x in str(origin).split(","))
                from elasticsearch_tpu.ops.filters import geo_distance
                # reuse haversine by computing distances then linear decay
                r = 6371008.8
                p1 = jnp.radians(geo_col.lat)
                p2 = np.radians(olat)
                dphi = jnp.radians(geo_col.lat - olat)
                dlmb = jnp.radians(geo_col.lon - olon)
                a = jnp.sin(dphi / 2) ** 2 + jnp.cos(p1) * np.cos(p2) * \
                    jnp.sin(dlmb / 2) ** 2
                dist = 2 * r * jnp.arcsin(jnp.sqrt(a))
                scale = q.parse_distance(spec["scale"])
                offset = q.parse_distance(spec.get("offset", 0))
                return fs_ops.decay(dist, geo_col.exists, 0.0, scale, offset,
                                    float(spec.get("decay", 0.5)), fn.kind)
            if ncol is None:
                return jnp.ones(self.n, jnp.float32)
            if fm is not None and fm.type == "date":
                origin_v = parse_date(origin) if origin is not None else 0.0
                from elasticsearch_tpu.common.settings import parse_time_value
                scale = parse_time_value(spec["scale"]) * 1000.0
                offset = parse_time_value(spec.get("offset", 0)) * 1000.0
            else:
                origin_v = float(origin if origin is not None else 0.0)
                scale = float(spec["scale"])
                offset = float(spec.get("offset", 0))
            return fs_ops.decay(ncol.hi, ncol.exists, origin_v, scale, offset,
                                float(spec.get("decay", 0.5)), fn.kind)
        if fn.kind == "script_score":
            script = params.get("script", params)
            if isinstance(script, dict):
                src = script.get("source", script.get("inline", ""))
                sparams = script.get("params", {})
            else:
                src, sparams = str(script), {}
            return self._eval_script(src, sparams, base_scores)
        raise QueryParsingError(f"unknown score function [{fn.kind}]")

    def _eval_script(self, source: str, params: dict, scores):
        def get_numeric(field):
            ncol = self.seg.numeric.get(field)
            if ncol is None:
                return jnp.zeros(self.n, jnp.float32), jnp.zeros(self.n, bool)
            return ncol.hi, ncol.exists

        def get_vector(field):
            vcol = self.seg.vector.get(field)
            if vcol is None:
                raise QueryParsingError(f"no vector field [{field}]")
            return vcol.vecs, vcol.exists

        ctx = ScriptContext(get_numeric, get_vector, scores, params)
        out = compile_script(source).evaluate(ctx)
        return jnp.broadcast_to(jnp.asarray(out, jnp.float32), (self.n,))

    def _exec_ScriptScoreQuery(self, query: q.ScriptScoreQuery):
        base_scores, base_mask = self.execute(query.query or q.MatchAllQuery())
        scores = self._eval_script(query.script, query.params, base_scores)
        return jnp.where(base_mask, scores * np.float32(query.boost), 0.0), \
            base_mask

    def _exec_KnnQuery(self, query: q.KnnQuery):
        vcol = self.seg.vector.get(query.field)
        if vcol is None:
            return self._zeros()
        qv = jnp.asarray(query.query_vector, jnp.float32)
        scores = vector_ops.cosine_scores(vcol.vecs, vcol.exists, qv)
        return (scores + 1.0) * np.float32(query.boost) * \
            vcol.exists.astype(jnp.float32), vcol.exists

    def _exec_GeoDistanceQuery(self, query: q.GeoDistanceQuery):
        gcol = self.seg.geo.get(query.field)
        if gcol is None:
            return self._zeros()
        mask = filter_ops.geo_distance(gcol.lat, gcol.lon, gcol.exists,
                                       query.lat, query.lon, query.distance_m)
        return bool_ops.constant_score(mask, query.boost)

    def _exec_GeoBoundingBoxQuery(self, query: q.GeoBoundingBoxQuery):
        gcol = self.seg.geo.get(query.field)
        if gcol is None:
            return self._zeros()
        mask = filter_ops.geo_bounding_box(
            gcol.lat, gcol.lon, gcol.exists,
            query.top, query.left, query.bottom, query.right)
        return bool_ops.constant_score(mask, query.boost)


def _resolve_msm(msm, num_clauses: int) -> int:
    """minimum_should_match: int, negative int, or percentage string."""
    if isinstance(msm, int):
        return msm if msm >= 0 else max(num_clauses + msm, 0)
    s = str(msm).strip()
    if s.endswith("%"):
        pct = float(s[:-1])
        val = int(num_clauses * pct / 100.0) if pct >= 0 \
            else num_clauses - int(num_clauses * -pct / 100.0)
        return max(val, 0)
    return int(s)


def _bisect_left(vocab: list[str], v: str) -> int:
    import bisect
    return bisect.bisect_left(vocab, v)


def _bisect_right(vocab: list[str], v: str) -> int:
    import bisect
    return bisect.bisect_right(vocab, v)
