"""Query execution: AST → (scores, mask) per device segment.

The analog of Lucene's Query.createWeight/scorer tree as driven by
QueryPhase.execute (core/search/query/QueryPhase.java:99-314), re-designed
for XLA: the executor walks the AST **host-side** resolving per-segment
constants (term ids, idf from reader-aggregated df, keyword ordinal bounds,
double-double range bounds), then emits pure jnp ops over the segment's
columns. The whole walk happens inside one traced function per
(segment shape × query plan) — see :class:`SegmentExecutor.jitted` — so XLA
fuses scoring, boolean algebra, function_score and top-k into one program.

Term-to-ordinal resolution happens OUTSIDE the traced function (host dict
lookups), which is exactly the part of Lucene's per-segment TermsEnum.seek
that has no business running on an accelerator.
"""

from __future__ import annotations

import fnmatch
import re
from dataclasses import dataclass
from typing import Any

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.common.errors import QueryParsingError
from elasticsearch_tpu.index.device_reader import (
    DeviceReader, DeviceSegment, dd_split)
from elasticsearch_tpu.mapping.mapper import parse_date, KIND_NUMERIC
from elasticsearch_tpu.ops import (
    lexical, phrase as phrase_ops, boolean as bool_ops, filters as filter_ops,
    vector as vector_ops, functionscore as fs_ops)
from elasticsearch_tpu.ops.similarity import BM25Params, idf as bm25_idf
from elasticsearch_tpu.search import query_dsl as q
from elasticsearch_tpu.search.scripts import ScriptContext, compile_script


class ConstFeed:
    """Separates a query's *structure* from its *constants* so the executor
    walk can be traced once per (structure, segment layout) and replayed as
    one compiled XLA program with fresh constants (term ids, idf, bounds) as
    inputs — the compile-cache seam promised by this module's docstring.

    plan mode: record every dynamic constant (value + shape/dtype into the
    signature) and every static token; replay mode: hand back the traced
    arrays of the jitted function in the same (deterministic) walk order.
    """

    __slots__ = ("mode", "values", "sig", "_replay", "_pos")

    def __init__(self, mode: str = "plan", replay=None):
        self.mode = mode
        self.values: list[np.ndarray] = []
        self.sig: list = []
        self._replay = replay
        self._pos = 0

    def feed(self, v, dtype=None):
        """A dynamic constant: value may differ between queries that share
        one compiled program."""
        if self.mode == "plan":
            arr = np.asarray(v, dtype=dtype)
            self.values.append(arr)
            self.sig.append(("c", arr.shape, str(arr.dtype)))
            return jnp.asarray(arr)
        t = self._replay[self._pos]
        self._pos += 1
        return t

    def static(self, *tokens) -> None:
        """A static token: anything that changes the traced structure
        (field names, clause counts, modifiers, slop windows...)."""
        if self.mode == "plan":
            self.sig.append(tokens)

    def signature(self) -> tuple:
        return tuple(self.sig)


def _eager_const(v, dtype=None):
    return np.asarray(v, dtype=dtype)


def _noop_static(*tokens) -> None:
    return None


@dataclass
class ExecutionContext:
    reader: DeviceReader
    mapper_service: Any
    bm25: BM25Params = BM25Params()
    cf: ConstFeed | None = None


def _edit_distance_le(a: str, b: str, k: int) -> bool:
    """Banded Levenshtein ≤ k (fuzzy query vocab scan)."""
    if abs(len(a) - len(b)) > k:
        return False
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i] + [0] * len(b)
        lo = max(1, i - k)
        hi = min(len(b), i + k)
        if lo > 1:
            cur[lo - 1] = k + 1
        for j in range(lo, hi + 1):
            cost = 0 if ca == b[j - 1] else 1
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
        for j in range(hi + 1, len(b) + 1):
            cur[j] = k + 1
        prev = cur
        if min(prev) > k:
            return False
    return prev[len(b)] <= k


class SegmentExecutor:
    """Executes query ASTs against one device segment."""

    def __init__(self, seg: DeviceSegment, ctx: ExecutionContext):
        self.seg = seg
        self.ctx = ctx
        self.n = seg.padded_docs
        # dynamic-constant / static-token seams (plan-replay tracing); the
        # eager path feeds plain numpy values straight into the jnp ops
        self.c = ctx.cf.feed if ctx.cf is not None else _eager_const
        self.sig = ctx.cf.static if ctx.cf is not None else _noop_static

    # ------------------------------------------------------------------ util

    def _analyzer_for(self, field: str, override: str | None):
        ms = self.ctx.mapper_service
        if override:
            return ms.analysis.get(override)
        fm = ms.field_mapper(field)
        if fm is not None and getattr(fm, "kind", None) == "text":
            return fm.search_analyzer
        return ms.analysis.get("standard")

    def _zeros(self):
        self.sig("zeros")
        return jnp.zeros(self.n, jnp.float32), jnp.zeros(self.n, bool)

    def _all(self, boost: float):
        return (jnp.full(self.n, 1.0, jnp.float32)
                * self.c(boost, np.float32), jnp.ones(self.n, bool))

    def _numeric_value(self, field: str, value):
        fm = self.ctx.mapper_service.field_mapper(field)
        if fm is not None and fm.type == "date" and not isinstance(
                value, (int, float)):
            return parse_date(value)
        if isinstance(value, bool):
            return 1.0 if value else 0.0
        return float(value)

    # ------------------------------------------------------------- dispatch

    def execute(self, query: q.Query):
        """→ (scores [N] f32, mask [N] bool); live-mask applied by caller."""
        method = getattr(self, f"_exec_{type(query).__name__}", None)
        if method is None:
            raise QueryParsingError(
                f"no executor for query type [{type(query).__name__}]")
        self.sig(type(query).__name__, getattr(query, "field", None))
        return method(query)

    def match_mask(self, query: q.Query):
        return self.execute(query)[1]

    # ----------------------------------------------------------------- leafs

    def _exec_MatchAllQuery(self, query: q.MatchAllQuery):
        return self._all(query.boost)

    def _exec_MatchNoneQuery(self, query: q.MatchNoneQuery):
        return self._zeros()

    def _match_terms(self, field: str, terms: list[str]):
        """Resolve analyzed terms to per-segment ids + idf (reader stats)."""
        col = self.seg.text.get(field)
        if col is None:
            return None
        st = self.ctx.reader.text_stats(field)
        tids, idfs = [], []
        for t in terms:
            tid = col.column.tid(t)
            df = self.ctx.reader.df(field, t)
            tids.append(tid)
            idfs.append(bm25_idf(df, max(st.doc_count, 1)) if df > 0 else 0.0)
        return col, st, tids, idfs

    def _exec_MatchQuery(self, query: q.MatchQuery):
        if query.field in ("*", "_all"):
            # all-fields match (ES _all / query_string default): OR over every
            # text field present in the segment — iteration order is part of
            # the plan signature (const feed order follows it)
            self.sig("all-fields", tuple(self.seg.text))
            subs = [q.MatchQuery(field=f, text=query.text,
                                 operator=query.operator, boost=query.boost)
                    for f in self.seg.text]
            if not subs:
                return self._zeros()
            scores = None
            mask = None
            for sub in subs:
                s, m = self.execute(sub)
                scores = s if scores is None else jnp.maximum(scores, s)
                mask = m if mask is None else (mask | m)
            return scores, mask
        if self.seg.text.get(query.field) is None and (
                query.field in self.seg.keyword
                or query.field in self.seg.numeric):
            # match on keyword/numeric doc values == exact term (ES behavior)
            return self.execute(q.TermQuery(
                field=query.field, value=query.text, boost=query.boost))
        analyzer = self._analyzer_for(query.field, query.analyzer)
        terms = [t.term for t in analyzer.analyze(query.text)]
        if not terms:
            return self._zeros()
        resolved = self._match_terms(query.field, terms)
        if resolved is None:
            return self._zeros()
        col, st, tids, idfs = resolved
        p = self.ctx.bm25
        scores, nmatch = lexical.bm25_match(
            col.uterms, col.utf, col.doc_len,
            jnp.asarray(self.c(tids, np.int32)),
            jnp.asarray(self.c(idfs, np.float32)),
            jnp.ones(len(tids), jnp.float32), p.k1, p.b,
            self.c(max(st.avgdl, 1e-9), np.float32))
        if query.operator == "and":
            required = len(terms)
        elif query.minimum_should_match is not None:
            required = _resolve_msm(query.minimum_should_match, len(terms))
        else:
            required = 1
        mask = nmatch >= self.c(required, np.int32)
        return jnp.where(mask, scores * self.c(query.boost, np.float32),
                         0.0), mask

    def _exec_MatchPhraseQuery(self, query: q.MatchPhraseQuery):
        analyzer = self._analyzer_for(query.field, query.analyzer)
        toks = analyzer.analyze(query.text)
        if not toks:
            return self._zeros()
        if len(toks) == 1:
            return self.execute(q.MatchQuery(
                field=query.field, text=query.text, analyzer=query.analyzer,
                boost=query.boost))
        resolved = self._match_terms(query.field, [t.term for t in toks])
        if resolved is None:
            return self._zeros()
        col, st, tids, idfs = resolved
        deltas = [t.position - toks[0].position for t in toks]
        self.sig("phrase", tuple(deltas), query.slop)
        p = self.ctx.bm25
        tid_scalars = [jnp.int32(self.c(t, np.int32)) for t in tids]
        if query.slop > 0:
            scores, mask = phrase_ops.sloppy_phrase_score(
                col.tokens, col.doc_len, tid_scalars, deltas, query.slop,
                jnp.asarray(self.c(idfs, np.float32)), p.k1, p.b,
                self.c(max(st.avgdl, 1e-9), np.float32))
            return scores * self.c(query.boost, np.float32), mask
        scores, mask = phrase_ops.phrase_score(
            col.tokens, col.doc_len, tid_scalars, deltas,
            self.c(sum(idfs), np.float32), p.k1, p.b,
            self.c(max(st.avgdl, 1e-9), np.float32))
        return scores * self.c(query.boost, np.float32), mask

    def _exec_MultiMatchQuery(self, query: q.MultiMatchQuery):
        self.sig("multi_match", query.type, query.tie_breaker > 0,
                 len(query.fields))
        subs = []
        for fspec in query.fields:
            fname, _, fboost = fspec.partition("^")
            boost = float(fboost) if fboost else 1.0
            if query.type == "phrase":
                sub = q.MatchPhraseQuery(field=fname, text=query.text, boost=boost)
            else:
                sub = q.MatchQuery(field=fname, text=query.text,
                                   operator=query.operator, boost=boost)
            subs.append(self.execute(sub))
        if not subs:
            return self._zeros()
        scores = None
        mask = None
        for s, m in subs:
            if scores is None:
                scores, mask = s, m
                continue
            mask = mask | m
            if query.type == "most_fields":
                scores = scores + s
            else:  # best_fields: max + tie_breaker * others
                mx = jnp.maximum(scores, s)
                if query.tie_breaker > 0:
                    scores = mx + self.c(query.tie_breaker, np.float32) * \
                        (scores + s - mx)
                else:
                    scores = mx
        return jnp.where(mask, scores * self.c(query.boost, np.float32),
                         0.0), mask

    def _keyword_or_text_term_mask(self, field: str, value):
        fm = self.ctx.mapper_service.field_mapper(field)
        kcol = self.seg.keyword.get(field)
        if kcol is not None:
            self.sig("term-kw", field)
            return filter_ops.keyword_term(
                kcol.ords, self.c(kcol.column.ord(str(value)), np.int32))
        ncol = self.seg.numeric.get(field)
        if ncol is not None or (fm is not None and fm.kind == KIND_NUMERIC):
            if ncol is None:
                self.sig("term-none", field)
                return jnp.zeros(self.n, bool)
            self.sig("term-num", field)
            hi, lo = dd_split(self._numeric_value(field, value))
            return filter_ops.numeric_term(ncol.hi, ncol.lo, ncol.exists,
                                           self.c(hi, np.float32),
                                           self.c(lo, np.float32))
        tcol = self.seg.text.get(field)
        if tcol is not None:
            self.sig("term-text", field)
            return lexical.term_filter(
                tcol.uterms, self.c(tcol.column.tid(str(value)), np.int32))
        self.sig("term-none", field)
        return jnp.zeros(self.n, bool)

    def _exec_TermQuery(self, query: q.TermQuery):
        # term on text fields scores BM25 like a single-term match (Lucene
        # TermQuery); on keyword/numeric doc values it is constant-score.
        tcol = self.seg.text.get(query.field)
        if tcol is not None and self.seg.keyword.get(query.field) is None:
            return self.execute(q.MatchQuery(
                field=query.field, text=str(query.value), analyzer="keyword",
                boost=query.boost))
        mask = self._keyword_or_text_term_mask(query.field, query.value)
        return bool_ops.constant_score(mask, self.c(query.boost, np.float32))

    def _exec_TermsQuery(self, query: q.TermsQuery):
        kcol = self.seg.keyword.get(query.field)
        if kcol is not None:
            self.sig("terms-kw", query.field)
            qords = [kcol.column.ord(str(v)) for v in query.values]
            mask = filter_ops.keyword_terms(
                kcol.ords, jnp.asarray(self.c(qords or [-1], np.int32)))
            return bool_ops.constant_score(mask,
                                           self.c(query.boost, np.float32))
        self.sig("terms-any", query.field, len(query.values))
        mask = jnp.zeros(self.n, bool)
        for v in query.values:
            mask = mask | self._keyword_or_text_term_mask(query.field, v)
        return bool_ops.constant_score(mask, self.c(query.boost, np.float32))

    def _exec_RangeQuery(self, query: q.RangeQuery):
        ncol = self.seg.numeric.get(query.field)
        if ncol is not None:
            # gte/gt (and lte/lt) apply independently; effective bound is the
            # tightest (ES RangeQueryParser applies each given bound).
            lo_v = -np.inf
            if query.gte is not None:
                lo_v = self._numeric_value(query.field, query.gte)
            if query.gt is not None:
                lo_v = max(lo_v, np.nextafter(np.float64(
                    self._numeric_value(query.field, query.gt)), np.inf))
            hi_v = np.inf
            if query.lte is not None:
                hi_v = self._numeric_value(query.field, query.lte)
            if query.lt is not None:
                hi_v = min(hi_v, np.nextafter(np.float64(
                    self._numeric_value(query.field, query.lt)), -np.inf))
            self.sig("range-num", query.field)
            ghi, glo = dd_split(lo_v)
            lhi, llo = dd_split(hi_v)
            mask = filter_ops.numeric_range(
                ncol.hi, ncol.lo, ncol.exists,
                self.c(ghi, np.float32), self.c(glo, np.float32),
                self.c(lhi, np.float32), self.c(llo, np.float32))
            return bool_ops.constant_score(mask,
                                           self.c(query.boost, np.float32))
        kcol = self.seg.keyword.get(query.field)
        if kcol is not None:
            self.sig("range-kw", query.field)
            vocab = kcol.column.vocab
            lo_ord = 0
            hi_ord = len(vocab)
            if query.gte is not None:
                lo_ord = _bisect_left(vocab, str(query.gte))
            if query.gt is not None:
                lo_ord = _bisect_right(vocab, str(query.gt))
            if query.lte is not None:
                hi_ord = _bisect_right(vocab, str(query.lte))
            if query.lt is not None:
                hi_ord = _bisect_left(vocab, str(query.lt))
            mask = filter_ops.keyword_ord_range(
                kcol.ords, self.c(lo_ord, np.int32),
                self.c(hi_ord, np.int32))
            return bool_ops.constant_score(mask,
                                           self.c(query.boost, np.float32))
        return self._zeros()

    def _exec_ExistsQuery(self, query: q.ExistsQuery):
        f = query.field
        if f in self.seg.numeric:
            kind, mask = "num", self.seg.numeric[f].exists
        elif f in self.seg.keyword:
            kind, mask = "kw", (self.seg.keyword[f].ords >= 0).any(axis=1)
        elif f in self.seg.text:
            kind, mask = "text", self.seg.text[f].doc_len > 0
        elif f in self.seg.vector:
            kind, mask = "vec", self.seg.vector[f].exists
        elif f in self.seg.geo:
            kind, mask = "geo", self.seg.geo[f].exists
        else:
            kind, mask = "none", jnp.zeros(self.n, bool)
        self.sig("exists", kind, f)
        return bool_ops.constant_score(mask, self.c(query.boost, np.float32))

    # --- vocab-scan leaf family (prefix/wildcard/regexp/fuzzy) -------------

    def _vocab_scan_mask(self, field: str, pred):
        """Expand a term predicate against per-segment vocabularies —
        Lucene's MultiTermQuery rewrite (TermsEnum scan) stays host-side.
        Matching term-id lists are padded to power-of-2 buckets so queries
        with different expansion counts share compiled programs."""
        kcol = self.seg.keyword.get(field)
        if kcol is not None:
            self.sig("scan-kw", field)
            qords = [i for i, v in enumerate(kcol.column.vocab) if pred(v)]
            if not qords:
                self.sig("scan-empty")
                return jnp.zeros(self.n, bool)
            qords = _pad_pow2(qords, -1)
            return filter_ops.keyword_terms(
                kcol.ords, jnp.asarray(self.c(qords, np.int32)))
        tcol = self.seg.text.get(field)
        if tcol is not None:
            self.sig("scan-text", field)
            tids = [i for i, t in enumerate(tcol.column.terms) if pred(t)]
            if not tids:
                self.sig("scan-empty")
                return jnp.zeros(self.n, bool)
            tids = _pad_pow2(tids, -1)
            qt = jnp.asarray(self.c(tids, np.int32))
            hit = (tcol.uterms[:, :, None] == qt[None, None, :]) & \
                (qt[None, None, :] >= 0)
            return hit.any(axis=(1, 2))
        self.sig("scan-none", field)
        return jnp.zeros(self.n, bool)

    def _exec_PrefixQuery(self, query: q.PrefixQuery):
        kcol = self.seg.keyword.get(query.field)
        if kcol is not None:   # sorted vocab → ordinal interval, no scan
            self.sig("prefix-kw", query.field)
            vocab = kcol.column.vocab
            lo = _bisect_left(vocab, query.value)
            hi = _bisect_left(vocab, query.value + "￿")
            mask = filter_ops.keyword_ord_range(
                kcol.ords, self.c(lo, np.int32), self.c(hi, np.int32))
            return bool_ops.constant_score(mask,
                                           self.c(query.boost, np.float32))
        mask = self._vocab_scan_mask(query.field,
                                     lambda t: t.startswith(query.value))
        return bool_ops.constant_score(mask, self.c(query.boost, np.float32))

    def _exec_WildcardQuery(self, query: q.WildcardQuery):
        rx = re.compile(fnmatch.translate(query.pattern))
        mask = self._vocab_scan_mask(query.field, lambda t: rx.match(t) is not None)
        return bool_ops.constant_score(mask, self.c(query.boost, np.float32))

    def _exec_RegexpQuery(self, query: q.RegexpQuery):
        rx = re.compile(query.pattern)
        mask = self._vocab_scan_mask(query.field,
                                     lambda t: rx.fullmatch(t) is not None)
        return bool_ops.constant_score(mask, self.c(query.boost, np.float32))

    def _exec_FuzzyQuery(self, query: q.FuzzyQuery):
        v = query.value
        if query.fuzziness == "AUTO":
            k = 0 if len(v) < 3 else (1 if len(v) < 6 else 2)
        else:
            k = int(query.fuzziness)
        mask = self._vocab_scan_mask(query.field,
                                     lambda t: _edit_distance_le(t, v, k))
        return bool_ops.constant_score(mask, self.c(query.boost, np.float32))

    def _exec_IdsQuery(self, query: q.IdsQuery):
        wanted = set(query.values)
        hits = np.zeros(self.n, bool)
        for local, did in enumerate(self.seg.seg.ids):
            if did in wanted:
                hits[local] = True
        return bool_ops.constant_score(jnp.asarray(self.c(hits)),
                                       self.c(query.boost, np.float32))

    # ------------------------------------------------------------- compound

    def _exec_BoolQuery(self, query: q.BoolQuery):
        self.sig("bool", len(query.must), len(query.should),
                 len(query.must_not), len(query.filter))
        must = [self.execute(sub) for sub in query.must]
        should = [self.execute(sub) for sub in query.should]
        must_not = [self.match_mask(sub) for sub in query.must_not]
        filters = [self.match_mask(sub) for sub in query.filter]
        if query.minimum_should_match is not None:
            msm = _resolve_msm(query.minimum_should_match, len(query.should))
        else:
            msm = 1 if (query.should and not query.must and not query.filter) \
                else 0
        scores, mask = bool_ops.combine_bool(
            self.n, must, should, must_not, filters,
            self.c(msm, np.int32) if should else 0)
        return scores * self.c(query.boost, np.float32), mask

    def _exec_ConstantScoreQuery(self, query: q.ConstantScoreQuery):
        mask = self.match_mask(query.filter_query)
        return bool_ops.constant_score(mask, self.c(query.boost, np.float32))

    def _exec_FunctionScoreQuery(self, query: q.FunctionScoreQuery):
        self.sig("function_score", query.score_mode, query.boost_mode,
                 query.max_boost is not None, query.min_score is not None,
                 tuple((fn.kind, fn.weight is not None,
                        fn.filter_query is not None)
                       for fn in query.functions))
        base_scores, base_mask = self.execute(query.query or q.MatchAllQuery())
        factors, masks = [], []
        for fn in query.functions:
            factor = self._function_factor(fn, base_scores)
            if fn.weight is not None:
                factor = factor * self.c(fn.weight, np.float32) \
                    if fn.kind != "weight" \
                    else fs_ops.weight_factor(self.n,
                                              self.c(fn.weight, np.float32))
            fmask = self.match_mask(fn.filter_query) if fn.filter_query \
                else jnp.ones(self.n, bool)
            factors.append(factor)
            masks.append(fmask)
        combined = fs_ops.combine_functions(factors, masks, query.score_mode)
        if combined is None:
            scores = base_scores
        else:
            max_boost = None if query.max_boost is None \
                else self.c(query.max_boost, np.float32)
            scores = fs_ops.apply_boost_mode(base_scores, combined,
                                             query.boost_mode, max_boost)
        mask = base_mask
        if query.min_score is not None:
            mask = mask & (scores >= self.c(query.min_score, np.float32))
        return scores * self.c(query.boost, np.float32), mask

    def _function_factor(self, fn: q.ScoreFunction, base_scores):
        params = fn.params
        if fn.kind == "weight":
            return fs_ops.weight_factor(self.n,
                                        self.c(fn.weight or 1.0, np.float32))
        if fn.kind == "random_score":
            self.sig("random", int(params.get("seed", 0)))
            return fs_ops.random_score(self.n, int(params.get("seed", 0)),
                                       self.c(self.seg.doc_base, np.uint32))
        if fn.kind == "field_value_factor":
            fname = params["field"]
            ncol = self.seg.numeric.get(fname)
            if ncol is None:
                self.sig("fvf-missing", fname)
                missing = params.get("missing", 1.0)
                return jnp.full(self.n, 1.0, jnp.float32) * \
                    self.c(missing, np.float32)
            self.sig("fvf", fname, params.get("modifier", "none"),
                     params.get("missing") is None)
            missing = params.get("missing")
            return fs_ops.field_value_factor(
                ncol.hi, ncol.exists,
                factor=self.c(float(params.get("factor", 1.0)), np.float32),
                modifier=params.get("modifier", "none"),
                missing=None if missing is None
                else self.c(float(missing), np.float32))
        if fn.kind in ("gauss", "exp", "linear"):
            fname, spec = next(iter(params.items()))
            ncol = self.seg.numeric.get(fname)
            origin = spec.get("origin")
            fm = self.ctx.mapper_service.field_mapper(fname)
            geo_col = self.seg.geo.get(fname)
            if geo_col is not None:
                self.sig("decay-geo", fname, fn.kind)
                # geo decay: distance to origin in meters
                if isinstance(origin, dict):
                    olat, olon = float(origin["lat"]), float(origin["lon"])
                else:
                    olat, olon = (float(x) for x in str(origin).split(","))
                olat = self.c(olat, np.float32)
                olon = self.c(olon, np.float32)
                # reuse haversine by computing distances then decay
                r = 6371008.8
                p1 = jnp.radians(geo_col.lat)
                p2 = jnp.radians(olat)
                dphi = jnp.radians(geo_col.lat - olat)
                dlmb = jnp.radians(geo_col.lon - olon)
                a = jnp.sin(dphi / 2) ** 2 + jnp.cos(p1) * jnp.cos(p2) * \
                    jnp.sin(dlmb / 2) ** 2
                dist = 2 * r * jnp.arcsin(jnp.sqrt(a))
                scale = q.parse_distance(spec["scale"])
                offset = q.parse_distance(spec.get("offset", 0))
                return fs_ops.decay(dist, geo_col.exists,
                                    self.c(0.0, np.float32),
                                    self.c(scale, np.float32),
                                    self.c(offset, np.float32),
                                    self.c(float(spec.get("decay", 0.5)),
                                           np.float32), fn.kind)
            if ncol is None:
                self.sig("decay-missing", fname)
                return jnp.ones(self.n, jnp.float32)
            self.sig("decay", fname, fn.kind)
            if fm is not None and fm.type == "date":
                origin_v = parse_date(origin) if origin is not None else 0.0
                from elasticsearch_tpu.common.settings import parse_time_value
                scale = parse_time_value(spec["scale"]) * 1000.0
                offset = parse_time_value(spec.get("offset", 0)) * 1000.0
            else:
                origin_v = float(origin if origin is not None else 0.0)
                scale = float(spec["scale"])
                offset = float(spec.get("offset", 0))
            return fs_ops.decay(ncol.hi, ncol.exists,
                                self.c(origin_v, np.float32),
                                self.c(scale, np.float32),
                                self.c(offset, np.float32),
                                self.c(float(spec.get("decay", 0.5)),
                                       np.float32), fn.kind)
        if fn.kind == "script_score":
            script = params.get("script", params)
            if isinstance(script, dict):
                src = script.get("source", script.get("inline", ""))
                sparams = script.get("params", {})
            else:
                src, sparams = str(script), {}
            return self._eval_script(src, sparams, base_scores)
        raise QueryParsingError(f"unknown score function [{fn.kind}]")

    def _feed_script_params(self, params: dict) -> dict:
        """Numeric script params become dynamic constants (vector params as
        f32 arrays); anything else is structural."""
        out = {}
        for key in sorted(params):
            v = params[key]
            if isinstance(v, bool) or isinstance(v, str):
                self.sig("sparam", key, v)
                out[key] = v
            elif isinstance(v, (int, float)):
                self.sig("sparam", key, "num")
                out[key] = self.c(float(v), np.float32)
            elif isinstance(v, (list, tuple)):
                self.sig("sparam", key, "vec", len(v))
                out[key] = self.c(np.asarray(v, np.float32))
            else:
                self.sig("sparam", key, repr(v))
                out[key] = v
        return out

    def _eval_script(self, source: str, params: dict, scores):
        self.sig("script", source)
        params = self._feed_script_params(params)
        def get_numeric(field):
            ncol = self.seg.numeric.get(field)
            if ncol is None:
                return jnp.zeros(self.n, jnp.float32), jnp.zeros(self.n, bool)
            return ncol.hi, ncol.exists

        def get_vector(field):
            vcol = self.seg.vector.get(field)
            if vcol is None:
                raise QueryParsingError(f"no vector field [{field}]")
            return vcol.vecs, vcol.exists

        ctx = ScriptContext(get_numeric, get_vector, scores, params)
        out = compile_script(source).evaluate(ctx)
        return jnp.broadcast_to(jnp.asarray(out, jnp.float32), (self.n,))

    def _exec_ScriptScoreQuery(self, query: q.ScriptScoreQuery):
        base_scores, base_mask = self.execute(query.query or q.MatchAllQuery())
        scores = self._eval_script(query.script, query.params, base_scores)
        return jnp.where(base_mask,
                         scores * self.c(query.boost, np.float32), 0.0), \
            base_mask

    def _exec_KnnQuery(self, query: q.KnnQuery):
        vcol = self.seg.vector.get(query.field)
        if vcol is None:
            return self._zeros()
        qv = jnp.asarray(self.c(query.query_vector, np.float32))
        scores = vector_ops.cosine_scores(vcol.vecs, vcol.exists, qv)
        return (scores + 1.0) * self.c(query.boost, np.float32) * \
            vcol.exists.astype(jnp.float32), vcol.exists

    def _exec_GeoDistanceQuery(self, query: q.GeoDistanceQuery):
        gcol = self.seg.geo.get(query.field)
        if gcol is None:
            return self._zeros()
        mask = filter_ops.geo_distance(gcol.lat, gcol.lon, gcol.exists,
                                       self.c(query.lat, np.float32),
                                       self.c(query.lon, np.float32),
                                       self.c(query.distance_m, np.float32))
        return bool_ops.constant_score(mask, self.c(query.boost, np.float32))

    def _exec_GeoBoundingBoxQuery(self, query: q.GeoBoundingBoxQuery):
        gcol = self.seg.geo.get(query.field)
        if gcol is None:
            return self._zeros()
        mask = filter_ops.geo_bounding_box(
            gcol.lat, gcol.lon, gcol.exists,
            self.c(query.top, np.float32), self.c(query.left, np.float32),
            self.c(query.bottom, np.float32),
            self.c(query.right, np.float32))
        return bool_ops.constant_score(mask, self.c(query.boost, np.float32))


def _resolve_msm(msm, num_clauses: int) -> int:
    """minimum_should_match: int, negative int, or percentage string."""
    if isinstance(msm, int):
        return msm if msm >= 0 else max(num_clauses + msm, 0)
    s = str(msm).strip()
    if s.endswith("%"):
        pct = float(s[:-1])
        val = int(num_clauses * pct / 100.0) if pct >= 0 \
            else num_clauses - int(num_clauses * -pct / 100.0)
        return max(val, 0)
    return int(s)


def _pad_pow2(ids: list[int], fill: int) -> list[int]:
    """Pad an id list to the next power-of-2 length so vocab-expansion
    queries (wildcard/fuzzy/regexp) share compiled programs per bucket."""
    n = max(len(ids), 1)
    target = 1 << (n - 1).bit_length()
    return ids + [fill] * (target - len(ids))


def _bisect_left(vocab: list[str], v: str) -> int:
    import bisect
    return bisect.bisect_left(vocab, v)


def _bisect_right(vocab: list[str], v: str) -> int:
    import bisect
    return bisect.bisect_right(vocab, v)
