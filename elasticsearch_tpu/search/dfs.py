"""DFS phase — global term statistics for dfs_query_then_fetch.

Reference: core/search/dfs/DfsPhase.java:45 collects each shard's term and
collection statistics for the query's terms; the coordinator aggregates
them (aggregateDfs, core/search/controller/SearchPhaseController.java:
105-154) and the query phase then scores every shard with the SAME global
idf/avgdl — so multi-shard results are bit-identical to a single-shard
index over the same corpus.

Here the shard side walks the query AST host-side (the same analysis the
resolver performs), returns df per (field, term) plus per-field collection
stats, and the merged statistics flow into resolution through
``ExecutionContext.dfs_stats`` (execute.SegmentResolver._term_stats).
On-mesh (shard_map) execution gets the identical effect from a psum over
the df vectors (parallel/distributed.py); this host-side round serves the
RPC fan-out path.
"""

from __future__ import annotations

from elasticsearch_tpu.search import query_dsl as q

# wire key separator: (field, term) → "field\x00term" (JSON-safe)
_SEP = "\x00"


def _analyzer_for(mapper_service, field: str, override: str | None):
    if override:
        return mapper_service.analysis.get(override)
    fm = mapper_service.field_mapper(field)
    if fm is not None and getattr(fm, "kind", None) == "text":
        return fm.search_analyzer
    return mapper_service.analysis.get("standard")


def collect_terms(query: q.Query, text_fields: set[str],
                  mapper_service, reader=None) -> set[tuple[str, str]]:
    """→ {(field, term)} — every analyzed term whose idf affects scoring.

    Mirrors the resolver's analysis exactly (same analyzers, same
    all-fields expansion) so the DFS round covers precisely the statistics
    the query phase will look up. ``reader`` (optional) resolves
    more_like_this liked-document sources.
    """
    out: set[tuple[str, str]] = set()

    def fields_of(f: str) -> list[str]:
        return sorted(text_fields) if f in ("*", "_all") else [f]

    def analyze_into(f: str, text: str, analyzer_override=None):
        an = _analyzer_for(mapper_service, f, analyzer_override)
        out.update((f, tok.term) for tok in an.analyze(text))

    def walk(node: q.Query | None):
        if node is None:
            return
        t = type(node).__name__
        if t in ("MatchQuery", "MatchPhraseQuery"):
            for f in fields_of(node.field):
                analyze_into(f, node.text, node.analyzer)
        elif t == "MultiMatchQuery":
            for fspec in node.fields:
                for f in fields_of(fspec.partition("^")[0]):
                    analyze_into(f, node.text)
        elif t == "CommonTermsQuery":
            for f in fields_of(node.field):
                analyze_into(f, node.text, node.analyzer)
        elif t in ("TermQuery", "SpanTermQuery"):
            if node.field in text_fields:
                # resolver scores text terms via a keyword-analyzed match
                out.add((node.field, str(getattr(node, "value"))))
        elif t == "SpanNearQuery":
            for c in node.clauses:
                walk(c)
        elif t == "MoreLikeThisQuery":
            fields = node.fields or sorted(text_fields)
            texts_by_field = {f: list(node.like_texts) for f in fields}
            if reader is not None and node.like_docs:
                wanted = {str(s.get("_id", "")) for s in node.like_docs}
                for seg in reader.segments:
                    host = getattr(seg, "seg", seg)
                    for local, hid in enumerate(
                            host.ids[:host.num_docs]):
                        if hid in wanted:
                            src = host.sources[local]
                            for f in fields:
                                if isinstance(src.get(f), str):
                                    texts_by_field[f].append(src[f])
            # all candidate terms — the resolver's df-based selection then
            # reads GLOBAL stats, so coverage must precede selection
            for f, texts in texts_by_field.items():
                for text in texts:
                    analyze_into(f, text)
        elif t == "NestedQuery":
            walk(node.query)
        elif t == "DisMaxQuery":
            for sub in node.queries:
                walk(sub)
        elif t == "BoostingQuery":
            walk(node.positive)
            walk(node.negative)
        elif t == "BoolQuery":
            for sub in (*node.must, *node.should, *node.must_not,
                        *node.filter):
                walk(sub)
        elif t == "ConstantScoreQuery":
            walk(node.filter_query)
        elif t == "FunctionScoreQuery":
            walk(node.query)
            for fn in node.functions:
                walk(fn.filter_query)
        elif t == "ScriptScoreQuery":
            walk(node.query)
        # other leaf types (range/terms/prefix/...) are constant-score:
        # no idf in their scores
    walk(query)
    return out


def shard_dfs(reader, mapper_service, query: q.Query) -> dict:
    """Shard-side DFS collection (DfsPhase.execute analog) → wire-safe
    {"df": {"field\\x00term": n}, "fields": {field: [doc_count,
    docs_with_field, total_tokens]}}."""
    text_fields = set()
    for seg in reader.segments:
        text_fields.update(seg.text)
    terms = collect_terms(query, text_fields, mapper_service, reader=reader)
    df = {f"{f}{_SEP}{t}": reader.df(f, t) for f, t in terms}
    # collection term frequencies ride along for LM-family similarities
    # (P(t|C) must be GLOBAL under dfs_query_then_fetch, like idf)
    ctf = {}
    for f, t in terms:
        total = 0.0
        for seg in reader.segments:
            col = seg.seg.text_fields.get(f)
            if col is None:
                continue
            tid = col.tid(t)
            if tid >= 0:
                total += col.ctf(tid)
        ctf[f"{f}{_SEP}{t}"] = total
    fields = {}
    for f in {f for f, _ in terms}:
        st = reader.text_stats(f)
        fields[f] = [st.doc_count, st.docs_with_field, st.total_tokens]
    return {"df": df, "ctf": ctf, "fields": fields}


def aggregate_dfs(shard_results: list[dict]) -> dict:
    """Coordinator reduce (aggregateDfs analog) → the wire form passed to
    every shard's query phase."""
    df: dict[str, int] = {}
    ctf: dict[str, float] = {}
    fields: dict[str, list[int]] = {}
    for r in shard_results:
        for key, n in r.get("df", {}).items():
            df[key] = df.get(key, 0) + int(n)
        for key, n in r.get("ctf", {}).items():
            ctf[key] = ctf.get(key, 0.0) + float(n)
        for f, (dc, dwf, tt) in r.get("fields", {}).items():
            cur = fields.setdefault(f, [0, 0, 0])
            cur[0] += int(dc)
            cur[1] += int(dwf)
            cur[2] += int(tt)
    return {"df": df, "ctf": ctf, "fields": fields}


def to_execution_stats(wire: dict | None) -> dict | None:
    """Wire form → ExecutionContext.dfs_stats ({(field, term): df},
    per-field doc_count and avgdl)."""
    if not wire:
        return None
    df = {}
    for key, n in wire.get("df", {}).items():
        f, _, t = key.partition(_SEP)
        df[(f, t)] = int(n)
    ctf = {}
    for key, n in wire.get("ctf", {}).items():
        f, _, t = key.partition(_SEP)
        ctf[(f, t)] = float(n)
    doc_count = {}
    avgdl = {}
    total_tokens = {}
    for f, (dc, dwf, tt) in wire.get("fields", {}).items():
        doc_count[f] = int(dc)
        avgdl[f] = tt / max(dwf, 1)
        total_tokens[f] = int(tt)
    return {"df": df, "ctf": ctf, "doc_count": doc_count, "avgdl": avgdl,
            "total_tokens": total_tokens}
