"""Lane-admission registry: the single source of truth for the four
compiled serving lanes' fallback vocabularies, their pairwise decline
edges, and the stats counters the lanes bump.

Everything in this module is a PLAIN LITERAL on purpose: plane-lint's
whole-program pass parses this file's AST (rule families
``counter-discipline`` and ``fallback-taxonomy``) and the
``estpu-lint --emit-lane-graph`` extractor emits it — together with the
source locations of every admission predicate and reason-labeled
decline site — as ``analysis/lane_graph.json``, the machine-readable
lane model the unified-planner refactor (ROADMAP item 3) consumes. A
tier-1 test (tests/test_lane_graph.py) round-trips the emitted graph
against these live registries, so registry, runtime and artifact cannot
drift apart.

Runtime consumers:

* :mod:`elasticsearch_tpu.search.jit_exec` initializes its ``_stats`` /
  ``_data_layer`` counter stores from :data:`JIT_COUNTERS` /
  :data:`DATA_LAYER_COUNTERS` (so every registered counter is surfaced
  through ``cache_stats`` → ``_nodes/stats`` by construction) and
  asserts every ``note_*_fallback`` reason against
  :data:`LANE_REASONS`;
* :mod:`elasticsearch_tpu.search.percolator` initializes each
  registry's ``stats`` dict from :data:`PERCOLATE_COUNTERS`.
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# Counters: every key must be bumped somewhere (plane-lint
# counter-discipline flags orphans in BOTH directions: a bump of an
# unregistered key, and a registered key nothing bumps).
# ---------------------------------------------------------------------------

#: jit_exec._stats — the compiled-path program/cache/lane counters
#: surfaced verbatim under ``_nodes/stats`` ``indices.jit``.
JIT_COUNTERS = {
    "hits": "per-segment program cache hits",
    "misses": "per-segment program cache misses (one trace+compile)",
    "fallbacks": "compiled-program executions degraded to eager",
    "mesh_program_hits": "collective-plane program-layer cache hits",
    "mesh_program_misses": "collective-plane program trace+compiles",
    "plane_fallbacks": "collective-plane admission declines "
                       "(request served by the RPC fan-out)",
    "percolate_program_hits": "fused percolate lane program cache hits",
    "percolate_program_misses": "fused percolate lane trace+compiles",
    "breaker_open_skips": "requests the open plane breaker routed to "
                          "the fan-out/eager path (zero dispatches)",
    "oom_evictions": "HBM-OOM cold-block eviction sweeps",
    "oom_bytes_evicted": "device-block bytes freed by OOM sweeps",
    "impact_admissions": "requests served by the impact lane",
    "impact_blocks_scored": "impact blocks scored by the block-max sweep",
    "impact_blocks_skipped": "impact blocks skipped below the running "
                             "theta (the sublinearity evidence)",
    "impact_requant_refreshes": "impact requantizations forced by "
                                "cross-segment df drift",
    "knn_admissions": "requests served by the compiled knn lane",
    "fusion_dispatches": "in-program hybrid fusion dispatches",
    "maxsim_dispatches": "fused MaxSim dispatches over rank_vectors",
    "rescore_fused_dispatches": "impact→rescore plans composed into one "
                                "device-side dispatch",
    # cost-driven query planner (search/planner.py): the single
    # admission surface over the compiled lanes
    "planner_plans": "batches the query planner priced and routed onto "
                     "a compiled arm",
    "planner_cold_plans": "plans priced on a cold estimate (static "
                          "analysis / lane aggregate, no measured EWMA)",
    "planner_fallbacks": "planner admission outcomes that left the "
                         "compiled arms (reason-labeled)",
    # continuous-batching scheduler (search/scheduler.py): the live
    # serving path's device feeder
    "scheduler_batches_launched": "micro-batches the continuous-batching "
                                  "scheduler dispatched",
    "scheduler_batches_drained": "scheduler batches whose device→host "
                                 "drain completed",
    "scheduler_requests_admitted": "requests served through scheduler "
                                   "batches (pad rows excluded)",
    "scheduler_requests_shed": "requests the scheduler shed "
                               "(deadline / SLO-burn / capacity)",
    "scheduler_pad_rows": "no-op pad rows appended to reach the pow2 "
                          "program bucket (never delivered or counted)",
    # dispatch watchdog (search/watchdog.py): stall detection on every
    # registered device wait
    "watchdog_stalls": "device waits that outlived their predicted "
                       "envelope (flight-recorded dispatch-stall)",
    "watchdog_abandoned": "stalled waits the watchdog abandoned (the "
                          "wedged program may still own the device)",
    "watchdog_quarantines": "quarantine entries after repeated stalls "
                            "(breaker held open, probe-gated reopen)",
    "watchdog_probe_reopens": "quarantines lifted by a successful "
                              "background probe program",
}

#: jit_exec._data_layer — incremental data-plane traffic accounting
#: (surfaced under ``indices.jit.data_layer`` and the per-index /
#: collective-plane mirrors).
DATA_LAYER_COUNTERS = {
    "bytes_uploaded": "host→device bytes (columns + live masks)",
    "bytes_reused": "resident-block column bytes composed, not re-sent",
    "col_bytes_uploaded": "column bytes uploaded",
    "mask_bytes_uploaded": "live-mask bytes uploaded",
    "incremental_refreshes": "rebuilds that uploaded O(new segment)",
    "full_rebuilds": "cold / changed-layout full pack builds",
    "mask_only_refreshes": "delete-only refreshes (zero column bytes)",
    "impact_bytes_uploaded": "impact-column bytes uploaded",
    "impact_bytes_reused": "resident impact-block bytes reused",
    "vector_bytes_uploaded": "knn vector-column bytes uploaded",
    "vector_bytes_reused": "resident vector-block bytes reused",
    "placement_bytes_uploaded": "placed mesh-lane block bytes shipped "
                                "to owning devices (delta refreshes "
                                "count changed shard slices only)",
    "placement_bytes_reused": "placed block bytes reused in place "
                              "(unchanged shard slices of a refresh)",
}

#: PercolatorRegistry.stats — per-index registry/evaluation counters
#: (surfaced via the ``_stats`` percolate section and `_nodes/stats`).
PERCOLATE_COUNTERS = {
    "builds": "registry constructions from scratch",
    "syncs": "metadata syncs that applied a change",
    "adds": "query registrations",
    "removes": "query unregistrations",
    "bucket_invalidations": "shape buckets touched by syncs",
    "mapper_rebuilds": "scratch MapperService rebuilds",
    "count": "percolate ops (one per probe doc)",
    "time_ms": "wall milliseconds in percolate ops",
    "fused_queries": "query evaluations on the fused device lane",
    "fallback_queries": "query evaluations on the per-query eager lane",
    "breaker_skips": "fused dispatches the open breaker routed eager",
}

#: the program lanes of the cost observatory — one per compiled-program
#: class (every ``jit_exec.observed_compile`` call names one; plane-lint
#: rule ``program-cost-unknown-lane`` checks the literals). These are
#: PROGRAM classes, finer than the four serving lanes: the planner costs
#: "impact-pruned at this shape", not "the impact lane".
PROGRAM_LANES = (
    "segment",          # run_segment: one query × one device segment
    "segment-batch",    # run_segment_batch: B queries × one segment
    "reader-batch",     # run_reader_batch: whole-reader fused program
    "streamed",         # run_segments_streamed: host-pool segment sweep
    "percolate",        # run_percolate_lanes: fused percolate groups
    "impact-eager",     # run_impact_batch: quantized eager impacts
    "impact-pruned",    # run_impact_pruned: block-max sweep
    "impact-rescore",   # run_impact_rescore: impact candidates + fused
                        # device-side rescore stage, one dispatch
    "knn",              # run_knn_hybrid_batch: vector/hybrid programs
    "mesh",             # mesh_engine._program: the collective plane
    "impact-mesh",      # run_impact_mesh: pod-slice block-max sweep
                        # (per-shard sweeps + θ-exchange + cross-chip
                        # top-k merge, one shard_map program)
    "knn-mesh",         # run_knn_hybrid_mesh: doc-sharded vector/
                        # MaxSim scoring + cross-chip candidate merge
)

#: the program cost observatory's per-lane gauge registry — the
#: OpenMetrics exposition renders one ``estpu_program_cost_<key>{lane=}``
#: gauge per entry from ``costs.lane_rollup()`` (whose rollup dicts
#: carry exactly these keys), so adding a field here adds it to the
#: scrape by construction. Emitted into ``lane_graph.json`` next to the
#: counter registries — the planner reads the lanes' observable cost
#: surface from the same artifact as their admission model.
PROGRAM_COST = {
    "resident": "programs resident in the cost table",
    "compiles": "program trace+compiles (sum over resident programs)",
    "compile_ms": "wall milliseconds spent compiling",
    "dispatches": "program dispatches recorded",
    "device_time_us": "accumulated device time (µs, span-measured)",
    "requests": "real requests served (the n_real contract)",
    "rows": "program batch rows dispatched (incl. pow2 padding)",
    "predicted_us": "dispatch-weighted roofline prediction (µs)",
    "measured_us": "dispatch-weighted measured EWMA (µs)",
}

# ---------------------------------------------------------------------------
# Fallback taxonomy: ONE registered reason vocabulary per lane.
# note_plane_fallback / note_impact_fallback / note_knn_fallback /
# note_percolate_fallback assert membership at runtime; plane-lint's
# fallback-taxonomy rule checks every literal call site statically and
# flags unknown, duplicated, and never-noted reasons.
# ---------------------------------------------------------------------------

LANE_REASONS = {
    # collective plane (mesh) admission declines, search_action
    "plane": (
        "ineligible-shape",     # sort/agg/cursor shape the mesh can't serve
        "parse-error",          # body failed the plane's re-parse
        "refresh-race",         # pack vs fetch-reader generation raced twice
        "device-error",         # mesh build/dispatch raised: eager rescue
        "not-local",            # not every target shard lives on this node
        "breaker-open",         # plane breaker open: zero-dispatch decline
        "device-stall",         # watchdog abandoned a wedged device wait
        "routed-impact",        # planner priced the impact arm cheaper
        "routed-knn",           # planner routed the knn lane (knn never
                                # rides the mesh)
    ),
    # impact-ordered lane admission declines, phase._impact_batch_launch
    "impact": (
        "dfs-stats",            # DFS global idf vs reader-local impacts
        "streamed-reader",      # non-resident segments can't pack impacts
        "ineligible-shape",     # aggs/sort/rescore/... shape screen
        "ineligible-cursor",    # search_after arity the lane can't resume
        "ineligible-query",     # not an impact-scorable term disjunction
        "mixed-fields",         # batch spans more than one impact field
        "no-impact-columns",    # opted in but no segment built impacts
        "cross-lane-cursor",    # cursor minted outside the quantized lane
        "device-error",         # impact pack/dispatch raised: exact rescue
    ),
    # dense / late-interaction lane declines, phase._knn_batch_launch
    "knn": (
        "mixed-shapes",         # batch spans fields/modes/plan signatures
        "streamed-reader",      # non-resident segments can't pack vectors
        "no-vector-columns",    # mapped but no segment carries vectors
        "device-error",         # vector pack/dispatch raised: eager rescue
        "breaker-open",         # plane breaker open: straight to eager
    ),
    # fused percolate lane declines, percolator.PercolatorRegistry.run
    "percolate": (
        "device-error",         # fused dispatch raised: eager rescue
        "breaker-open",         # plane breaker open: eager lane serves
    ),
    # continuous-batching scheduler sheds, scheduler.submit / pickup
    "scheduler": (
        "queue-deadline",       # deadline blown while queued: serial path
        "task-cancelled",       # task cancelled while queued: abort
        "slo-shed",             # queue_wait SLO burn: typed 429 rejection
        "queue-full",           # admission queue at capacity: typed 429
        "closed",               # node shutting down: serial fallback
        "device-stall",         # batch abandoned by the dispatch
                                # watchdog: waiters redirected serial
    ),
    # cost-driven query planner, planner.plan_batch — the single
    # admission surface that replaced the pairwise decline edges: the
    # plane no longer hardcodes "impact-preferred"/"knn-lane" handoffs,
    # it asks the planner which priced arm serves the request
    "planner": (
        "routed-impact",        # plan chose the impact arm over the mesh
        "routed-knn",           # plan chose the vector/hybrid arm (the
                                # mesh program has no vector lanes)
        "breaker-open",         # breaker open/quarantined: every device
                                # candidate excluded from the plan
        "no-plan",              # no candidate sub-plan admissible: the
                                # serial per-request path serves
        "plan-error",           # planner raised: legacy admission order
                                # served the batch (degraded, counted)
    ),
}

#: (declining lane, serving lane, reason the decliner labels): the
#: pairwise admission-handoff edges. EMPTY since the cost-driven
#: planner (search/planner.py) replaced the hardcoded handoffs — lane
#: choice is one priced decision surfaced through the ``planner``
#: vocabulary above (``routed-impact`` / ``routed-knn``), not an N×N
#: decline matrix. The tuple stays registered so the lane-graph
#: artifact keeps recording "no pairwise edges" machine-checkably.
DECLINE_EDGES = ()

#: lane → "pkg-relative module path::Qualname" of the admission
#: predicate (the function whose declines bump that lane's reasons).
#: The lane-graph extractor resolves these to file:line against the
#: live tree, so a rename breaks the tier-1 round-trip loudly.
LANE_ADMISSIONS = {
    "plane": "elasticsearch_tpu/action/search_action.py"
             "::SearchActions._try_collective_plane",
    "impact": "elasticsearch_tpu/search/phase.py"
              "::ShardSearcher._impact_batch_launch",
    "knn": "elasticsearch_tpu/search/phase.py"
           "::ShardSearcher._knn_batch_launch",
    "percolate": "elasticsearch_tpu/search/percolator.py"
                 "::PercolatorRegistry.run",
    "scheduler": "elasticsearch_tpu/search/scheduler.py"
                 "::ContinuousBatchScheduler.submit",
    "planner": "elasticsearch_tpu/search/planner.py"
               "::plan_batch",
}


def check_reason(lane: str, reason: str) -> str:
    """Assert-style guard the ``note_*_fallback`` seams call: an
    unregistered reason is a programming error (the taxonomy is closed;
    plane-lint checks literals statically, this catches dynamic ones)."""
    assert reason in LANE_REASONS[lane], (
        f"unregistered {lane}-lane fallback reason {reason!r} — add it "
        f"to elasticsearch_tpu.search.lanes.LANE_REASONS[{lane!r}]")
    return reason
