"""Compiled query execution: one fused XLA program per (plan, layout).

This delivers the promise in execute.py's docstring — the production query
path analog of QueryPhase's single collector pass (ref:
core/search/query/QueryPhase.java:99-314, `searcher.search(query,
collector)` :314): instead of eagerly dispatching one device op per AST
node, the whole per-segment walk — scoring, boolean algebra,
function_score, min_score, post_filter, search-after continuation, hit
counting and top-k — traces into ONE jitted program.

Mechanics (see execute.ConstFeed):

1. **plan pass** — `jax.eval_shape` walks the executor abstractly (zero
   device work), recording every dynamic constant (term ids, idf, bounds)
   and a structural signature (query shape, static tokens, const shapes).
2. **cache** — key = (signature, segment layout, BM25 params, output
   wants). Hit → the compiled program runs with this query's constants as
   inputs. Queries differing only in terms/values/boosts share a program;
   segments sharing a shape bucket share it too (the bounded-recompilation
   contract of segment.doc_count_bucket).
3. **replay** — the jitted function rebuilds a segment view from traced
   arrays and re-walks the same executor code, with `ConstFeed` handing
   back traced constants in recorded order.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import replace as dc_replace

import jax
import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.index.device_reader import DeviceSegment
from elasticsearch_tpu.ops import topk as topk_ops
from elasticsearch_tpu.search.execute import (
    ConstFeed, ExecutionContext, SegmentExecutor)

_CACHE_CAP = 512
_cache: OrderedDict[tuple, "jax.stages.Wrapped"] = OrderedDict()
_cache_lock = threading.Lock()
_stats = {"hits": 0, "misses": 0, "fallbacks": 0}


def cache_stats() -> dict:
    return dict(_stats)


def note_fallback() -> None:
    with _cache_lock:
        _stats["fallbacks"] += 1


def clear_cache() -> None:
    with _cache_lock:
        _cache.clear()
        _stats.update(hits=0, misses=0, fallbacks=0)


# ---------------------------------------------------------------------------
# Segment flatten/rebuild (the traced-input pytree)
# ---------------------------------------------------------------------------

_KINDS = ("text", "keyword", "numeric", "vector", "geo")
_ARRAYS = {
    "text": ("tokens", "uterms", "utf", "doc_len"),
    "keyword": ("ords",),
    "numeric": ("hi", "lo", "exists"),
    "vector": ("vecs", "exists"),
    "geo": ("lat", "lon", "exists"),
}


def seg_flatten(seg: DeviceSegment) -> list:
    """Device arrays of a segment in deterministic order (live first)."""
    flat = [seg.live]
    for kind in _KINDS:
        fields = getattr(seg, kind)
        for name in sorted(fields):
            col = fields[name]
            for attr in _ARRAYS[kind]:
                flat.append(getattr(col, attr))
    return flat


def seg_rebuild(seg: DeviceSegment, flat: list) -> DeviceSegment:
    """Shallow-copy `seg` with arrays swapped for (traced) `flat`."""
    it = iter(flat)
    live = next(it)
    kinds = {}
    for kind in _KINDS:
        fields = getattr(seg, kind)
        # arrays were flattened in sorted-name order, but the rebuilt dicts
        # must preserve the ORIGINAL iteration order — executor walks (e.g.
        # the all-fields match loop) iterate these dicts, and plan/replay
        # const order depends on it
        rebuilt = {
            name: dc_replace(fields[name],
                             **{attr: next(it) for attr in _ARRAYS[kind]})
            for name in sorted(fields)}
        kinds[kind] = {name: rebuilt[name] for name in fields}
    return dc_replace(seg, live=live, **kinds)


def layout_key(seg: DeviceSegment) -> tuple:
    out = [seg.padded_docs]
    for kind in _KINDS:
        fields = getattr(seg, kind)
        for name in sorted(fields):
            col = fields[name]
            out.append((kind, name) + tuple(
                (tuple(getattr(col, attr).shape),
                 str(getattr(col, attr).dtype))
                for attr in _ARRAYS[kind]))
    return tuple(out)


# ---------------------------------------------------------------------------
# The fused per-segment program
# ---------------------------------------------------------------------------

def _build(seg_view, ctx, query, post_filter, flags, k):
    """The traced body: executor walk + phase post-processing + top-k."""
    cf = ctx.cf
    ex = SegmentExecutor(seg_view, ctx)
    scores, mask = ex.execute(query)
    mask = mask & seg_view.live
    if flags["min_score"]:
        mask = mask & (scores >= cf.feed(flags["_min_score"], np.float32))
    if post_filter is not None:
        pf_mask = SegmentExecutor(seg_view, ctx).match_mask(post_filter)
        mask_post = mask & pf_mask
    else:
        mask_post = mask
    if flags["search_after"]:
        last_score = cf.feed(flags["_sa_score"], np.float32)
        last_doc = cf.feed(flags["_sa_doc"], np.int32)
        ids = jnp.arange(seg_view.padded_docs, dtype=jnp.int32) + \
            cf.feed(flags["_doc_base"], np.int32)
        cont = (scores < last_score) | ((scores == last_score) &
                                        (ids > last_doc))
        mask_post = mask_post & cont
    count = mask_post.sum(dtype=jnp.int32)
    outs = {"count": count}
    if flags["want_topk"]:
        ts, td = topk_ops.top_k(scores, mask_post,
                                min(k, seg_view.padded_docs),
                                0)
        outs["top_scores"], outs["top_docs"] = ts, td
    if flags["want_arrays"]:
        outs["scores"] = scores
        outs["mask"] = mask_post
        # pre-post_filter mask for aggregations (ES computes aggs on the
        # main query result, ignoring post_filter)
        outs["agg_mask"] = mask
    return outs


def run_segment(seg: DeviceSegment, ctx: ExecutionContext, query,
                *, post_filter=None, min_score=None, search_after=None,
                k: int | None = None, want_arrays: bool = False) -> dict:
    """Execute a query against one device segment as one compiled program.

    Returns {"count": i32 [, "top_scores", "top_docs"] [, "scores",
    "mask", "agg_mask"]} as device arrays; top_docs are segment-local
    (caller adds seg.doc_base).
    """
    flags = {
        "min_score": min_score is not None,
        "_min_score": 0.0 if min_score is None else float(min_score),
        "search_after": search_after is not None,
        "_sa_score": 0.0 if search_after is None
        else float(search_after[0]),
        "_sa_doc": -1 if (search_after is None or len(search_after) < 2)
        else int(search_after[1]),
        "_doc_base": seg.doc_base,
        "want_topk": k is not None,
        "want_arrays": want_arrays,
    }
    k_static = 0 if k is None else int(k)

    # ---- plan pass: collect consts + signature, no device work ----------
    pcf = ConstFeed("plan")
    pctx = dc_replace(ctx, cf=pcf)
    jax.eval_shape(
        lambda: _build(seg, pctx, query, post_filter, flags, k_static))
    consts = tuple(jnp.asarray(v) for v in pcf.values)

    key = (pcf.signature(), layout_key(seg),
           float(ctx.bm25.k1), float(ctx.bm25.b),
           flags["min_score"], flags["search_after"], k_static, want_arrays,
           post_filter is not None)

    flat = seg_flatten(seg)
    with _cache_lock:
        fn = _cache.get(key)
        if fn is not None:
            _cache.move_to_end(key)
            _stats["hits"] += 1
    if fn is None:
        with _cache_lock:
            _stats["misses"] += 1

        def run(flat_in, consts_in):
            rcf = ConstFeed("replay", replay=consts_in)
            rctx = dc_replace(ctx, cf=rcf)
            view = seg_rebuild(seg, flat_in)
            return _build(view, rctx, query, post_filter, flags, k_static)

        # AOT lower+compile and cache ONLY the executable: a cached
        # jax.jit closure would pin the whole DeviceSegment/DeviceReader
        # (every column's device arrays) for the life of the cache entry —
        # an accumulating device-memory leak across index churn
        shapes = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            (flat, consts))
        fn = jax.jit(run).lower(*shapes).compile()
        with _cache_lock:
            _cache[key] = fn
            while len(_cache) > _CACHE_CAP:
                _cache.popitem(last=False)

    return fn(flat, consts)
