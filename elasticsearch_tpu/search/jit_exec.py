"""Compiled query execution: one fused XLA program per (plan, layout).

This delivers the promise in execute.py's docstring — the production query
path analog of QueryPhase's single collector pass (ref:
core/search/query/QueryPhase.java:99-314, `searcher.search(query,
collector)` :314): the whole per-segment walk — scoring, boolean algebra,
function_score, min_score, post_filter, search-after continuation, hit
counting and top-k — runs as ONE jitted program.

Mechanics (see execute.SegmentResolver):

1. **resolve** — host-side "createWeight": dictionary lookups collect every
   dynamic constant (term ids, idf, bounds) into a ConstTable plus a
   structural signature, and produce emit closures of pure jnp ops.
   Microseconds per query — no tracing, no device work.
2. **cache** — key = (signature, segment layout, BM25 params, output
   wants). Hit → the compiled program runs with this query's constants as
   inputs. Queries differing only in terms/values/boosts share a program;
   segments sharing a shape bucket share it too (the bounded-recompilation
   contract of segment.doc_count_bucket).
3. **emit under jit** — the jitted function rebuilds a segment view from
   traced arrays and calls the emit closures with traced constants.
4. **batch** — B same-signature queries stack their constants on a leading
   axis and run under ``jax.vmap`` as one program (run_segment_batch): the
   TPU-native answer to request-at-a-time dispatch.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from collections import OrderedDict
from dataclasses import replace as dc_replace

import jax
import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.index.device_reader import DeviceSegment
from elasticsearch_tpu.observability import attribution as _attribution
from elasticsearch_tpu.observability.context import current_node_id
from elasticsearch_tpu.observability.tracing import device_span
from elasticsearch_tpu.ops import blockmax as blockmax_ops
from elasticsearch_tpu.ops import topk as topk_ops
from elasticsearch_tpu.search import lanes
from elasticsearch_tpu.search.execute import (
    ConstTable, EmitCtx, ExecutionContext, SegmentResolver)

_CACHE_CAP = 512
_cache: OrderedDict[tuple, "jax.stages.Wrapped"] = OrderedDict()
_cache_lock = threading.Lock()


# ---------------------------------------------------------------------------
# Device-fault seam + plane circuit breaker (accelerator-fault tolerance)
# ---------------------------------------------------------------------------

class DeviceFaultError(RuntimeError):
    """Simulated accelerator error (testing_disruption.DeviceFaultScheme)
    — shaped like the dispatch/upload/compile failures a sick device
    raises, so every fallback seam treats it exactly like the real
    thing."""


class DeviceOomError(DeviceFaultError):
    """Simulated HBM out-of-memory (the XLA RESOURCE_EXHAUSTED shape):
    the one device error with a recovery action cheaper than degrading —
    evict cold device blocks and let the next build retry smaller."""


class DeviceStallError(DeviceFaultError):
    """A device wait outlived its predicted envelope and the watchdog
    abandoned it. HONESTY: Python cannot cancel a wedged XLA dispatch or
    transfer — the underlying program may still own the device; what was
    abandoned is the *wait*, so the caller fails over while the wedged
    thread is left to finish (or not) on its own."""


#: chaos seam: a callable(site: str) that may raise at each device
#: touchpoint — ``dispatch`` (compiled per-segment/reader programs),
#: ``compile`` (program build), ``upload`` (host→device block/column
#: transfer), ``compose`` (device-side pack stacking), ``plane-dispatch``
#: (the collective-plane mesh program), ``percolate`` (fused percolate
#: lanes). None in production — the check is a single attribute read.
_device_fault_hook = None


def set_device_fault_hook(hook):
    """Install (or with None, remove) the device-fault hook → the
    previous hook, so stacked schemes can chain and restore."""
    global _device_fault_hook
    prev = _device_fault_hook
    _device_fault_hook = hook
    return prev


def device_fault_point(site: str) -> None:
    """One device touchpoint: gives the installed chaos hook the chance
    to raise an accelerator-style error here."""
    hook = _device_fault_hook
    if hook is not None:
        hook(site)


def seam_device_put(a, device=None, site: str = "upload"):
    """Host→device transfer through the fault seam: modules outside the
    seam allowlist (device readers, standalone models, the distributed
    data plane) route uploads here instead of calling ``jax.device_put``
    raw, so chaos injection reaches every transfer and the plane breaker
    observes real upload failures (plane-lint rule device-raw-call).

    ``site`` must be a literal site class at the call site (plane-lint
    checks it): ``upload`` for plane/block transfers, ``reader-upload``
    for the RPC fan-out's baseline reader — the serving FLOOR, which the
    default chaos draw leaves alone (see testing_disruption.
    DEVICE_FAULT_SITES) so degraded-mode serving always has a working
    fallback; targeted tests opt in via ``p_by_site``."""
    with device_span(site):
        device_fault_point(site)
        return jax.device_put(a) if device is None \
            else jax.device_put(a, device)


def seam_jit(fn, **kwargs):
    """Program construction through the fault seam. Callers OWN the
    caching — memoize the result per static shape (plane-lint rule
    recompile-request-path checks call sites); the seam only makes the
    compile injectable and breaker-visible."""
    with device_span("compile"):
        device_fault_point("compile")
        return jax.jit(fn, **kwargs)


def observed_compile(lane: str, shape_key, lower_fn, *,
                     owner: str | None = None):
    """THE program-compile seam: every ``.lower(...).compile(...)`` in
    the seam modules flows through here (plane-lint rule family
    ``program-cost-discipline`` holds the tree to it).

    ``lower_fn()`` returns the ``jax.stages.Lowered``; this seam owns
    the ``.compile()`` so it can stamp, per program key (``lane`` ×
    ``shape_key`` — the program cache's own key), the XLA static cost
    analyses and the compile wall time into the per-node
    ProgramCostTable (observability/costs.py). ``lane`` must be a
    string literal from ``lanes.PROGRAM_LANES`` at the call site;
    ``owner`` (an engine incarnation uuid, when the caller knows one)
    lets the table drain the program's row when the engine closes.
    The fault point and the compile span live here too, so chaos
    injection and the tracer see exactly one compile per flow."""
    assert lane in lanes.PROGRAM_LANES, (
        f"unregistered program lane {lane!r} — add it to "
        f"elasticsearch_tpu.search.lanes.PROGRAM_LANES")
    from elasticsearch_tpu.observability import costs
    with device_span("compile") as dsp:
        device_fault_point("compile")
        t0 = time.perf_counter()
        compiled = lower_fn().compile()
        compile_ms = (time.perf_counter() - t0) * 1e3
        dsp.set(lane=lane, compile_ms=round(compile_ms, 3))
    costs.note_compile(lane, shape_key, compiled, compile_ms,
                       owner=owner)
    return compiled


def is_device_oom(exc: BaseException) -> bool:
    """Does this exception look like device memory exhaustion? Covers
    the injected :class:`DeviceOomError` and the strings real XLA
    runtime errors carry (RESOURCE_EXHAUSTED / out of memory)."""
    if isinstance(exc, DeviceOomError):
        return True
    msg = str(exc)
    return "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower()


class PlaneBreaker:
    """Per-node circuit breaker over the compiled device paths.

    closed → open after ``threshold`` CONSECUTIVE device errors →
    half-open probe after an exponentially backed-off wait. While open,
    admission gates (collective-plane admission in search_action, the
    percolator's fused lanes, ShardSearcher's compiled query phase)
    route straight to the fan-out/eager path, so an unhealthy device
    costs fallback latency — not a failed device dispatch per query.
    In half-open exactly ONE request is admitted as the probe; its
    success closes the breaker, its failure re-opens with a doubled
    backoff (capped at ``max_backoff_s``).

    All in-process nodes share one device, so the module singleton
    ``plane_breaker`` IS the per-node breaker (one node = one process =
    one device in deployment); ``search.plane_breaker.*`` node settings
    configure it.
    """

    #: a claimed half-open probe that never reports back (thread died)
    #: frees the probe slot after this long
    PROBE_TIMEOUT_S = 30.0

    def __init__(self, threshold: int = 3, backoff_s: float = 1.0,
                 max_backoff_s: float = 30.0):
        self._lock = threading.Lock()
        self.threshold = int(threshold)
        self.base_backoff_s = float(backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self._reset_locked()

    def _reset_locked(self) -> None:
        self.state = "closed"
        self.consecutive_errors = 0
        self.trips = 0
        self.probes = 0
        self.errors_total = 0
        self.last_error: str | None = None
        self._backoff_s = self.base_backoff_s
        self._retry_at = 0.0
        self._probe_deadline: float | None = None
        # watchdog quarantine: while set, allow() is False for live
        # traffic unconditionally — reopen is gated on the watchdog's
        # background probe program, never on a live-request probe
        self.quarantined = False

    #: breaker state → registered flight-recorder event type
    _TRANSITION_EVENTS = {"open": "breaker-open",
                          "half_open": "breaker-half-open",
                          "closed": "breaker-closed"}

    @staticmethod
    def _note_transition(state: str, **attrs) -> None:
        """One breaker state transition on the flight recorder (called
        AFTER the breaker lock releases — the ring lock stays a leaf)."""
        from elasticsearch_tpu.observability import flightrec
        flightrec.note(PlaneBreaker._TRANSITION_EVENTS[state],
                       state=state, **attrs)

    def reset(self) -> None:
        with self._lock:
            was = self.state
            self._reset_locked()
        if was != "closed":
            self._note_transition("closed", reset=True)

    def configure(self, threshold=None, backoff_s=None,
                  max_backoff_s=None) -> None:
        """Apply node settings (None leaves a knob unchanged)."""
        with self._lock:
            if threshold is not None:
                self.threshold = max(int(threshold), 1)
            if backoff_s is not None:
                self.base_backoff_s = float(backoff_s)
                if self.state == "closed":
                    self._backoff_s = self.base_backoff_s
            if max_backoff_s is not None:
                self.max_backoff_s = float(max_backoff_s)

    def allow(self) -> bool:
        """May a device dispatch proceed? Open → False (until the
        backoff elapses); half-open → True for exactly one caller (the
        probe), False for everyone else."""
        now = time.monotonic()
        probing = False
        with self._lock:
            if self.quarantined:
                return False
            if self.state == "closed":
                return True
            if self.state == "open":
                if now < self._retry_at:
                    return False
                self.state = "half_open"
                self.probes += 1
                self._probe_deadline = now + self.PROBE_TIMEOUT_S
                probing = True
            elif self._probe_deadline is not None and \
                    now < self._probe_deadline:
                # half_open: one probe in flight at a time
                return False
            else:
                self.probes += 1
                self._probe_deadline = now + self.PROBE_TIMEOUT_S
                return True
        if probing:
            self._note_transition("half_open", probes=self.probes)
        return True

    def record_success(self) -> None:
        """A device dispatch completed: closes a half-open probe, resets
        the consecutive-error count."""
        closed = False
        with self._lock:
            if self.state == "half_open":
                self.state = "closed"
                self._backoff_s = self.base_backoff_s
                closed = True
            self.consecutive_errors = 0
            self._probe_deadline = None
        if closed:
            self._note_transition("closed", probes=self.probes)

    def record_error(self, exc: BaseException) -> None:
        """A device dispatch failed: counts toward the trip threshold;
        a failed half-open probe re-opens with doubled backoff."""
        now = time.monotonic()
        opened = None
        with self._lock:
            self.errors_total += 1
            self.last_error = f"{type(exc).__name__}: {str(exc)[:160]}"
            self.consecutive_errors += 1
            if self.state == "half_open":
                self.state = "open"
                self.trips += 1
                self._backoff_s = min(self._backoff_s * 2,
                                      self.max_backoff_s)
                self._retry_at = now + self._backoff_s
                self._probe_deadline = None
                opened = "probe-failed"
            elif self.state == "closed" and \
                    self.consecutive_errors >= self.threshold:
                self.state = "open"
                self.trips += 1
                self._retry_at = now + self._backoff_s
                opened = "threshold"
        if opened is not None:
            self._note_transition(
                "open", cause=opened, trips=self.trips,
                consecutive_errors=self.consecutive_errors,
                error=self.last_error,
                backoff_seconds=round(self._backoff_s, 3))

    def quarantine(self) -> None:
        """Watchdog escalation: hold the breaker open unconditionally.
        While quarantined, ``allow()`` declines every live request (no
        half-open probe on live traffic); only
        :meth:`release_quarantine` — called by the watchdog after its
        background probe program completes — readmits."""
        with self._lock:
            already = self.quarantined
            self.quarantined = True
            if self.state != "open":
                self.state = "open"
                self.trips += 1
            self._probe_deadline = None
        if not already:
            self._note_transition("open", cause="quarantine",
                                  trips=self.trips)

    def release_quarantine(self) -> None:
        """The watchdog's probe program completed: fully reset to
        closed (the device proved itself end to end)."""
        with self._lock:
            was = self.quarantined
            self._reset_locked()
        if was:
            self._note_transition("closed", probe_reopen=True)

    def stats(self) -> dict:
        now = time.monotonic()
        with self._lock:
            return {
                "state": self.state,
                "threshold": self.threshold,
                "consecutive_errors": self.consecutive_errors,
                "trips": self.trips,
                "probes": self.probes,
                "errors_total": self.errors_total,
                "last_error": self.last_error,
                "quarantined": self.quarantined,
                "backoff_seconds": round(self._backoff_s, 3),
                "open_remaining_seconds":
                    round(max(self._retry_at - now, 0.0), 3)
                    if self.state == "open" and not self.quarantined
                    else 0.0,
            }


#: THE per-node plane breaker (module singleton — see class docstring)
plane_breaker = PlaneBreaker()


def note_device_error(exc: BaseException) -> None:
    """One device error observed at a compiled-path seam: feeds the
    plane breaker, and for HBM-OOM shapes first evicts cold blocks from
    the PR 5 device-block cache — reclaiming headroom is cheaper than
    degrading, and the next (re)build retries against a smaller
    footprint."""
    if is_device_oom(exc):
        try:
            from elasticsearch_tpu.parallel import mesh_engine
            freed = mesh_engine.evict_cold_blocks()
        except Exception:                # noqa: BLE001 — best-effort
            freed = 0
        with _cache_lock:
            _bump("oom_evictions")
            _bump("oom_bytes_evicted", int(freed))
    plane_breaker.record_error(exc)


def note_breaker_skip() -> None:
    """One request routed to the fan-out/eager path because the plane
    breaker was open — the degraded-mode-serving counter. (Collective-
    plane admission declines label ``fallback_reasons`` separately via
    :func:`note_plane_fallback` with reason ``breaker-open``.)"""
    with _cache_lock:
        _bump("breaker_open_skips")
# mesh_program_* count the collective plane's shape-keyed PROGRAM layer
# (mesh_engine._program): a miss is a fresh shard_map trace+compile, a
# hit re-dispatches a compiled program against a new data-layer pack —
# the counters that prove a repeated sorted/terms-agg query re-traces at
# most once per shape, not per refresh generation. plane_fallbacks
# counts ADMISSION declines (the request still succeeds on the RPC
# fan-out) — kept apart from `fallbacks`, which tracks compiled-program
# executions degrading to eager and is held at zero by the jit suites.
# Keys (and their meanings) live in the lane registry — the store is
# built FROM it so every registered counter is surfaced through
# cache_stats() → _nodes/stats by construction, and plane-lint's
# counter-discipline rule can prove registry ⇔ bump-site agreement.
_stats = {k: 0 for k in lanes.JIT_COUNTERS}
#: why searches left the compiled/collective path, by label
#: (ineligible-shape / parse-error / refresh-race / device-error / …)
_fallback_reasons: dict[str, int] = {}
#: why impact-lane admission declined, by label — only bumped for
#: indices that OPTED IN to the impact plane (the exact scorer is the
#: default; a disabled index never logs an impact fallback)
_impact_fallback_reasons: dict[str, int] = {}
#: why knn/hybrid requests left the compiled lane (the eager
#: per-segment fallback served them), by label
_knn_fallback_reasons: dict[str, int] = {}
#: why fused-percolate dispatches fell to the per-query eager lane
#: (breaker-open / device-error), by label
_percolate_fallback_reasons: dict[str, int] = {}
#: why the continuous-batching scheduler shed requests (queue-deadline /
#: slo-shed / queue-full / task-cancelled / closed), by label
_scheduler_shed_reasons: dict[str, int] = {}
#: planner admission outcomes by label (routed-impact / routed-knn /
#: breaker-open / no-plan / plan-error) — the vocabulary that replaced
#: the pairwise decline edges
_planner_fallback_reasons: dict[str, int] = {}
#: per-INDEX knn-lane accounting — feeds the per-index _stats
#: "search.knn" section and the _cat/indices knn.* columns
_knn_index_stats: dict[str, dict] = {}
#: per-INDEX impact-lane accounting (admissions, blocks scored/skipped)
#: — feeds the per-index _stats "search.impact" section and the
#: _cat/indices impact.{blocks,skip_ratio} columns
_impact_index_stats: dict[str, dict] = {}

# Per-NODE attribution of the rollups above: every in-process node
# shares this module, so without node keying a two-node cluster test
# reads one node's compiles in the other node's _nodes/stats. Counter
# bumps attribute to observability.context.current_node_id() (the
# executing task's node); cache_stats(node_id=...) reads one bucket.
_node_stats: dict[str, dict] = {}
_node_fallback_reasons: dict[str, dict] = {}


def _bump(key: str, n: int = 1) -> None:
    """Count one event on the process-global rollup, the current node's
    bucket, and (for program-cache keys) the per-request slow-log
    attribution. Callers hold ``_cache_lock``."""
    _stats[key] += n
    nid = current_node_id()
    if nid is not None:
        bucket = _node_stats.setdefault(nid, {})
        bucket[key] = bucket.get(key, 0) + n
    if key in _attribution.MIRRORED_COUNTS:
        _attribution.count(key, n)

# data_layer.* count the collective plane's INCREMENTAL data layer
# (mesh_engine._DeviceBlockCache): bytes_uploaded is actual host→device
# transfer (column + live-mask bytes, split out below), bytes_reused is
# the column bytes of already-resident blocks a rebuild composed instead
# of re-uploading. The refresh classifiers prove the contract the tier-1
# guards pin down: a one-segment refresh is `incremental` (uploads O(new
# segment)), a delete-only refresh is `mask_only` (ZERO column bytes),
# and only a cold/changed-layout build is a `full_rebuild`.
_data_layer = {k: 0 for k in lanes.DATA_LAYER_COUNTERS}


def cache_stats(node_id: str | None = None) -> dict:
    """The process-global rollup (default), or — with ``node_id`` — the
    counters attributed to one node's tasks (the per-node view
    ``_nodes/stats`` reports as ``jit.node_local``)."""
    if node_id is not None:
        with _cache_lock:
            bucket = dict(_node_stats.get(node_id, {}))
            reasons = dict(_node_fallback_reasons.get(node_id, {}))
        out = {key: bucket.get(key, 0) for key in _stats}
        out["fallback_reasons"] = reasons
        return out
    with _cache_lock:
        out = {**_stats, "fallback_reasons": dict(_fallback_reasons),
               "impact_fallback_reasons": dict(_impact_fallback_reasons),
               "knn_fallback_reasons": dict(_knn_fallback_reasons),
               "percolate_fallback_reasons":
                   dict(_percolate_fallback_reasons),
               "scheduler_shed_reasons": dict(_scheduler_shed_reasons),
               "planner_fallback_reasons":
                   dict(_planner_fallback_reasons),
               "data_layer": dict(_data_layer)}
    out["plane_breaker"] = plane_breaker.stats()
    return out


def note_data_blocks(col_bytes: int = 0, mask_bytes: int = 0,
                     reused_bytes: int = 0) -> None:
    """Block-cache traffic from one data-layer (re)build: host→device
    uploads (columns / live masks) and resident-block reuse."""
    with _cache_lock:
        _data_layer["bytes_uploaded"] += col_bytes + mask_bytes
        _data_layer["col_bytes_uploaded"] += col_bytes
        _data_layer["mask_bytes_uploaded"] += mask_bytes
        _data_layer["bytes_reused"] += reused_bytes


def note_data_refresh(kind: str) -> None:
    """One data-layer rebuild classified: 'full' (no resident block
    reused), 'incremental' (new column bytes composed with resident
    blocks), or 'mask_only' (zero column bytes uploaded)."""
    key = {"full": "full_rebuilds", "incremental": "incremental_refreshes",
           "mask_only": "mask_only_refreshes"}[kind]
    with _cache_lock:
        _data_layer[key] += 1


def note_mesh_program(hit: bool) -> None:
    """One collective-plane program-cache lookup (mesh_engine._program)."""
    with _cache_lock:
        _bump("mesh_program_hits" if hit else "mesh_program_misses")


def note_plane_fallback(reason: str) -> None:
    """One collective-plane admission decline, reason-labeled."""
    lanes.check_reason("plane", reason)
    _attribution.label("fallback", reason)
    with _cache_lock:
        _bump("plane_fallbacks")
        _fallback_reasons[reason] = _fallback_reasons.get(reason, 0) + 1
        nid = current_node_id()
        if nid is not None:
            bucket = _node_fallback_reasons.setdefault(nid, {})
            bucket[reason] = bucket.get(reason, 0) + 1


_logged_fallbacks: set = set()


def note_fallback(exc: BaseException | None = None,
                  reason: str | None = None) -> None:
    if reason is not None:
        # compiled-path degradations share the plane vocabulary
        lanes.check_reason("plane", reason)
    with _cache_lock:
        _bump("fallbacks")
        if reason is not None:
            _fallback_reasons[reason] = _fallback_reasons.get(reason, 0) + 1
    if exc is not None:
        # log each distinct failure once — silent fallbacks hide real
        # kernel bugs (round-2 verdict weak #9)
        key = (type(exc).__name__, str(exc)[:120])
        if key not in _logged_fallbacks:
            _logged_fallbacks.add(key)
            import logging
            import traceback
            logging.getLogger("elasticsearch_tpu.jit").warning(
                "jit path fell back to eager: %s",
                "".join(traceback.format_exception(
                    type(exc), exc, exc.__traceback__)[-3:]).strip())


def clear_cache() -> None:
    with _cache_lock:
        _cache.clear()
        _stats.update({k: 0 for k in _stats})
        _fallback_reasons.clear()
        _impact_fallback_reasons.clear()
        _impact_index_stats.clear()
        _knn_fallback_reasons.clear()
        _knn_index_stats.clear()
        _percolate_fallback_reasons.clear()
        _scheduler_shed_reasons.clear()
        _planner_fallback_reasons.clear()
        _data_layer.update({k: 0 for k in _data_layer})
        _node_stats.clear()
        _node_fallback_reasons.clear()
    # the cost observatory and flight recorder reset with the program
    # cache: their books describe the programs the cache holds
    from elasticsearch_tpu.observability import costs, flightrec
    costs.reset()
    flightrec.reset()


# ---------------------------------------------------------------------------
# Segment flatten/rebuild (the traced-input pytree)
# ---------------------------------------------------------------------------

_KINDS = ("text", "keyword", "numeric", "vector", "mvector", "geo",
          "shape")
_ARRAYS = {
    "text": ("tokens", "uterms", "utf", "doc_len"),
    "keyword": ("ords",),
    "numeric": ("hi", "lo", "exists"),
    "vector": ("vecs", "exists"),
    "mvector": ("vecs", "lens", "exists"),
    "geo": ("lat", "lon", "exists"),
    "shape": ("lats", "lons", "nv", "exists", "rid", "area"),
}


_materialize_lock = threading.Lock()


def _fetch(seg: DeviceSegment, col, attr: str):
    """Read a column array, materializing LAZY host-side columns (tokens /
    vecs) onto the reader's device on first use. The result is cached back
    on the column object so the transfer happens once per reader
    generation; the lock stops concurrent first-phrase-queries from
    shipping the same hundreds of MB twice."""
    a = getattr(col, attr)
    if seg.lazy_put is None or not isinstance(a, np.ndarray):
        return a
    with _materialize_lock:
        a = getattr(col, attr)
        if isinstance(a, np.ndarray):
            a = seg.lazy_put(a)
            setattr(col, attr, a)
    return a


def _keep(kind: str, attr: str, name: str, positions_for, vectors_for
          ) -> bool:
    """Tree-shaking rule for the traced-input pytree: text position
    matrices and vector columns are kept per-FIELD, everything else
    always. `None` for either filter means "keep everything" (the mesh
    engine pre-stacks segments once, before any plan exists)."""
    if kind == "text" and attr == "tokens":
        return positions_for is None or name in positions_for
    if kind in ("vector", "mvector") and attr == "vecs":
        return vectors_for is None or name in vectors_for
    return True


def seg_flatten(seg: DeviceSegment, positions_for: frozenset | None = None,
                vectors_for: frozenset | None = None) -> list:
    """Device arrays of a segment in deterministic order (live first;
    nested child blocks recurse after the flat kinds). Text position
    matrices flatten ONLY for fields in `positions_for`, and vector/geo
    columns only when the plan declared that kind — tracing the [N, L]
    tokens array (or a [N, 768] vector column) no op reads multiplies
    XLA compile time for nothing (measured ~14x at 1M docs)."""
    flat = [seg.live]
    for kind in _KINDS:
        fields = getattr(seg, kind)
        for name in sorted(fields):
            col = fields[name]
            for attr in _ARRAYS[kind]:
                if not _keep(kind, attr, name, positions_for, vectors_for):
                    continue
                flat.append(_fetch(seg, col, attr))
    for path in sorted(seg.nested):
        blk = seg.nested[path]
        flat.append(blk.parent)
        flat.extend(seg_flatten(blk.child, positions_for, vectors_for))
    return flat


def seg_rebuild(seg: DeviceSegment, flat: list,
                positions_for: frozenset | None = None,
                vectors_for: frozenset | None = None) -> DeviceSegment:
    """Shallow-copy `seg` with arrays swapped for (traced) `flat`. Arrays
    excluded from the flatten become None — a plan reading data it never
    declared fails loudly at trace time (and falls back to eager) instead
    of silently baking a device buffer into the compiled program as a
    constant."""
    it = iter(flat)

    def rebuild(s: DeviceSegment) -> DeviceSegment:
        live = next(it)
        kinds = {}
        for kind in _KINDS:
            fields = getattr(s, kind)
            # arrays were flattened in sorted-name order, but the rebuilt
            # dicts must preserve the ORIGINAL iteration order — resolver
            # walks (e.g. the all-fields match loop) iterate these dicts,
            # and the emitted structure depends on it
            rebuilt = {
                name: dc_replace(fields[name],
                                 **{attr: (next(it)
                                           if _keep(kind, attr, name,
                                                    positions_for,
                                                    vectors_for)
                                           else None)
                                    for attr in _ARRAYS[kind]})
                for name in sorted(fields)}
            kinds[kind] = {name: rebuilt[name] for name in fields}
        nested = {}
        for path in sorted(s.nested):
            blk = s.nested[path]
            parent = next(it)
            nested[path] = dc_replace(blk, parent=parent,
                                      child=rebuild(blk.child))
        nested = {path: nested[path] for path in s.nested}
        return dc_replace(s, live=live, nested=nested, **kinds)

    return rebuild(seg)


def layout_key(seg: DeviceSegment) -> tuple:
    out = [seg.padded_docs]
    for kind in _KINDS:
        fields = getattr(seg, kind)
        for name in sorted(fields):
            col = fields[name]
            out.append((kind, name) + tuple(
                (tuple(getattr(col, attr).shape),
                 str(getattr(col, attr).dtype))
                for attr in _ARRAYS[kind]))
    for path in sorted(seg.nested):
        blk = seg.nested[path]
        out.append(("nested", path, tuple(blk.parent.shape),
                    layout_key(blk.child)))
    return tuple(out)


# ---------------------------------------------------------------------------
# The fused per-segment program
# ---------------------------------------------------------------------------

def _plan(seg: DeviceSegment, ctx: ExecutionContext, query, post_filter,
          flags):
    """Host resolve → (ConstTable, emit_q, emit_pf mask-emit, flag refs)."""
    ct = ConstTable()
    resolver = SegmentResolver(seg, ctx, ct)
    emit_q = resolver.resolve(query)
    emit_pf = resolver.resolve_mask(post_filter) \
        if post_filter is not None else None
    refs = {}
    if flags["min_score"]:
        refs["min_score"] = ct.add(flags["_min_score"], np.float32)
    if flags["search_after"]:
        refs["sa_score"] = ct.add(flags["_sa_score"], np.float32)
        refs["sa_doc"] = ct.add(flags["_sa_doc"], np.int32)
        refs["doc_base"] = ct.add(flags["_doc_base"], np.int32)
    return ct, emit_q, emit_pf, refs


def _build(view, consts, emit_q, emit_pf, refs, flags, k: int):
    """The program body: emit + phase post-processing + top-k."""
    em = EmitCtx(view, consts)
    scores, mask = emit_q(em)
    mask = mask & view.live
    if "min_score" in refs:
        mask = mask & (scores >= em.get(refs["min_score"]))
    if emit_pf is not None:
        mask_post = mask & emit_pf(em)
    else:
        mask_post = mask
    if "sa_score" in refs:
        last_score = em.get(refs["sa_score"])
        last_doc = em.get(refs["sa_doc"])
        ids = jnp.arange(view.padded_docs, dtype=jnp.int32) + \
            em.get(refs["doc_base"])
        cont = (scores < last_score) | ((scores == last_score) &
                                        (ids > last_doc))
        mask_post = mask_post & cont
    count = mask_post.sum(dtype=jnp.int32)
    outs = {"count": count}
    if flags["want_topk"]:
        ts, td = topk_ops.top_k(scores, mask_post,
                                min(k, view.padded_docs), 0)
        outs["top_scores"], outs["top_docs"] = ts, td
    if flags["want_arrays"]:
        outs["scores"] = scores
        outs["mask"] = mask_post
        # pre-post_filter mask for aggregations (ES computes aggs on the
        # main query result, ignoring post_filter)
        outs["agg_mask"] = mask
    return outs


def _get_compiled(key, lower_fn, lane: str = "segment",
                  owner: str | None = None):
    """Program-cache trampoline: ``lower_fn`` returns the LOWERED
    program; a miss routes it through :func:`observed_compile` (which
    owns the ``.compile()``, the fault point and the cost-table stamp)
    under ``lane``'s books."""
    with _cache_lock:
        fn = _cache.get(key)
        if fn is not None:
            _cache.move_to_end(key)
            _bump("hits")
            return fn
    # compile OUTSIDE the lock (slow); a racing duplicate compile is
    # harmless — last one wins the cache slot
    with _cache_lock:
        _bump("misses")
    fn = observed_compile(lane, key, lower_fn, owner=owner)
    with _cache_lock:
        _cache[key] = fn
        while len(_cache) > _CACHE_CAP:
            _cache.popitem(last=False)
    return fn


def run_segment(seg: DeviceSegment, ctx: ExecutionContext, query,
                *, post_filter=None, min_score=None, search_after=None,
                k: int | None = None, want_arrays: bool = False) -> dict:
    """Execute a query against one device segment as one compiled program.

    Returns {"count": i32 [, "top_scores", "top_docs"] [, "scores",
    "mask", "agg_mask"]} as device arrays; top_docs are segment-local
    (caller adds seg.doc_base).
    """
    flags = {
        "min_score": min_score is not None,
        "_min_score": 0.0 if min_score is None else float(min_score),
        "search_after": search_after is not None,
        "_sa_score": 0.0 if search_after is None
        else float(search_after[0]),
        "_sa_doc": -1 if (search_after is None or len(search_after) < 2)
        else int(search_after[1]),
        "_doc_base": seg.doc_base,
        "want_topk": k is not None,
        "want_arrays": want_arrays,
    }
    k_static = 0 if k is None else int(k)

    ct, emit_q, emit_pf, refs = _plan(seg, ctx, query, post_filter, flags)
    consts = [jnp.asarray(v) for v in ct.values]

    pos_for = frozenset(ct.positions_needed)
    vecs = frozenset(ct.vectors_needed)
    key = (ct.signature(), layout_key(seg), pos_for, vecs,
           float(ctx.bm25.k1), float(ctx.bm25.b),
           flags["min_score"], flags["search_after"], k_static, want_arrays,
           post_filter is not None)
    flat = seg_flatten(seg, pos_for, vecs)

    def compile_fn():
        def run(flat_in, consts_in):
            view = seg_rebuild(seg, flat_in, pos_for, vecs)
            return _build(view, consts_in, emit_q, emit_pf, refs, flags,
                          k_static)
        # AOT lower (observed_compile owns the .compile()) and cache
        # ONLY the executable: a cached jax.jit closure would pin the
        # whole DeviceSegment/DeviceReader (every column's device
        # arrays) for the life of the cache entry — an accumulating
        # device-memory leak across index churn
        shapes = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            (flat, consts))
        return jax.jit(run).lower(*shapes)

    fn = _get_compiled(key, compile_fn, lane="segment",
                       owner=getattr(ctx.reader, "engine_uuid", None))
    with device_span("dispatch", cost=("segment", key, 1, 1)):
        device_fault_point("dispatch")
        return fn(flat, consts)


def _plan_segment_batch(seg: DeviceSegment, ctx: ExecutionContext,
                        queries: list, k_static: int) -> dict | None:
    """Plan a batch of same-signature queries against one segment and pack
    their dynamic constants per dtype into ONE [B, total] buffer each:
    every host→device transfer pays dispatch/tunnel latency, so 2 packed
    buffers beat N small ones; the program unpacks by static slicing
    (free under XLA). The spec layout is a pure function of the plan
    signature, so cached programs agree on it. Returns None when the
    queries do not share one plan signature or the shared plan has no
    dynamic constants (callers fall back to per-query execution)."""
    if not queries:
        return None
    flags = {
        "min_score": False, "_min_score": 0.0,
        "search_after": False, "_sa_score": 0.0, "_sa_doc": -1,
        "_doc_base": seg.doc_base,
        "want_topk": True, "want_arrays": False,
    }
    sig0 = None
    emit0 = refs0 = None
    pos_for: frozenset = frozenset()
    vecs: frozenset = frozenset()
    consts_rows: list[list[np.ndarray]] = []
    for query in queries:
        ct, emit_q, _, refs = _plan(seg, ctx, query, None, flags)
        if sig0 is None:
            sig0, emit0, refs0 = ct.signature(), emit_q, refs
            pos_for = frozenset(ct.positions_needed)
            vecs = frozenset(ct.vectors_needed)
        elif ct.signature() != sig0:
            return None
        consts_rows.append(ct.values)

    b = len(queries)
    # pad the batch axis to the next power of two (repeating the last
    # query's constants) so varying batch sizes share compiled programs
    from elasticsearch_tpu.search.batching import pow2_bucket
    b_pad = pow2_bucket(b)
    if b_pad != b:
        consts_rows = consts_rows + [consts_rows[-1]] * (b_pad - b)
    if not consts_rows[0]:
        # const-free plans (match_none / absent-field zeros): nothing to
        # vmap over — the per-query path handles these (rare) shapes
        return None
    specs = []                       # per const: (dtype, offset, shape, size)
    totals: dict[str, int] = {}
    for v in consts_rows[0]:
        dt = str(v.dtype)
        off = totals.get(dt, 0)
        size = int(v.size)
        specs.append((dt, off, v.shape, size))
        totals[dt] = off + size
    packed = {}
    for dt, total in totals.items():
        packed[dt] = np.empty((b_pad, total), dtype=dt)
    for bi, row in enumerate(consts_rows):
        for v, (dt, off, _shape, size) in zip(row, specs):
            packed[dt][bi, off:off + size] = v.reshape(-1)
    return {
        "seg": seg, "sig": sig0, "emit": emit0, "refs": refs0,
        "pos": pos_for, "vecs": vecs, "flags": flags,
        "specs": tuple(specs), "packed": packed, "b_pad": b_pad,
        "flat": seg_flatten(seg, pos_for, vecs),
        "key": (sig0, layout_key(seg), pos_for, vecs,
                float(ctx.bm25.k1), float(ctx.bm25.b), k_static, b_pad,
                tuple(specs)),
        "k": k_static,
    }


def _lane_fn(plan: dict, view: DeviceSegment):
    """One vmap lane: unpack this query's constants by static slicing and
    run the shared program body."""
    def one(packed_one):
        consts_one = [
            packed_one[dt][off:off + size].reshape(shape)
            for dt, off, shape, size in plan["specs"]]
        return _build(view, consts_one, plan["emit"], None, plan["refs"],
                      plan["flags"], plan["k"])
    return one


def run_reader_batch(segments: list, ctx: ExecutionContext, queries: list,
                     *, k: int, pack: bool, n_real: int | None = None):
    """The whole reader's batched query phase as ONE compiled program:
    per-segment vmapped scoring + top-k, cross-segment merge to
    reader-global doc ids (TopDocs.merge tie-break — concat in segment
    order + stable top_k, core/search/controller/SearchPhaseController
    .java:165), hit-count sum, and (with ``pack``) the [B, 2k+1] packed
    fetch layout — a single device dispatch + a single device→host fetch
    per batch instead of S+2 dispatches, which matters when every
    dispatch pays tunneled-interconnect round-trip latency.

    Returns a packed [B, 2k+1] f32 array (``pack=True``; exact only while
    doc ids and counts stay below 2**24 — the caller checks max_doc), or
    ``{"top_scores", "top_docs", "count"}`` device arrays. None when any
    segment's queries do not share one plan signature (caller falls back
    to per-query execution).
    """
    if not queries or not segments:
        return None
    k_static = int(k)
    plans = []
    for seg in segments:
        plan = _plan_segment_batch(seg, ctx, queries, k_static)
        if plan is None:
            return None
        plans.append(plan)
    b = len(queries)
    b_pad = plans[0]["b_pad"]
    bases = tuple(int(seg.doc_base) for seg in segments)
    key = ("reader", bases, bool(pack)) + tuple(p["key"] for p in plans)
    flats = [p["flat"] for p in plans]
    packeds = [{dt: jnp.asarray(buf) for dt, buf in p["packed"].items()}
               for p in plans]
    if os.environ.get("JIT_DEBUG"):
        total = sum(int(a.size) * a.dtype.itemsize
                    for flat in flats for a in flat)
        print(f"[jit-debug] reader batch: {len(plans)} segment(s), "
              f"{sum(len(f) for f in flats)} arrays, {total/1e6:.1f} MB "
              f"traced; pos_for={sorted(plans[0]['pos'])} "
              f"vecs={sorted(plans[0]['vecs'])}", flush=True)

    def compile_fn():
        def run(flats_in, packeds_in):
            ts_list, td_list = [], []
            counts = None
            for plan, flat_in, packed_in in zip(plans, flats_in,
                                                packeds_in):
                view = seg_rebuild(plan["seg"], flat_in,
                                   plan["pos"], plan["vecs"])
                outs = jax.vmap(_lane_fn(plan, view))(packed_in)
                ts_list.append(outs["top_scores"])
                td_list.append(outs["top_docs"])
                counts = outs["count"] if counts is None \
                    else counts + outs["count"]
            top_s, top_d = topk_ops.merge_top_k_batch_body(
                ts_list, td_list, k_static, bases)
            if pack:
                return topk_ops.pack_batch_result_body(top_s, top_d,
                                                       counts)
            return {"top_scores": top_s, "top_docs": top_d, "count": counts}

        shapes = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            (flats, packeds))
        return jax.jit(run).lower(*shapes)

    fn = _get_compiled(key, compile_fn, lane="reader-batch",
                       owner=getattr(ctx.reader, "engine_uuid", None))
    with device_span("dispatch",
                     cost=("reader-batch", key,
                           n_real if n_real is not None else b, b_pad)):
        device_fault_point("dispatch")
        out = fn(flats, packeds)
    if b_pad != b:
        out = out[:b] if pack else {name: v[:b] for name, v in out.items()}
    return out


#: how long the streamed consumer waits on the feeder (per segment, and
#: for the teardown join) before declaring the feeder's host→device
#: transfer wedged — generous vs any real DMA; stall tests shrink it
STREAM_FEEDER_STALL_S = 60.0


def run_segments_streamed(segments: list, ctx: ExecutionContext,
                          queries: list, *, k: int,
                          device=None) -> list | None:
    """Batched query phase over HOST-POOL (non-resident) segments: each
    segment's columns are DMA'd host→HBM per batch, double-buffered so
    segment i+1's transfer overlaps segment i's compute, and the device
    buffers are dropped as soon as the program consumes them — corpora
    beyond HBM capacity execute at a bounded footprint of ~two segments'
    columns (SURVEY §7 "HBM budget & residency"; the over-capacity analog
    of the reference's FS-cache paging,
    core/index/store/FsDirectoryService.java mmap).

    Returns one ``{"count", "top_scores", "top_docs"}`` dict per segment
    (batch axis padded like :func:`run_segment_batch` — callers slice),
    or None when any segment's plan is ineligible for batching.
    """
    if not segments:
        return []
    k_static = int(k)
    plans = []
    for seg in segments:
        plan = _plan_segment_batch(seg, ctx, queries, k_static)
        if plan is None:
            return None
        plans.append(plan)
    def put(a, _dev=device):
        with device_span("upload"):
            device_fault_point("upload")
            return jax.device_put(a, _dev) if _dev is not None \
                else jax.device_put(a)

    def get_fn(seg, plan):
        def compile_fn():
            def run(flat_in, packed_in):
                view = seg_rebuild(seg, flat_in, plan["pos"], plan["vecs"])
                return jax.vmap(_lane_fn(plan, view))(packed_in)
            shapes = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                (plan["flat"], plan["packed"]))
            return jax.jit(run).lower(*shapes)
        # same key space as run_segment_batch: bucketized segments with a
        # common layout share ONE compiled program across the whole sweep
        return _get_compiled(("batch",) + plan["key"], compile_fn,
                             lane="streamed",
                             owner=getattr(ctx.reader, "engine_uuid",
                                           None))

    # transfers run on a DEDICATED feeder thread, one segment ahead:
    # host→HBM DMA overlaps the in-flight program's compute even when
    # device_put itself blocks the calling thread on this interconnect —
    # the same reason batching.py drains on worker threads. A
    # 2-permit semaphore bounds MATERIALIZED segments to two (the
    # over-capacity contract this path exists for); the consumer blocks
    # on segment i−1's completion before granting the next permit, so
    # async dispatch cannot run ahead of the device and pin every
    # segment's buffers at once.
    prefetch: queue.Queue = queue.Queue()
    feed_err: list = []
    slots = threading.Semaphore(2)
    stop = threading.Event()

    def _feeder():
        try:
            for plan in plans:
                while not slots.acquire(timeout=0.25):
                    if stop.is_set():
                        return
                if stop.is_set():
                    return
                prefetch.put([put(a) for a in plan["flat"]])
        except Exception as e:               # noqa: BLE001 — surfaced below
            feed_err.append(e)
            prefetch.put(None)

    feeder = threading.Thread(target=_feeder, daemon=True,
                              name="hbm-stream-feeder")
    feeder.start()
    outs_all = []
    stats = {"put_wait_s": 0.0, "dispatch_s": 0.0}
    try:
        for i, (seg, plan) in enumerate(zip(segments, plans)):
            t0 = time.perf_counter()
            stall_at = t0 + STREAM_FEEDER_STALL_S
            while True:
                try:
                    cur = prefetch.get(timeout=0.25)
                    break
                except queue.Empty:
                    if feed_err:
                        raise feed_err[0]
                    if time.perf_counter() > stall_at:
                        raise DeviceStallError(
                            f"hbm-stream feeder stalled staging segment "
                            f"{i}/{len(plans)} (no transfer completed in "
                            f"{STREAM_FEEDER_STALL_S:.0f}s)")
            if cur is None:
                raise feed_err[0]
            stats["put_wait_s"] += time.perf_counter() - t0
            fn = get_fn(seg, plan)
            packed = {dt: jnp.asarray(buf)
                      for dt, buf in plan["packed"].items()}
            t1 = time.perf_counter()
            with device_span("dispatch",
                             cost=("streamed", ("batch",) + plan["key"],
                                   len(queries), plan["b_pad"])):
                device_fault_point("dispatch")
                outs = fn(cur, packed)      # async dispatch
            stats["dispatch_s"] += time.perf_counter() - t1
            outs_all.append(outs)
            del cur                         # free as soon as compute drains
            if i >= 1:
                # segment i−1's program has fully drained → its column
                # buffers are free; only then does a permit return so
                # the feeder may stage segment i+1 (keeps exactly two
                # segments materialized: i computing, i+1 staging)
                jax.block_until_ready(  # estpu: allow[host-sync-hot-loop] two-segment residency backpressure — the sync IS the contract (feeder may stage i+1 only after i−1 drains)
                    outs_all[i - 1]["count"])
                slots.release()
    finally:
        stop.set()                          # unblocks a waiting feeder on
        feeder.join(timeout=STREAM_FEEDER_STALL_S)  # any consumer error
        if feeder.is_alive():
            # the feeder is wedged inside a host→device transfer Python
            # cannot cancel: abandon the daemon thread, record the stall
            # (breaker + flight recorder), and let teardown proceed —
            # raising here would mask a propagating consumer error
            stalled = DeviceStallError(
                "hbm-stream feeder wedged in a host→device transfer; "
                "teardown abandoned the join (thread left to finish)")
            note_device_error(stalled)
            from elasticsearch_tpu.observability import flightrec
            flightrec.note("dispatch-stall", site="upload",
                           lane="streamed", where="feeder-join",
                           wait_seconds=STREAM_FEEDER_STALL_S)
            feed_err.append(stalled)
    if feed_err:
        raise feed_err[0]
    run_segments_streamed.last_stats = stats
    return outs_all


# ---------------------------------------------------------------------------
# Percolation lanes: many registered queries × one probe doc, one dispatch
# ---------------------------------------------------------------------------

def pack_query_consts(consts_rows: list) -> tuple | None:
    """Stack B same-signature queries' ConstTable values into one [B_pad,
    total] buffer per dtype (the run_segment_batch packing discipline: two
    packed transfers beat N small ones, and the batch axis pads to the
    next power of two so varying registration counts share programs).
    → (specs, packed, b_pad) or None when the shared plan is const-free
    (the caller runs the program once and broadcasts)."""
    from elasticsearch_tpu.search.batching import pow2_bucket
    b = len(consts_rows)
    b_pad = pow2_bucket(b)
    if b_pad != b:
        consts_rows = consts_rows + [consts_rows[-1]] * (b_pad - b)
    if not consts_rows[0]:
        return None
    specs = []                       # per const: (dtype, offset, shape, size)
    totals: dict[str, int] = {}
    for v in consts_rows[0]:
        dt = str(v.dtype)
        off = totals.get(dt, 0)
        size = int(v.size)
        specs.append((dt, off, v.shape, size))
        totals[dt] = off + size
    packed = {dt: np.empty((b_pad, total), dtype=dt)
              for dt, total in totals.items()}
    for bi, row in enumerate(consts_rows):
        for v, (dt, off, _shape, size) in zip(row, specs):
            packed[dt][bi, off:off + size] = v.reshape(-1)
    return tuple(specs), packed, b_pad


def make_percolate_lane(seg: DeviceSegment, emit, sig: tuple,
                        pos_for: frozenset, vecs_for: frozenset,
                        consts_rows: list, bm25) -> dict:
    """One percolate lane = (one probe segment × one same-signature query
    group): the emit closure of the group's first plan plus every member's
    constants packed on a leading batch axis. `consts_rows` must all share
    `sig` (the caller groups by actual plan signature)."""
    packed_spec = pack_query_consts(consts_rows)
    if packed_spec is None:
        specs, packed, b_pad = (), {}, 1     # const-free: run once, broadcast
    else:
        specs, packed, b_pad = packed_spec
    return {
        "seg": seg, "emit": emit, "specs": specs, "packed": packed,
        "pos": pos_for, "vecs": vecs_for, "b_pad": b_pad,
        "b": len(consts_rows),
        "flat": seg_flatten(seg, pos_for, vecs_for),
        "key": (sig, layout_key(seg), pos_for, vecs_for,
                float(bm25.k1), float(bm25.b), b_pad, specs),
    }


def run_percolate_lanes(lanes: list) -> list:
    """Evaluate percolate lanes as ONE compiled dispatch per PLAN SHAPE:
    lanes sharing a key (plan signature × probe layout × batch bucket) —
    e.g. an _mpercolate's D same-shaped probe docs against the same query
    bucket — stack their segment arrays AND their packed constants on a
    leading axis and run as one doubly-vmapped program (docs × queries).
    Inside each lane the probe segment view rebuilds from traced arrays,
    the group's queries run with their constants unpacked by static
    slicing, and the per-query (matched, score) pair reduces in-program
    (ops/percolate.match_reduce_body) so a whole lane's result crosses
    the link as one small [B, 2] pack.

    Keying per lane (not per lane-SET) is what bounds compiles to ≤1 per
    plan shape: a probe-dependent lane (wildcard expansion differing per
    doc) recompiles alone instead of dragging every stable lane with it.

    → one [b, 2] numpy array per lane (match flag, score), batch padding
    dropped; const-free lanes come back as [1, 2] (callers broadcast)."""
    from elasticsearch_tpu.ops import percolate as perc_ops
    from elasticsearch_tpu.search.batching import pow2_bucket
    if not lanes:
        return []
    groups: dict[tuple, list[int]] = {}
    for i, lane in enumerate(lanes):
        groups.setdefault(lane["key"], []).append(i)
    results: list = [None] * len(lanes)
    pending = []
    for key, idxs in groups.items():
        rep = lanes[idxs[0]]
        n = len(idxs)
        n_pad = pow2_bucket(n)          # stack axis bucketed like the
        padded = idxs + [idxs[-1]] * (n_pad - n)   # query batch axis
        flats = [jnp.stack([lanes[i]["flat"][j] for i in padded])
                 for j in range(len(rep["flat"]))]
        packed = {dt: jnp.stack([jnp.asarray(lanes[i]["packed"][dt])
                                 for i in padded])
                  for dt in rep["packed"]}

        def compile_fn(rep=rep):
            def run(flats_in, packed_in):
                def one(flat_one, packed_one):
                    view = seg_rebuild(rep["seg"], flat_one,
                                       rep["pos"], rep["vecs"])
                    if rep["specs"]:
                        def one_q(pq):
                            consts_one = [
                                pq[dt][off:off + size].reshape(shape)
                                for dt, off, shape, size in rep["specs"]]
                            em = EmitCtx(view, consts_one)
                            scores, mask = rep["emit"](em)
                            return perc_ops.match_reduce_body(
                                scores, mask & view.live)
                        matched, best = jax.vmap(one_q)(packed_one)
                    else:
                        # const-free plan (match_all / match_none
                        # shapes): every query in the group IS the same
                        # program — run once; the host broadcasts
                        em = EmitCtx(view, [])
                        scores, mask = rep["emit"](em)
                        matched, best = perc_ops.match_reduce_body(
                            scores, mask & view.live)
                        matched, best = matched[None], best[None]
                    return perc_ops.pack_match_result_body(matched, best)
                return jax.vmap(one)(flats_in, packed_in)

            shapes = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                (flats, packed))
            return jax.jit(run).lower(*shapes)

        full_key = ("percolate", key, n_pad)
        with _cache_lock:
            hit = full_key in _cache
            _bump("percolate_program_hits" if hit
                  else "percolate_program_misses")
        fn = _get_compiled(full_key, compile_fn, lane="percolate")
        with device_span("percolate",
                         cost=("percolate", full_key, n, n_pad)):
            device_fault_point("percolate")
            out = fn(flats, packed)     # async dispatch: groups pipeline
        pending.append((idxs, out))
    for idxs, out in pending:
        arr = np.asarray(out)           # [n_pad, b(_pad)|1, 2]
        for row, i in enumerate(idxs):
            lane = lanes[i]
            results[i] = arr[row, :lane["b"]] if lane["specs"] \
                else arr[row]
    return results


def run_segment_batch(seg: DeviceSegment, ctx: ExecutionContext,
                      queries: list, *, k: int,
                      n_real: int | None = None) -> dict | None:
    """Execute a BATCH of queries against one device segment as ONE vmapped
    compiled program.

    This is the TPU-native answer to the reference's request-at-a-time
    search dispatch (SearchService.executeQueryPhase,
    core/search/SearchService.java:293, driven per request by
    TransportSearchTypeAction): an accelerator wants batches, so queries
    sharing a plan signature share one program with their constants stacked
    on a leading batch axis — scoring, masking and per-query top-k all run
    under jax.vmap with no host round-trips between queries.

    Only the score-ordered top-k shape is supported (no post_filter /
    min_score / search_after / aggregation arrays — callers route such
    requests down the per-query path). Returns ``{"count": [B] i32,
    "top_scores": [B, k] f32, "top_docs": [B, k] i32}`` (segment-local doc
    ids) as device arrays, or ``None`` when the queries do not all share
    one plan signature — or the shared plan has no dynamic constants —
    (the caller falls back to per-query execution).

    The batch axis is padded to the next power of two (repeating the last
    query's constants) so varying batch sizes share compiled programs.
    """
    plan = _plan_segment_batch(seg, ctx, queries, int(k))
    if plan is None:
        return None
    b = len(queries)
    key = ("batch",) + plan["key"]
    flat = plan["flat"]
    packed = {dt: jnp.asarray(buf) for dt, buf in plan["packed"].items()}
    if os.environ.get("JIT_DEBUG"):
        total = sum(int(a.size) * a.dtype.itemsize for a in flat)
        print(f"[jit-debug] batch flat: {len(flat)} arrays, "
              f"{total/1e6:.1f} MB traced; pos_for={sorted(plan['pos'])} "
              f"vecs={sorted(plan['vecs'])}", flush=True)

    def compile_fn():
        def run(flat_in, packed_in):
            view = seg_rebuild(seg, flat_in, plan["pos"], plan["vecs"])
            return jax.vmap(_lane_fn(plan, view))(packed_in)

        shapes = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            (flat, packed))
        return jax.jit(run).lower(*shapes)

    fn = _get_compiled(key, compile_fn, lane="segment-batch",
                       owner=getattr(ctx.reader, "engine_uuid", None))
    with device_span("dispatch",
                     cost=("segment-batch", key,
                           n_real if n_real is not None else b,
                           plan["b_pad"])):
        device_fault_point("dispatch")
        outs = fn(flat, packed)
    if plan["b_pad"] != b:
        outs = {name: v[:b] for name, v in outs.items()}
    return outs


# ---------------------------------------------------------------------------
# Impact-ordered lane: quantized eager impacts + block-max pruning
#
# The exact forward kernel recomputes idf·tfNorm per (doc, term) on every
# query. The impact lane reads the quantized per-(term, doc) impacts
# precomputed at segment-build time (index/segment.build_impact_column,
# BM25S-style) — a dense compare + integer sum — and, with block maxima,
# sweeps row blocks in descending upper-bound order skipping blocks that
# cannot reach the running k-th score (ops/blockmax.py). Admission is
# opt-in per index (`index.search.impact_plane`): quantized scores agree
# with the exact scorer only within the documented quantization bound,
# so the exact scorer stays the default.
#
# Device residency rides the PR 5 per-segment block cache
# (mesh_engine._DeviceBlockCache.fetch_aux): a refresh uploads impact
# bytes only for NEW (or drift-requantized) segments, counter-verified
# via data_layer.impact_bytes_{uploaded,reused}.
# ---------------------------------------------------------------------------

from dataclasses import dataclass as _dataclass


@_dataclass(frozen=True)
class ImpactPlaneConfig:
    """Per-index impact-lane knobs (index.search.impact.* settings)."""
    bits: int = 8
    block_rows: int = 2048
    prune: bool = True          # block-max sweep when totals not tracked
    max_terms: int = 64         # T cap (term-batched reduction chunks
                                # keep program size ~T/8, so expansion-
                                # sized queries fit the impact arm)


#: index name → config for indices that opted in (None = lane off)
_impact_configs: dict[str, ImpactPlaneConfig] = {}


def validate_impact_settings(settings) -> tuple:
    """Validate the ``index.search.impact.*`` knobs, raising the
    create-index-time 400 on a bad value — mirroring the store.type
    idiom: a typo must fail the CREATE REQUEST, never reach the
    cluster-state applier, and never surface later as a misleading
    'device-error' fallback when the column build rejects it inside the
    dispatch seam. → (bits, block_rows, max_terms)."""
    from elasticsearch_tpu.common.errors import IllegalArgumentError
    from elasticsearch_tpu.index.segment import (IMPACT_BITS,
                                                 IMPACT_BLOCK_ROWS)
    get = settings.get if settings is not None else (lambda *_: None)

    def setting(name, default):
        raw = get(name, default)
        try:
            return int(default if raw is None or raw == "" else raw)
        except (TypeError, ValueError):
            raise IllegalArgumentError(
                f"{name} must be an integer, got [{raw}]")

    bits = setting("index.search.impact.bits", IMPACT_BITS)
    if bits not in (8, 16):
        raise IllegalArgumentError(
            f"index.search.impact.bits must be 8 or 16, got {bits}")
    block_rows = setting("index.search.impact.block_rows",
                         IMPACT_BLOCK_ROWS)
    if block_rows <= 0 or block_rows & (block_rows - 1):
        raise IllegalArgumentError(
            "index.search.impact.block_rows must be a power of two, "
            f"got {block_rows}")
    max_terms = setting("index.search.impact.max_terms", 64)
    if max_terms < 1:
        raise IllegalArgumentError(
            f"index.search.impact.max_terms must be >= 1, got "
            f"{max_terms}")
    # the packed (Σq·256 + matches) reduction must stay inside int32:
    # the match count needs T ≤ 255 (one byte), and 16-bit impacts need
    # T·65535·256 < 2³¹ → T ≤ 127 (ops/blockmax.impact_scores)
    cap = 127 if bits == 16 else 255
    if max_terms > cap:
        raise IllegalArgumentError(
            f"index.search.impact.max_terms must be <= {cap} at "
            f"{bits}-bit impacts, got {max_terms}")
    return bits, block_rows, max_terms


def configure_impact_plane(index_name: str, settings=None) -> None:
    """Register (or with the setting off, clear) an index's impact-lane
    config from its settings. Called at IndexService construction; tests
    call it directly with a dict. Bad values raise here too
    (validate_impact_settings), but the create-index path validates
    BEFORE the cluster state commits, so the applier never sees them."""
    get = settings.get if settings is not None else (lambda *_: None)
    raw = get("index.search.impact_plane", "false")
    if str(raw).lower() not in ("true", "1"):
        _impact_configs.pop(index_name, None)
        return
    bits, block_rows, max_terms = validate_impact_settings(settings)
    _impact_configs[index_name] = ImpactPlaneConfig(
        bits=bits, block_rows=block_rows, max_terms=max_terms,
        prune=str(get("index.search.impact.prune", "true")).lower()
        in ("true", "1"))


def impact_plane_config(index_name: str | None) -> ImpactPlaneConfig | None:
    if index_name is None:
        return None
    return _impact_configs.get(index_name)


def note_impact_fallback(reason: str) -> None:
    """One impact-lane admission decline (the request proceeds on the
    exact scorer), reason-labeled like note_plane_fallback."""
    lanes.check_reason("impact", reason)
    _attribution.label("impact_fallback", reason)
    with _cache_lock:
        _impact_fallback_reasons[reason] = \
            _impact_fallback_reasons.get(reason, 0) + 1


def note_impact_served(index_name: str | None, n_requests: int,
                       blocks_scored: int, blocks_skipped: int) -> None:
    """`n_requests` served by the impact lane plus its block-sweep work
    accounting (eager-lane requests count every block as scored)."""
    with _cache_lock:
        _bump("impact_admissions", n_requests)
        _bump("impact_blocks_scored", int(blocks_scored))
        _bump("impact_blocks_skipped", int(blocks_skipped))
        if index_name:
            bucket = _impact_index_stats.setdefault(
                index_name, {"admissions": 0, "blocks_scored": 0,
                             "blocks_skipped": 0})
            bucket["admissions"] += n_requests
            bucket["blocks_scored"] += int(blocks_scored)
            bucket["blocks_skipped"] += int(blocks_skipped)


def impact_index_stats(index_name: str) -> dict:
    """One index's impact-lane rollup (zeros when never admitted)."""
    with _cache_lock:
        bucket = dict(_impact_index_stats.get(index_name, {}))
    out = {"admissions": bucket.get("admissions", 0),
           "blocks_scored": bucket.get("blocks_scored", 0),
           "blocks_skipped": bucket.get("blocks_skipped", 0)}
    total = out["blocks_scored"] + out["blocks_skipped"]
    out["skip_ratio"] = round(out["blocks_skipped"] / total, 4) \
        if total else 0.0
    return out


class _ImpactPack:
    """One reader generation's device-resident impact pack for a field:
    per-segment (uterms, qimp, live[, block_max]) device arrays plus the
    host ImpactColumns (term dictionaries + quantization metadata)."""

    __slots__ = ("field", "cfg", "k1", "b", "segs", "bases", "can_prune",
                 "total_blocks", "bound_per_term", "scales",
                 "engine_uuid")

    def __init__(self, field, cfg, k1, b):
        self.field = field
        self.cfg = cfg
        self.k1, self.b = k1, b
        self.segs = []          # dicts per segment (see impact_pack_for)
        self.bases = []
        self.can_prune = True
        self.total_blocks = 0
        self.bound_per_term = 0.0
        self.scales = None      # [S] f32 device constant (compose step)
        self.engine_uuid = None  # cost-table owner (drains on close)

    def sig(self) -> tuple:
        out = [self.field, self.cfg.bits, float(self.k1), float(self.b)]
        for s in self.segs:
            bm = s["block_max"]
            out.append((s["np_docs"], s["u"], str(s["qimp"].dtype),
                        None if bm is None else tuple(bm.shape),
                        s["doc_base"]))
        return tuple(out)


def _impact_global_df(reader, field: str, col) -> "np.ndarray":
    """READER-global df for one segment's term dictionary: the segment's
    own df plus every sibling segment's count for the same term string —
    the cross-segment aggregation the exact scorer does per query term,
    done once per impact build over the whole vocabulary. Vectorized as
    a sorted-terms merge (segment term dictionaries are sorted, see
    TextFieldColumn.terms): O(V log V') numpy per sibling instead of a
    per-term dict-lookup loop, so large vocabularies don't stall the
    refresh path host-side."""
    df = np.asarray(col.df, np.int64).copy()
    if not col.terms:
        return df
    terms = np.asarray(col.terms)
    for other in reader.segments:
        ocol = other.seg.text_fields.get(field)
        if ocol is None or ocol is col or not ocol.terms:
            continue
        oterms = np.asarray(ocol.terms)
        pos = np.minimum(np.searchsorted(oterms, terms),
                         len(oterms) - 1)
        hit = oterms[pos] == terms
        df[hit] += np.asarray(ocol.df, np.int64)[pos[hit]]
    return df


def _host_impact_column(reader, dseg, field: str, cfg: ImpactPlaneConfig,
                        k1: float, b: float, doc_count: int,
                        avgdl: float):
    """The host-side quantized column for one segment, cached ON the
    immutable host Segment (it survives reader swaps, so unchanged
    segments never requantize). A cached column is reused while the
    reader's statistics have drifted less than one quantization step
    from its snapshot; beyond that the segment requantizes against
    fresh statistics (impact_requant_refreshes counts these — the
    tier-1 guard proves steady-state refreshes stay at zero)."""
    from elasticsearch_tpu.index.segment import build_impact_column
    host = dseg.seg
    col = host.text_fields.get(field)
    if col is None:
        return None
    cache = host.__dict__.setdefault("_impact_cache", {})
    ckey = (field, cfg.bits, cfg.block_rows, float(k1), float(b))
    icol = cache.get(ckey)
    if icol is not None:
        # requantize only when the statistics drift could move an
        # impact by more than ONE quantization step (score units) —
        # within a step the error stays inside bound_per_term
        if icol.drift_bound(doc_count, avgdl) <= icol.scale:
            return icol
        with _cache_lock:
            _bump("impact_requant_refreshes")
        quant_gen = icol.quant_gen + 1
    else:
        quant_gen = 0
    icol = build_impact_column(
        col, df=_impact_global_df(reader, field, col),
        doc_count=doc_count, avgdl=avgdl, k1=k1, b=b, bits=cfg.bits,
        block_rows=cfg.block_rows, quant_gen=quant_gen)
    cache[ckey] = icol
    return icol


def impact_pack_for(reader, field: str, cfg: ImpactPlaneConfig,
                    k1: float = 1.2, b: float = 0.75) -> _ImpactPack | None:
    """Build (or fetch the cached) impact pack for one reader generation.

    Device arrays come from the PR 5 per-segment block cache keyed by
    (engine uuid, block_uid, impact signature): unchanged segments reuse
    their resident impact blocks outright — a refresh that adds one
    segment uploads impact bytes only for it (data_layer.impact_bytes_*
    counters prove it). Returns None when no segment carries the field.
    """
    packs = reader.__dict__.setdefault("_impact_packs", {})
    pkey = (field, cfg.bits, cfg.block_rows, float(k1), float(b))
    pack = packs.get(pkey)
    if pack is not None:
        return pack
    st = reader.text_stats(field)
    if st.docs_with_field <= 0:
        return None
    from elasticsearch_tpu.parallel.mesh_engine import (
        fetch_impact_block)
    engine_uuid = getattr(reader, "engine_uuid", None) or \
        f"reader:{id(reader)}"
    breaker_service = getattr(reader, "breaker_service", None)
    pack = _ImpactPack(field, cfg, k1, b)
    pack.engine_uuid = getattr(reader, "engine_uuid", None)
    uploaded = reused = 0
    for dseg in reader.segments:
        icol = _host_impact_column(reader, dseg, field, cfg, k1, b,
                                   st.doc_count, st.avgdl)
        if icol is None:
            continue
        dev_qimp, dev_bm, up, re = fetch_impact_block(
            engine_uuid, dseg.seg.block_uid, field, icol,
            breaker_service)
        uploaded += up
        reused += re
        n_blocks = icol.qimp.shape[0] // icol.block_rows
        pack.segs.append({
            "uterms": _fetch(dseg, dseg.text[field], "uterms"),
            "live": dseg.live,
            "qimp": dev_qimp, "block_max": dev_bm,
            "scale": float(icol.scale), "col": icol,
            "host": dseg.seg.text_fields[field],
            "np_docs": int(icol.qimp.shape[0]),
            "u": int(icol.qimp.shape[1]),
            "doc_base": int(dseg.doc_base),
            "n_blocks": int(n_blocks),
            "block_uid": int(dseg.seg.block_uid),
        })
        pack.bases.append(int(dseg.doc_base))
        pack.total_blocks += int(n_blocks)
        pack.bound_per_term = max(pack.bound_per_term,
                                  icol.bound_per_term)
        if dev_bm is None:
            pack.can_prune = False
    if not pack.segs:
        return None
    note_data_blocks_impact(uploaded, reused)
    # compose step: the pack-level device constants (per-segment dequant
    # scales) the compiled lanes take as inputs — the one device
    # placement the pack itself performs, seamed + span-scoped so the
    # breaker/tracer see it like every other compose
    with device_span("blockmax-compose"):
        device_fault_point("blockmax-compose")
        pack.scales = jnp.asarray([s["scale"] for s in pack.segs],
                                  jnp.float32)
    packs[pkey] = pack
    return pack


def note_data_blocks_impact(uploaded: int, reused: int) -> None:
    """Impact-column block-cache traffic from one pack build."""
    with _cache_lock:
        _data_layer["impact_bytes_uploaded"] += int(uploaded)
        _data_layer["impact_bytes_reused"] += int(reused)


def verify_impact_cursor(pack: _ImpactPack, terms: list, boost: float,
                         search_after) -> tuple | None:
    """Admit a score-order search_after cursor to the impact lane only
    when it was produced by the SAME quantization.

    The lane's in-program continuation compares QUANTIZED scores
    against the cursor score; a cursor minted by the exact scorer
    (page 1 fell back — ineligible batch-mate, breaker open, device
    error) or by a pre-requant quantization differs by up to
    bound_per_term per matched term, which can skip or duplicate hits
    across pages. Provenance is verified by recomputation: the cursor
    doc's quantized score, rebuilt host-side from the pack's resident
    columns (the same integer sum and the same float32
    ``qsum · scale · boost`` arithmetic the compiled lanes run), must
    equal the cursor score bit-for-bit as float32 — true for any cursor
    this lane emitted under the current quant generation, and
    essentially never for an exact-scorer float. Score-only cursors
    (no doc tiebreak) carry nothing to verify against and decline the
    same way.

    Returns the canonical ``(float score, doc id)`` pair to feed the
    compiled continuation, or None → the caller declines admission
    (reason ``cross-lane-cursor``) and the exact scorer serves the
    page."""
    if len(search_after) != 2:
        return None
    doc = int(search_after[1])
    want = np.float32(float(search_after[0]))
    for s in pack.segs:
        base = s["doc_base"]
        if not (base <= doc < base + s["np_docs"]):
            continue
        row = doc - base
        ut = np.asarray(s["host"].uterms[row])
        qi = s["col"].qimp[row].astype(np.int64)
        tidx = s["host"].term_index
        qsum = 0
        for term in terms:
            tid = tidx.get(term, -1)
            if tid >= 0:
                qsum += int(qi[ut == tid].sum())
        scale_boost = np.float32(np.float32(s["scale"]) *
                                 np.float32(boost))
        got = np.float32(np.float32(qsum) * scale_boost)
        return (float(want), doc) if got == want else None
    return None


def _impact_query_inputs(pack: _ImpactPack, term_lists: list,
                         boosts: list, cursors: list):
    """Pack B queries' per-segment term ids / boosts / cursors into the
    lanes' input arrays (batch axis padded to a power of two, term axis
    padded to a shared pow2 bucket so varying term counts share
    programs)."""
    from elasticsearch_tpu.search.batching import pow2_bucket
    b = len(term_lists)
    b_pad = pow2_bucket(b)
    t_pad = pow2_bucket(max(max(len(t) for t in term_lists), 1))
    rows = term_lists + [term_lists[-1]] * (b_pad - b)
    boosts_p = list(boosts) + [boosts[-1]] * (b_pad - b)
    cursors_p = list(cursors) + [cursors[-1]] * (b_pad - b)
    qtids = []
    for s in pack.segs:
        tidx = s["host"].term_index
        arr = np.full((b_pad, t_pad), -1, np.int32)
        for bi, terms in enumerate(rows):
            for ti, term in enumerate(terms):
                arr[bi, ti] = tidx.get(term, -1)
        qtids.append(jnp.asarray(arr))
    cs = jnp.asarray([np.float32(c[0]) if c is not None else
                      np.float32(np.inf) for c in cursors_p])
    cd = jnp.asarray([np.int32(c[1]) if c is not None else -1
                      for c in cursors_p], jnp.int32)
    return qtids, jnp.asarray(boosts_p, jnp.float32), cs, cd, b_pad, t_pad


def run_impact_batch(pack: _ImpactPack, term_lists: list, boosts: list,
                     cursors: list, *, k: int,
                     n_real: int | None = None) -> dict:
    """Eager quantized-impact scoring of B queries over the whole
    reader as ONE compiled program: per-segment dense compare + integer
    gather/sum over the precomputed impacts (no per-doc BM25 float
    math), per-query per-segment top-k, cross-segment merge — the same
    output contract as run_reader_batch's unpacked mode. Counts are
    EXACT (the anyhit mask matches the forward kernel's msm1 mask)."""
    from elasticsearch_tpu.ops import blockmax as bm_ops
    from elasticsearch_tpu.ops import topk as topk_ops
    b = len(term_lists)
    k_static = int(k)
    qtids, boosts_a, cs, cd, b_pad, t_pad = _impact_query_inputs(
        pack, term_lists, boosts, cursors)
    bases = tuple(pack.bases)
    key = ("impact-eager", pack.sig(), k_static, b_pad, t_pad)
    seg_arrs = [(s["uterms"], s["qimp"], s["live"]) for s in pack.segs]

    def compile_fn():
        def run(seg_arrs_in, qtids_in, scales_in, boosts_in, cs_in,
                cd_in):
            ts_list, td_list = [], []
            counts = None
            for i, (ut, qi, lv) in enumerate(seg_arrs_in):
                base = bases[i]

                def one(qt, bo, c1, c2, ut=ut, qi=qi, lv=lv, i=i,
                        base=base):
                    return bm_ops.eager_segment_topk(
                        ut, qi, lv, qt, scales_in[i] * bo, k_static,
                        base, c1, c2)
                ts, td, cnt = jax.vmap(one)(qtids_in[i], boosts_in,
                                            cs_in, cd_in)
                ts_list.append(ts)
                td_list.append(td)
                counts = cnt if counts is None else counts + cnt
            top_s, top_d = topk_ops.merge_top_k_batch_body(
                ts_list, td_list, k_static, bases)
            return {"top_scores": top_s, "top_docs": top_d,
                    "count": counts}

        shapes = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            (seg_arrs, qtids, pack.scales, boosts_a, cs, cd))
        return jax.jit(run).lower(*shapes)

    fn = _get_compiled(key, compile_fn, lane="impact-eager",
                       owner=pack.engine_uuid)
    with device_span("dispatch",
                     cost=("impact-eager", key,
                           n_real if n_real is not None else b, b_pad)):
        device_fault_point("dispatch")
        out = fn(seg_arrs, qtids, pack.scales, boosts_a, cs, cd)
    if b_pad != b:
        out = {name: v[:b] for name, v in out.items()}
    return out


def run_impact_pruned(pack: _ImpactPack, term_lists: list, boosts: list,
                      cursors: list, *, k: int,
                      n_real: int | None = None) -> dict:
    """Block-max pruned top-k of B queries: blocks sweep in descending
    upper-bound order with the running k-th score as the skip threshold,
    carried ACROSS segments so early segments' candidates prune later
    ones (ops/blockmax.pruned_segment_topk). Queries run under lax.map
    so the skip stays a real branch. Returns the eager lane's output
    contract plus per-query ``blocks_scored``/``blocks_skipped``;
    ``count`` is matched docs in SCORED blocks only (a lower bound —
    admission requires track_total_hits=false)."""
    from elasticsearch_tpu.ops import blockmax as bm_ops
    if not pack.can_prune:
        raise ValueError("pack has segments without block maxima")
    b = len(term_lists)
    k_static = int(k)
    qtids, boosts_a, cs, cd, b_pad, t_pad = _impact_query_inputs(
        pack, term_lists, boosts, cursors)
    bases = tuple(pack.bases)
    key = ("impact-pruned", pack.sig(), k_static, b_pad, t_pad)
    seg_arrs = [(s["uterms"], s["qimp"], s["live"], s["block_max"])
                for s in pack.segs]

    def compile_fn():
        def run(seg_arrs_in, qtids_in, scales_in, boosts_in, cs_in,
                cd_in):
            def per_query(args):
                qts, bo, c1, c2 = args
                carry = bm_ops.pruned_carry_init(k_static)
                for i, (ut, qi, lv, bmx) in enumerate(seg_arrs_in):
                    carry = bm_ops.pruned_segment_topk(
                        carry, ut, qi, lv, bmx, qts[i],
                        scales_in[i] * bo, k_static, bases[i], c1, c2)
                ts, td, n_scored, n_skipped, n_matched = carry
                return {"top_scores": ts, "top_docs": td,
                        "count": n_matched, "blocks_scored": n_scored,
                        "blocks_skipped": n_skipped}
            return jax.lax.map(per_query,
                               (tuple(qtids_in), boosts_in, cs_in,
                                cd_in))

        shapes = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            (seg_arrs, qtids, pack.scales, boosts_a, cs, cd))
        return jax.jit(run).lower(*shapes)

    fn = _get_compiled(key, compile_fn, lane="impact-pruned",
                       owner=pack.engine_uuid)
    with device_span("pruning-dispatch",
                     cost=("impact-pruned", key,
                           n_real if n_real is not None else b, b_pad)):
        device_fault_point("pruning-dispatch")
        out = fn(seg_arrs, qtids, pack.scales, boosts_a, cs, cd)
    if b_pad != b:
        out = {name: v[:b] for name, v in out.items()}
    return out


def run_impact_rescore(pack: _ImpactPack, term_lists: list,
                       boosts: list, sec_term_lists: list,
                       sec_boosts: list, windows: list, qws: list,
                       rws: list, score_mode: str, *, k: int,
                       n_real: int | None = None) -> dict:
    """The planner's composed impact→rescore plan as ONE compiled
    dispatch: eager quantized candidate generation (primary top-k over
    the whole reader, k already widened to the largest rescore window),
    per-candidate secondary impact scoring via per-segment row gathers,
    and the QueryRescorer window combine + re-sort — all in-program, so
    a rescore request costs one dispatch instead of a primary dispatch
    plus a host re-rank pass (ops/blockmax.rescore_gather /
    rescore_window hold the kernels and the f32 op-order contract).

    Both stages score in the QUANTIZED domain (the impact lane's
    opt-in semantics): the bit-identity oracle is the sequential
    recompute — run_impact_batch primary, host-side secondary from the
    same columns, host window combine in the same float32 order.
    ``score_mode`` is static (part of the program key); windows /
    query weights are traced per-query inputs, so heterogeneous
    windows share one program."""
    from elasticsearch_tpu.ops import blockmax as bm_ops
    from elasticsearch_tpu.ops import topk as topk_ops
    b = len(term_lists)
    k_static = int(k)
    none_cursors = [None] * b
    qtids, boosts_a, cs, cd, b_pad, t_pad = _impact_query_inputs(
        pack, term_lists, boosts, none_cursors)
    qtids2, boosts2_a, _, _, _, t2_pad = _impact_query_inputs(
        pack, sec_term_lists, sec_boosts, none_cursors)

    def pad_b(vals, dtype):
        vals = list(vals) + [vals[-1]] * (b_pad - b)
        return jnp.asarray(np.asarray(vals, dtype))
    windows_a = pad_b(windows, np.int32)
    qws_a = pad_b(qws, np.float32)
    rws_a = pad_b(rws, np.float32)
    bases = tuple(pack.bases)
    key = ("impact-rescore", pack.sig(), k_static, b_pad, t_pad,
           t2_pad, str(score_mode))
    seg_arrs = [(s["uterms"], s["qimp"], s["live"]) for s in pack.segs]

    def compile_fn():
        def run(seg_arrs_in, qtids_in, scales_in, boosts_in, cs_in,
                cd_in, qtids2_in, boosts2_in, windows_in, qw_in,
                rw_in):
            # stage 1: eager primary candidate generation (identical
            # arithmetic to run_impact_batch — the oracle's stage 1)
            ts_list, td_list = [], []
            counts = None
            for i, (ut, qi, lv) in enumerate(seg_arrs_in):
                base = bases[i]

                def one(qt, bo, c1, c2, ut=ut, qi=qi, lv=lv, i=i,
                        base=base):
                    return bm_ops.eager_segment_topk(
                        ut, qi, lv, qt, scales_in[i] * bo, k_static,
                        base, c1, c2)
                ts, td, cnt = jax.vmap(one)(qtids_in[i], boosts_in,
                                            cs_in, cd_in)
                ts_list.append(ts)
                td_list.append(td)
                counts = cnt if counts is None else counts + cnt
            top_s, top_d = topk_ops.merge_top_k_batch_body(
                ts_list, td_list, k_static, bases)
            # stage 2: secondary scoring of the [B, K] candidates —
            # each segment gathers only ITS candidates' rows; summing
            # per-segment contributions composes the reader-wide score
            sec = jnp.zeros(top_s.shape, jnp.float32)
            hit = jnp.zeros(top_s.shape, bool)
            for i, (ut, qi, lv) in enumerate(seg_arrs_in):
                base = bases[i]

                def sec_one(docs_row, qt2, bo2, ut=ut, qi=qi, i=i,
                            base=base):
                    qsum, h = bm_ops.rescore_gather(ut, qi, docs_row,
                                                    qt2, base)
                    return (qsum.astype(jnp.float32) *
                            (scales_in[i] * bo2), h)
                s_i, h_i = jax.vmap(sec_one)(top_d, qtids2_in[i],
                                             boosts2_in)
                sec = sec + s_i
                hit = hit | h_i
            # stage 3: window combine + re-sort (the _apply_rescore
            # contract: tail keeps ORIGINAL unweighted primary scores)
            new_s, new_d = jax.vmap(
                lambda s_, d_, se, h, w, qw, rw:
                bm_ops.rescore_window(s_, d_, se, h, w, qw, rw,
                                      score_mode)
            )(top_s, top_d, sec, hit, windows_in, qw_in, rw_in)
            return {"top_scores": new_s, "top_docs": new_d,
                    "count": counts}

        shapes = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            (seg_arrs, qtids, pack.scales, boosts_a, cs, cd, qtids2,
             boosts2_a, windows_a, qws_a, rws_a))
        return jax.jit(run).lower(*shapes)

    fn = _get_compiled(key, compile_fn, lane="impact-rescore",
                       owner=pack.engine_uuid)
    with device_span("rescore-dispatch",
                     cost=("impact-rescore", key,
                           n_real if n_real is not None else b, b_pad)):
        device_fault_point("rescore-dispatch")
        out = fn(seg_arrs, qtids, pack.scales, boosts_a, cs, cd,
                 qtids2, boosts2_a, windows_a, qws_a, rws_a)
    if b_pad != b:
        out = {name: v[:b] for name, v in out.items()}
    return out


# ---------------------------------------------------------------------------
# Dense + late-interaction retrieval lane (top-level `knn` search section)
#
# Brute-force exact kNN over HBM-resident vector columns (the sharded
# matmul already beats BM25 QPS on every bench round — ROADMAP item 4),
# fused MaxSim over rank_vectors token matrices (ops/maxsim.py,
# FLASH-MAXSIM-style block accumulation), and IN-PROGRAM hybrid fusion:
# when a request carries both `knn` and `query`, both lanes score in the
# SAME compiled program and reduce on-device via RRF or weighted-sum, so
# a hybrid query is still ONE device dispatch — no second fan-out, no
# host-side merge.
#
# Device residency rides the PR 5 per-segment block cache
# (mesh_engine.fetch_vector_block): a refresh uploads vector bytes only
# for NEW segments, counter-verified via data_layer.vector_bytes_*.
# `index.knn.quantization: int8` stores the columns int8-dense with a
# per-segment scale/offset snapshot (~4x HBM capacity; scores within the
# stamped quantization bound); f32 stays the exact default.
# ---------------------------------------------------------------------------


@_dataclass(frozen=True)
class KnnPlaneConfig:
    """Per-index knn-lane knobs (`index.knn.*` / `index.search.hybrid.*`
    settings). Unlike the impact plane the lane needs no opt-in — the
    `knn` search section itself is the opt-in."""
    quantization: str = "f32"      # f32 | int8
    fusion_mode: str = "rrf"       # rrf | weighted
    rank_constant: int = 60        # RRF k
    lexical_weight: float = 0.5    # weighted-sum lexical leg weight


#: index name → config (indices without an entry use the defaults)
_knn_configs: dict[str, KnnPlaneConfig] = {}


def validate_knn_settings(settings) -> KnnPlaneConfig:
    """Validate the `index.knn.*` / `index.search.hybrid.*` knobs,
    raising the create-index-time 400 on a bad value (the store.type /
    impact-settings idiom: a typo must fail the CREATE REQUEST, never
    reach the cluster-state applier or surface later as a misleading
    device-error fallback)."""
    from elasticsearch_tpu.common.errors import IllegalArgumentError
    get = settings.get if settings is not None else (lambda *_: None)
    quant = str(get("index.knn.quantization", "f32") or "f32").lower()
    if quant not in ("f32", "int8"):
        raise IllegalArgumentError(
            f"index.knn.quantization must be f32 or int8, got [{quant}]")
    mode = str(get("index.search.hybrid.mode", "rrf") or "rrf").lower()
    if mode not in ("rrf", "weighted"):
        raise IllegalArgumentError(
            f"index.search.hybrid.mode must be rrf or weighted, "
            f"got [{mode}]")
    raw_k0 = get("index.search.hybrid.rank_constant", 60)
    try:
        k0 = int(60 if raw_k0 is None or raw_k0 == "" else raw_k0)
    except (TypeError, ValueError):
        raise IllegalArgumentError(
            f"index.search.hybrid.rank_constant must be an integer, "
            f"got [{raw_k0}]") from None
    if k0 < 1:
        raise IllegalArgumentError(
            f"index.search.hybrid.rank_constant must be >= 1, got {k0}")
    raw_w = get("index.search.hybrid.lexical_weight", 0.5)
    try:
        w = float(0.5 if raw_w is None or raw_w == "" else raw_w)
    except (TypeError, ValueError):
        raise IllegalArgumentError(
            f"index.search.hybrid.lexical_weight must be a number, "
            f"got [{raw_w}]") from None
    if not 0.0 <= w <= 1.0:
        raise IllegalArgumentError(
            f"index.search.hybrid.lexical_weight must be in [0, 1], "
            f"got {w}")
    return KnnPlaneConfig(quantization=quant, fusion_mode=mode,
                          rank_constant=k0, lexical_weight=w)


def configure_knn_plane(index_name: str, settings=None) -> None:
    """Register an index's knn-lane config from its settings (called at
    IndexService construction; tests call it directly with a dict)."""
    _knn_configs[index_name] = validate_knn_settings(settings)


def knn_plane_config(index_name: str | None) -> KnnPlaneConfig:
    if index_name is None:
        return KnnPlaneConfig()
    return _knn_configs.get(index_name) or KnnPlaneConfig()


def note_knn_fallback(reason: str) -> None:
    """One knn/hybrid request served by the eager per-segment fallback
    lane instead of the compiled program, reason-labeled."""
    lanes.check_reason("knn", reason)
    _attribution.label("knn_fallback", reason)
    with _cache_lock:
        _knn_fallback_reasons[reason] = \
            _knn_fallback_reasons.get(reason, 0) + 1


def note_percolate_fallback(reason: str) -> None:
    """One fused-percolate dispatch served by the per-query eager lane
    instead (breaker open / device error), reason-labeled like the
    other lanes so the percolator's declines ride the same taxonomy."""
    lanes.check_reason("percolate", reason)
    with _cache_lock:
        _percolate_fallback_reasons[reason] = \
            _percolate_fallback_reasons.get(reason, 0) + 1


def note_scheduler_batch(n_real: int, pad_rows: int = 0) -> None:
    """One continuous-batching scheduler micro-batch launched:
    ``n_real`` queued requests admitted (pad rows counted separately —
    they are no-op replicas, never delivered)."""
    with _cache_lock:
        _bump("scheduler_batches_launched")
        _bump("scheduler_requests_admitted", int(n_real))
        if pad_rows:
            _bump("scheduler_pad_rows", int(pad_rows))


def note_scheduler_drain() -> None:
    """One scheduler batch's device→host drain completed (launched −
    drained = batches in flight, the pipelining evidence)."""
    with _cache_lock:
        _bump("scheduler_batches_drained")


def note_scheduler_shed(reason: str, n: int = 1) -> None:
    """``n`` requests the scheduler shed instead of queueing toward a
    blown deadline / burning SLO, reason-labeled against the closed
    ``scheduler`` vocabulary like the admission lanes. Sheds also land
    on the flight recorder, burst-coalesced, so a 429 storm is
    diagnosable from ``_nodes/diagnostics`` after the fact."""
    lanes.check_reason("scheduler", reason)
    with _cache_lock:
        _bump("scheduler_requests_shed", int(n))
        _scheduler_shed_reasons[reason] = \
            _scheduler_shed_reasons.get(reason, 0) + int(n)
    from elasticsearch_tpu.observability import flightrec
    flightrec.note_shed(reason, int(n))


def note_planner_fallback(reason: str) -> None:
    """One planner admission outcome that left the compiled arms (or
    rerouted the mesh onto a cheaper arm), reason-labeled against the
    closed ``planner`` vocabulary — the taxonomy that replaced the
    pairwise ``impact-preferred``/``knn-lane`` decline edges."""
    lanes.check_reason("planner", reason)
    _attribution.label("planner_fallback", reason)
    with _cache_lock:
        _bump("planner_fallbacks")
        _planner_fallback_reasons[reason] = \
            _planner_fallback_reasons.get(reason, 0) + 1


def note_planner_plan(n_nodes: int, cold: bool = False) -> None:
    """One batch the query planner priced and routed onto a compiled
    arm (``n_nodes`` composed sub-plan nodes rode ONE dispatch);
    ``cold`` marks a plan priced without any measured EWMA — the
    pricing-confidence split the bench's cost-error leg reads."""
    with _cache_lock:
        _bump("planner_plans")
        if cold:
            _bump("planner_cold_plans")
    _attribution.label("plan_nodes", str(int(n_nodes)))


def note_rescore_fused(n: int = 1) -> None:
    """``n`` impact→rescore plans served as one composed device
    dispatch (candidate generation + secondary scoring + window
    re-sort in-program, no second dispatch for the rescore pass)."""
    with _cache_lock:
        _bump("rescore_fused_dispatches", int(n))


def note_watchdog_stall() -> None:
    """One registered device wait outlived its predicted envelope (the
    watchdog flight-recorded a ``dispatch-stall`` for it)."""
    with _cache_lock:
        _bump("watchdog_stalls")


def note_watchdog_abandoned() -> None:
    """One stalled wait the watchdog abandoned — the waiter failed over
    while the wedged thread keeps whatever it holds (non-cancellable)."""
    with _cache_lock:
        _bump("watchdog_abandoned")


def note_watchdog_quarantine() -> None:
    """One quarantine entry: repeated stalls held the breaker open with
    reopen gated on the background probe program."""
    with _cache_lock:
        _bump("watchdog_quarantines")


def note_watchdog_probe_reopen() -> None:
    """One quarantine lifted by a successful background probe program."""
    with _cache_lock:
        _bump("watchdog_probe_reopens")


def run_probe_program(device=None) -> float:
    """The watchdog's tiny quarantine probe: one host→device transfer
    plus one dispatched reduction, routed through the SAME fault seam as
    live traffic (``upload`` then ``dispatch`` fault points), so a
    still-wedged device holds the probe exactly like it held the
    request that tripped quarantine. Blocks until the device answers —
    run it from a disposable thread with a bounded join."""
    a = jnp.arange(8, dtype=jnp.float32)
    buf = seam_device_put(a, device, site="upload")
    with device_span("dispatch"):
        device_fault_point("dispatch")
        return float(jnp.dot(buf, buf))


def note_knn_served(index_name: str | None, n_requests: int,
                    fused: int = 0, maxsim: int = 0) -> None:
    """`n_requests` served by the compiled knn lane; `fused` of them
    were hybrid (one fusion dispatch each — the counter the one-dispatch
    acceptance reconciles against request count), `maxsim` scored a
    rank_vectors field."""
    with _cache_lock:
        _bump("knn_admissions", n_requests)
        if fused:
            _bump("fusion_dispatches", fused)
        if maxsim:
            _bump("maxsim_dispatches", maxsim)
        if index_name:
            bucket = _knn_index_stats.setdefault(
                index_name, {"admissions": 0, "fusion_dispatches": 0,
                             "maxsim_dispatches": 0})
            bucket["admissions"] += n_requests
            bucket["fusion_dispatches"] += fused
            bucket["maxsim_dispatches"] += maxsim


def knn_index_stats(index_name: str) -> dict:
    """One index's knn-lane rollup (zeros when never admitted)."""
    with _cache_lock:
        bucket = dict(_knn_index_stats.get(index_name, {}))
    return {"admissions": bucket.get("admissions", 0),
            "fusion_dispatches": bucket.get("fusion_dispatches", 0),
            "maxsim_dispatches": bucket.get("maxsim_dispatches", 0)}


def note_data_blocks_vector(uploaded: int, reused: int) -> None:
    """Vector-column block-cache traffic from one pack build."""
    with _cache_lock:
        _data_layer["vector_bytes_uploaded"] += int(uploaded)
        _data_layer["vector_bytes_reused"] += int(reused)


def _host_knn_column(host_seg, field: str, quant: str):
    """The host-side knn column for one segment — L2-normalized f32, or
    its int8 quantization — cached ON the immutable host Segment (the
    impact-column discipline: survives reader swaps, so unchanged
    segments never renormalize/requantize). Returns
    (arrays dict, multi: bool, dims) or None when the segment lacks the
    field. Shared by the compiled pack builder and the eager fallback
    lane so both lanes score the same bits."""
    import numpy as _np
    from elasticsearch_tpu.index.segment import quantize_vectors
    col = host_seg.vector_fields.get(field)
    mcol = host_seg.mvector_fields.get(field)
    if col is None and mcol is None:
        return None
    multi = col is None
    cache = host_seg.__dict__.setdefault("_knn_col_cache", {})
    ckey = (field, quant)
    hit = cache.get(ckey)
    if hit is not None:
        return hit
    if multi:
        norms = _np.linalg.norm(mcol.vecs, axis=2, keepdims=True)
        normed = (mcol.vecs / _np.maximum(norms, 1e-12)).astype(
            _np.float32)
        out = {"lens": _np.asarray(mcol.lens, _np.int32),
               "exists": _np.asarray(mcol.exists, bool)}
        dims = mcol.dims
    else:
        norms = _np.linalg.norm(col.vecs, axis=1, keepdims=True)
        normed = (col.vecs / _np.maximum(norms, 1e-12)).astype(
            _np.float32)
        out = {"lens": None, "exists": _np.asarray(col.exists, bool)}
        dims = col.dims
    if quant == "int8":
        qcol = quantize_vectors(normed, dims)
        out.update(vecs=qcol.qvecs, qcol=qcol,
                   scale=qcol.scale, offset=qcol.offset)
    else:
        out.update(vecs=_np.ascontiguousarray(normed), qcol=None,
                   scale=1.0, offset=0.0)
    entry = (out, multi, dims)
    cache[ckey] = entry
    return entry


class _VectorPack:
    """One reader generation's device-resident knn pack for a field:
    per-segment vector arrays (f32 or int8 + scale/offset snapshot)
    riding the per-segment block cache, aligned 1:1 with the reader's
    segments (None entries for segments without the field)."""

    __slots__ = ("field", "quant", "multi", "dims", "segs", "scales",
                 "offsets")

    def __init__(self, field, quant):
        self.field = field
        self.quant = quant
        self.multi = False
        self.dims = 0
        self.segs = []          # per reader segment: dict | None
        self.scales = None      # [S_present] f32 device (compose step)
        self.offsets = None

    def sig(self) -> tuple:
        out = [self.field, self.quant, self.multi, self.dims]
        for s in self.segs:
            if s is None:
                out.append(None)
            else:
                out.append((s["np_docs"], s.get("t", 0),
                            str(s["vecs"].dtype), s["doc_base"]))
        return tuple(out)

    def score_bound(self, qn) -> float:
        """Worst per-segment quantization score bound for one query
        (0.0 under f32) — the stamped int8 recall envelope."""
        bound = 0.0
        for s in self.segs:
            if s is not None and s.get("qcol") is not None:
                bound = max(bound, s["qcol"].score_bound(qn))
        return bound


def vector_pack_for(reader, field: str,
                    cfg: KnnPlaneConfig) -> _VectorPack | None:
    """Build (or fetch the cached) knn vector pack for one reader
    generation. Device arrays come from the PR 5 per-segment block
    cache keyed (engine uuid, block_uid, vector sig): unchanged
    segments reuse their resident vector blocks outright — a refresh
    that adds one segment uploads vector bytes only for it
    (data_layer.vector_bytes_* counters prove it). Returns None when no
    segment carries the field."""
    packs = reader.__dict__.setdefault("_vector_packs", {})
    pkey = (field, cfg.quantization)
    pack = packs.get(pkey)
    if pack is not None:
        return pack
    from elasticsearch_tpu.parallel.mesh_engine import fetch_vector_block
    engine_uuid = getattr(reader, "engine_uuid", None) or \
        f"reader:{id(reader)}"
    breaker_service = getattr(reader, "breaker_service", None)
    pack = _VectorPack(field, cfg.quantization)
    uploaded = reused = 0
    any_field = False
    for dseg in reader.segments:
        entry = _host_knn_column(dseg.seg, field, cfg.quantization)
        if entry is None:
            pack.segs.append(None)
            continue
        host, multi, dims = entry
        any_field = True
        pack.multi = multi
        pack.dims = dims
        arrs, up, re = fetch_vector_block(
            engine_uuid, dseg.seg.block_uid, field,
            (cfg.quantization, multi), lambda h=host: [
                h["vecs"], h["exists"].astype(np.bool_),
                h["lens"]], breaker_service)
        uploaded += up
        reused += re
        dev_vecs, dev_exists = arrs[0], arrs[1]
        dev_lens = arrs[2] if multi else None
        pack.segs.append({
            "vecs": dev_vecs, "exists": dev_exists, "lens": dev_lens,
            "live": dseg.live, "qcol": host["qcol"],
            "scale": float(host["scale"]),
            "offset": float(host["offset"]),
            "np_docs": int(dseg.padded_docs),
            "t": int(host["vecs"].shape[1]) if multi else 0,
            "doc_base": int(dseg.doc_base),
            "block_uid": int(dseg.seg.block_uid),
        })
    if not any_field:
        return None
    note_data_blocks_vector(uploaded, reused)
    # compose step: per-segment dequant scale/offset device constants
    # the compiled lanes take as inputs (seamed + span-scoped like the
    # impact pack's scales)
    present = [s for s in pack.segs if s is not None]
    with device_span("compose"):
        device_fault_point("compose")
        pack.scales = jnp.asarray([s["scale"] for s in present],
                                  jnp.float32)
        pack.offsets = jnp.asarray([s["offset"] for s in present],
                                   jnp.float32)
    packs[pkey] = pack
    return pack


def _rrf_fuse_body(ls, ld, ds, dd, boosts, k0: float, k: int):
    """In-program reciprocal-rank fusion of two candidate rankings.

    ls/ld: lexical (scores, GLOBAL doc ids) [B, C]; ds/dd: knn lane
    [B, C]; boosts: [B] knn-lane contribution multiplier. Each doc's
    fused score is the f32 sum of its per-lane ``1/(k0 + rank + 1)``
    contributions — each lane's lists carry unique docs, so a doc gets
    at most two contributions and the sum is order-exact in f32,
    matching the host fusion oracle bit-for-bit. Final top-k orders by
    (score desc, doc asc) — ops/blockmax.merge_topk_by_doc.

    NOTE: blockmax is imported at MODULE level, deliberately — this
    body runs under an active trace, and a first-import there would
    execute blockmax's module-level jnp constants inside the trace,
    caching foreign tracers into its globals (observed as 'compiled
    for N+3 inputs' failures on concurrent multi-shard searches)."""
    bm_ops = blockmax_ops
    c = ld.shape[1]
    rk = 1.0 / (jnp.float32(k0) + jnp.arange(c, dtype=jnp.float32) + 1.0)
    valid_l = ld >= 0
    valid_d = dd >= 0
    r_l = jnp.where(valid_l, rk[None, :], 0.0)
    r_d = jnp.where(valid_d, rk[None, :] * boosts[:, None], 0.0)
    eq = (ld[:, :, None] == dd[:, None, :]) & valid_l[:, :, None] \
        & valid_d[:, None, :]
    f_l = r_l + (eq * r_d[:, None, :]).sum(axis=2)
    f_d = r_d + (eq * r_l[:, :, None]).sum(axis=1)
    dup_d = eq.any(axis=1)
    s_l = jnp.where(valid_l, f_l, -jnp.inf)
    s_d = jnp.where(valid_d & ~dup_d, f_d, -jnp.inf)
    count = valid_l.sum(axis=1, dtype=jnp.int32) + \
        (valid_d & ~dup_d).sum(axis=1, dtype=jnp.int32)

    def one(sl, dl, sd, dd_):
        return bm_ops.merge_topk_by_doc(sl, dl, sd, dd_, k)
    ts, td = jax.vmap(one)(s_l, ld, s_d, dd)
    return ts, td, count


def _weighted_fuse_body(ls, ld, ds, dd, boosts, w_lex: float, k: int):
    """In-program weighted-sum fusion: each leg min-max-normalizes over
    its candidate list (the models/hybrid.py linear mode), then
    ``w·lex + (1-w)·boost·knn`` sums per doc. (Module-level blockmax
    import: see the note in :func:`_rrf_fuse_body`.)"""
    bm_ops = blockmax_ops
    valid_l = ld >= 0
    valid_d = dd >= 0

    def norm(s, valid):
        lo = jnp.where(valid, s, jnp.inf).min(axis=1, keepdims=True)
        hi = jnp.where(valid, s, -jnp.inf).max(axis=1, keepdims=True)
        rng = hi - lo
        rng = jnp.where((rng > 0) & jnp.isfinite(rng), rng, 1.0)
        lo = jnp.where(jnp.isfinite(lo), lo, 0.0)
        return jnp.where(valid, (s - lo) / rng, 0.0)
    r_l = jnp.float32(w_lex) * norm(ls, valid_l)
    r_d = (1.0 - jnp.float32(w_lex)) * boosts[:, None] * norm(ds, valid_d)
    eq = (ld[:, :, None] == dd[:, None, :]) & valid_l[:, :, None] \
        & valid_d[:, None, :]
    f_l = r_l + (eq * r_d[:, None, :]).sum(axis=2)
    f_d = r_d + (eq * r_l[:, :, None]).sum(axis=1)
    dup_d = eq.any(axis=1)
    s_l = jnp.where(valid_l, f_l, -jnp.inf)
    s_d = jnp.where(valid_d & ~dup_d, f_d, -jnp.inf)
    count = valid_l.sum(axis=1, dtype=jnp.int32) + \
        (valid_d & ~dup_d).sum(axis=1, dtype=jnp.int32)

    def one(sl, dl, sd, dd_):
        return bm_ops.merge_topk_by_doc(sl, dl, sd, dd_, k)
    ts, td = jax.vmap(one)(s_l, ld, s_d, dd)
    return ts, td, count


def _plan_knn_segment(dseg, ctx, reqs):
    """Resolve one segment's per-request lexical query (hybrid) and knn
    filter into emit closures + packed constants. → plan dict or None
    when the requests do not share one plan signature."""
    sig0 = None
    emit_q0 = emit_f0 = None
    pos_for: frozenset = frozenset()
    vecs_for: frozenset = frozenset()
    consts_rows = []
    for req in reqs:
        ct = ConstTable()
        resolver = SegmentResolver(dseg, ctx, ct)
        knn = req.knn
        emit_q = resolver.resolve(req.query) if knn.hybrid else None
        emit_f = resolver.resolve_mask(knn.filter) \
            if knn.filter is not None else None
        ct.static("knn-lane", knn.hybrid, knn.filter is not None)
        sig = ct.signature()
        if sig0 is None:
            sig0, emit_q0, emit_f0 = sig, emit_q, emit_f
            pos_for = frozenset(ct.positions_needed)
            vecs_for = frozenset(ct.vectors_needed)
        elif sig != sig0:
            return None
        consts_rows.append(ct.values)
    packed_spec = pack_query_consts(consts_rows)
    if packed_spec is None:
        specs, packed, b_pad = (), {}, None    # const-free plans
    else:
        specs, packed, b_pad = packed_spec
    return {
        "seg": dseg, "sig": sig0, "emit_q": emit_q0, "emit_f": emit_f0,
        "specs": specs, "packed": packed, "b_pad": b_pad,
        "pos": pos_for, "vecs": vecs_for,
        "flat": seg_flatten(dseg, pos_for, vecs_for),
        "key": (sig0, layout_key(dseg), pos_for, vecs_for),
    }


def _knn_query_inputs(reqs, pack):
    """Stack B requests' query vectors / boosts on a padded batch axis.
    → (qv, qmask | None, boosts, b_pad). Dense: qv [B_pad, D] f32
    row-normalized. Multi (rank_vectors): qv [B_pad, Qt_pad, D] with
    per-token normalization and qmask [B_pad, Qt_pad]."""
    from elasticsearch_tpu.search.batching import pow2_bucket
    b = len(reqs)
    b_pad = pow2_bucket(b)
    rows = [req.knn for req in reqs]
    rows = rows + [rows[-1]] * (b_pad - b)
    boosts = np.asarray([kn.boost for kn in rows], np.float32)
    if not pack.multi:
        qv = np.zeros((b_pad, pack.dims), np.float32)
        for i, kn in enumerate(rows):
            v = np.asarray(kn.query_vector, np.float32)
            qv[i] = v / max(float(np.linalg.norm(v)), 1e-12)
        return jnp.asarray(qv), None, jnp.asarray(boosts), b_pad
    qt_pad = pow2_bucket(max(
        max(len(kn.query_vector) for kn in rows), 1))
    qv = np.zeros((b_pad, qt_pad, pack.dims), np.float32)
    qmask = np.zeros((b_pad, qt_pad), bool)
    for i, kn in enumerate(rows):
        m = np.asarray(kn.query_vector, np.float32)
        norms = np.linalg.norm(m, axis=1, keepdims=True)
        qv[i, :m.shape[0]] = m / np.maximum(norms, 1e-12)
        qmask[i, :m.shape[0]] = True
    return jnp.asarray(qv), jnp.asarray(qmask), jnp.asarray(boosts), b_pad


def run_knn_hybrid_batch(reader, ctx, reqs, pack: _VectorPack,
                         cfg: KnnPlaneConfig, *, k: int,
                         num_candidates: int, n_real: int | None = None):
    """B knn (or hybrid BM25+knn) requests over the whole reader as ONE
    compiled program.

    Per segment: the knn lane scores the vector column (dense cosine
    matmul, int8-dequantized matmul, or fused MaxSim over rank_vectors)
    masked by exists ∧ live ∧ the request's `filter`; a hybrid request's
    lexical lane scores the SAME segment view through the standard emit
    closures under the same vmap. Each lane keeps its global
    top-`num_candidates` (per-segment top-C, cross-segment device
    merge), and hybrid requests reduce the two rankings on-device via
    RRF (`rank_constant`) or weighted-sum — the whole thing is one
    dispatch and one device→host fetch.

    Returns {"top_scores" [B, k], "top_docs" [B, k], "count" [B]} or
    None when the batch is not homogeneous (mixed plan signatures —
    callers retry per-request)."""
    from elasticsearch_tpu.ops import maxsim as maxsim_ops
    from elasticsearch_tpu.ops import vector as vector_ops
    segments = reader.segments
    if not segments or not reqs:
        return None
    hybrid = reqs[0].knn.hybrid
    b = len(reqs)
    k_static = int(k)
    c_static = int(num_candidates)
    need_seg = hybrid or any(r.knn.filter is not None for r in reqs)
    plans = None
    if need_seg:
        plans = []
        for dseg in segments:
            plan = _plan_knn_segment(dseg, ctx, reqs)
            if plan is None:
                return None
            plans.append(plan)
    qv, qmask, boosts, b_pad = _knn_query_inputs(reqs, pack)
    if need_seg:
        # const rows pad to the SAME bucket as the query vectors
        for plan in plans:
            if plan["b_pad"] is not None and plan["b_pad"] != b_pad:
                return None
    bases = tuple(int(s.doc_base) for s in segments)
    vec_bases = tuple(s["doc_base"] for s in pack.segs if s is not None)
    fusion_key = (cfg.fusion_mode, int(cfg.rank_constant),
                  float(cfg.lexical_weight)) if hybrid else None
    key = ("knn", pack.sig(), hybrid, need_seg, bases, k_static,
           c_static, b_pad,
           None if qmask is None else tuple(qmask.shape), fusion_key,
           tuple(p["key"] for p in plans) if need_seg else None,
           tuple(tuple(p["specs"]) for p in plans) if need_seg else None)
    flats = [p["flat"] for p in plans] if need_seg else []
    packeds = [{dt: jnp.asarray(buf) for dt, buf in p["packed"].items()}
               for p in plans] if need_seg else []
    vec_arrs = [() if s is None else
                ((s["vecs"], s["exists"], s["live"]) if not pack.multi
                 else (s["vecs"], s["exists"], s["live"], s["lens"]))
                for s in pack.segs]

    def compile_fn():
        def run(flats_in, packeds_in, vec_in, scales_in, offsets_in,
                qv_in, qmask_in, boosts_in):
            # ---- per-segment lexical scores / filter masks ----------
            lex_ts, lex_td = [], []
            fmasks = [None] * len(segments)
            if need_seg:
                for i, (plan, flat_in, packed_in) in enumerate(
                        zip(plans, flats_in, packeds_in)):
                    view = seg_rebuild(plan["seg"], flat_in,
                                       plan["pos"], plan["vecs"])

                    def lane(packed_one, plan=plan, view=view):
                        consts_one = [
                            packed_one[dt][off:off + size].reshape(shape)
                            for dt, off, shape, size in plan["specs"]]
                        em = EmitCtx(view, consts_one)
                        out = {}
                        if plan["emit_q"] is not None:
                            scores, mask = plan["emit_q"](em)
                            mask = mask & view.live
                            ts, td = topk_ops.top_k(
                                scores, mask,
                                min(c_static, view.padded_docs), 0)
                            out["ts"], out["td"] = ts, td
                        if plan["emit_f"] is not None:
                            out["fmask"] = plan["emit_f"](em)
                        return out

                    if plan["specs"]:
                        outs = jax.vmap(lane)(packed_in)
                    else:
                        # const-free plans: every request is the same
                        # program — run once, broadcast the batch axis
                        one = lane({})
                        outs = {kk: jnp.broadcast_to(
                            v, (b_pad,) + v.shape)
                            for kk, v in one.items()}
                    if hybrid:
                        lex_ts.append(outs["ts"])
                        lex_td.append(outs["td"])
                    if "fmask" in outs:
                        fmasks[i] = outs["fmask"]
            # ---- per-segment knn candidates -------------------------
            knn_ts, knn_td = [], []
            knn_counts = jnp.zeros(b_pad, jnp.int32)
            vi = 0
            for i, arrs in enumerate(vec_in):
                if not arrs:
                    continue
                if pack.multi:
                    vecs, exists, live, lens = arrs
                else:
                    vecs, exists, live = arrs
                if pack.multi and pack.quant == "int8":
                    scores = maxsim_ops.maxsim_scores_int8_batch_body(
                        vecs, scales_in[vi], offsets_in[vi], lens,
                        qv_in, qmask_in)
                elif pack.multi:
                    scores = maxsim_ops.maxsim_scores_batch_body(
                        vecs, lens, qv_in, qmask_in)
                elif pack.quant == "int8":
                    scores = vector_ops.cosine_scores_int8_batch(
                        vecs, scales_in[vi], offsets_in[vi], exists,
                        qv_in)
                else:
                    scores = jnp.where(exists[None, :],
                                       qv_in @ vecs.T, 0.0)
                if not hybrid:
                    # knn-only: the section boost scales the reported
                    # scores (rank-preserving — boost > 0 validated)
                    scores = scores * boosts_in[:, None]
                elig = exists & live
                masks = jnp.broadcast_to(elig[None, :],
                                         (b_pad, elig.shape[0]))
                if fmasks[i] is not None:
                    masks = masks & fmasks[i]
                ts, td = vector_ops.filtered_topk_batch(
                    scores, masks, min(c_static, elig.shape[0]), 0)
                knn_ts.append(ts)
                knn_td.append(td)
                knn_counts = knn_counts + masks.sum(axis=1,
                                                    dtype=jnp.int32)
                vi += 1
            ds, dd = topk_ops.merge_top_k_batch_body(
                knn_ts, knn_td, c_static, vec_bases)
            if not hybrid:
                ts, td = ds[:, :k_static], dd[:, :k_static]
                return {"top_scores": ts, "top_docs": td,
                        "count": knn_counts}
            ls, ld = topk_ops.merge_top_k_batch_body(
                lex_ts, lex_td, c_static, bases)
            if cfg.fusion_mode == "weighted":
                ts, td, count = _weighted_fuse_body(
                    ls, ld, ds, dd, boosts_in,
                    float(cfg.lexical_weight), k_static)
            else:
                ts, td, count = _rrf_fuse_body(
                    ls, ld, ds, dd, boosts_in,
                    float(cfg.rank_constant), k_static)
            return {"top_scores": ts, "top_docs": td, "count": count}

        args = (flats, packeds, vec_arrs, pack.scales, pack.offsets,
                qv, qmask if qmask is not None else jnp.zeros(0, bool),
                boosts)
        shapes = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), args)
        def run_outer(*a):
            return run(a[0], a[1], a[2], a[3], a[4], a[5],
                       a[6] if qmask is not None else None, a[7])
        return jax.jit(run_outer).lower(*shapes)

    fn = _get_compiled(key, compile_fn, lane="knn",
                       owner=getattr(reader, "engine_uuid", None))
    args = (flats, packeds, vec_arrs, pack.scales, pack.offsets,
            qv, qmask if qmask is not None else jnp.zeros(0, bool),
            boosts)
    cost = ("knn", key, n_real if n_real is not None else b, b_pad)
    if hybrid:
        with device_span("fusion-dispatch", cost=cost):
            device_fault_point("fusion-dispatch")
            out = fn(*args)
    elif pack.multi:
        with device_span("maxsim-dispatch", cost=cost):
            device_fault_point("maxsim-dispatch")
            out = fn(*args)
    else:
        with device_span("dispatch", cost=cost):
            device_fault_point("dispatch")
            out = fn(*args)
    if b_pad != b:
        out = {name: v[:b] for name, v in out.items()}
    return out


# ---------------------------------------------------------------------------
# Mesh-sharded retrieval lanes: the impact and knn/hybrid lanes served by
# a pod slice as ONE compiled shard_map program.
#
# Partitioning: each segment's impact rows / block-max tables / vector
# columns are doc-axis sharded over the mesh's ``shard`` axis through the
# placement-aware block cache (mesh_engine.fetch_placed_block — blocks
# pinned to owning devices, refresh deltas routed to the owner only),
# while the query batch shards over ``dp``. In-program, each shard runs
# the SAME per-segment kernels the single-chip lanes run on its local
# rows, then the per-shard top-k candidate lists (GLOBAL doc ids)
# all_gather over ICI and re-select under the identical
# (score desc, doc asc) order — so mesh-served results are bit-identical
# to the single-chip lanes (tests/test_mesh_lanes.py fuzzes the
# equivalence across geometries, delete churn and refresh). The pruned
# sweep additionally exchanges the running k-th score across chips
# (ops/blockmax.pruned_segment_topk_mesh's θ-exchange rounds) so
# cross-chip pruning stays conservative.
#
# The serving mesh is an OPT-IN module hook (set_serving_mesh): when no
# mesh is installed every production path is byte-for-byte the
# single-chip lane — the hook gates phase routing, scheduler shape keys
# and planner pricing.
# ---------------------------------------------------------------------------

_serving_mesh = None


def set_serving_mesh(mesh) -> None:
    """Install (or with None, remove) the pod-slice serving mesh the
    retrieval lanes shard over. Returns nothing; callers own clearing
    the program cache when they swap geometries mid-process (the
    program keys carry the geometry, so stale entries are merely
    unused, never wrong)."""
    global _serving_mesh
    _serving_mesh = mesh


def serving_mesh():
    """The installed serving mesh, or None (single-chip serving)."""
    return _serving_mesh


def mesh_geom(mesh) -> tuple:
    """The geometry component mesh-lane program keys and scheduler
    shape buckets carry: axis sizes + flat device ids, so the same
    request shape on two geometries compiles (at most) twice and never
    aliases across device re-enumeration."""
    return (tuple(sorted((str(k), int(v))
                         for k, v in mesh.shape.items())),
            tuple(int(d.id) for d in mesh.devices.flat))


def note_data_blocks_placed(uploaded: int, reused: int) -> None:
    """Placed-block (mesh-lane) cache traffic from one lane build."""
    with _cache_lock:
        _data_layer["placement_bytes_uploaded"] += int(uploaded)
        _data_layer["placement_bytes_reused"] += int(reused)


def _pad_batch_rows(arrs: list, b_new: int) -> list:
    """Pad each array's leading (batch) axis to ``b_new`` by repeating
    the last row — the dp-divisibility companion of the pow2 batch
    bucket (padded rows are trimmed from the output like pad rows)."""
    out = []
    for a in arrs:
        extra = b_new - a.shape[0]
        out.append(a if extra == 0 else
                   jnp.concatenate([a, jnp.repeat(a[-1:], extra,
                                                  axis=0)]))
    return out


def _mesh_place(tree, mesh, spec, kind: str):
    """Commit query-side operands to the serving mesh (dp-sharded batch
    consts or replicated scalars) under the plane's upload seam, so
    chaos injection and the tracer see the transfer like every other
    host→device move."""
    from jax.sharding import NamedSharding
    sh = NamedSharding(mesh, spec)
    leaves = jax.tree.leaves(tree)
    with device_span("upload") as dsp:
        device_fault_point("upload")
        out = jax.tree.map(lambda a: jax.device_put(a, sh), tree)
        dsp.set(bytes=int(sum(int(a.nbytes) for a in leaves)),
                kind=kind)
    return out


def _placed_impact_arrays(reader, pack: _ImpactPack, mesh) -> list:
    """Per-segment placed (uterms, qimp, live[, block_max]) device
    arrays for the mesh impact lane: rows pad to a whole number of
    blocks per shard (appended blocks carry all-zero block_max rows →
    never swept; pad rows are uterms=-1/live=False → never match), then
    pin to owning devices through the placement-aware block cache. A
    refresh re-ships only the shard slices that changed (the
    placement_bytes_* counters prove it)."""
    from elasticsearch_tpu.parallel.mesh_engine import fetch_placed_block
    s_axis = int(mesh.shape["shard"])
    engine_uuid = getattr(reader, "engine_uuid", None) or \
        f"reader:{id(reader)}"
    breaker_service = getattr(reader, "breaker_service", None)
    seg_arrs = []
    uploaded = reused = 0
    for s in pack.segs:
        icol = s["col"]
        n_blocks = s["n_blocks"]
        r = s["np_docs"] // n_blocks
        nb_pad = -(-n_blocks // s_axis) * s_axis
        rows_pad = nb_pad * r
        has_bm = s["block_max"] is not None

        def build(s=s, nb_pad=nb_pad, rows_pad=rows_pad,
                  n_blocks=n_blocks, has_bm=has_bm):
            pad = rows_pad - s["np_docs"]
            ut = np.pad(np.asarray(s["uterms"]), ((0, pad), (0, 0)),
                        constant_values=-1)
            qi = np.pad(np.asarray(s["qimp"]), ((0, pad), (0, 0)))
            lv = np.pad(np.asarray(s["live"]), (0, pad))
            out = [ut, qi, lv]
            if has_bm:
                out.append(np.pad(np.asarray(s["block_max"]),
                                  ((0, nb_pad - n_blocks), (0, 0))))
            return out

        sig = ("impact-mesh", pack.field, pack.cfg.bits,
               icol.block_rows, icol.quant_gen, has_bm, nb_pad)
        arrs, up, re = fetch_placed_block(
            mesh, engine_uuid, s["block_uid"], sig, build,
            breaker_service, component="impact")
        seg_arrs.append(tuple(arrs))
        uploaded += up
        reused += re
    note_data_blocks_placed(uploaded, reused)
    return seg_arrs


def run_impact_mesh(reader, pack: _ImpactPack, mesh, term_lists: list,
                    boosts: list, cursors: list, *, k: int,
                    prune: bool = False,
                    n_real: int | None = None) -> dict:
    """The impact lane served by the pod slice as ONE compiled
    shard_map dispatch: impact columns and block-max tables doc-axis
    sharded over ``shard``, the query batch over ``dp``; per-shard
    sweeps (eager, or block-max pruned with cross-chip θ-exchange),
    then an in-program all_gather + re-top-k merge. Output contract
    and bits match run_impact_batch / run_impact_pruned exactly —
    except the pruned lane's blocks_scored/blocks_skipped, which
    depend on how much the exchanged θ pruned (counts stay exact
    partitions for the eager lane, psum'd)."""
    from jax.sharding import PartitionSpec as P
    from elasticsearch_tpu.parallel.mesh import shard_map_compat
    if prune and not pack.can_prune:
        raise ValueError("pack has segments without block maxima")
    b = len(term_lists)
    k_static = int(k)
    dp = int(mesh.shape["dp"])
    qtids, boosts_a, cs, cd, b_pad, t_pad = _impact_query_inputs(
        pack, term_lists, boosts, cursors)
    b_pad_m = -(-b_pad // dp) * dp
    if b_pad_m != b_pad:
        qtids = _pad_batch_rows(qtids, b_pad_m)
        boosts_a, cs, cd = _pad_batch_rows([boosts_a, cs, cd], b_pad_m)
        b_pad = b_pad_m
    placed = _placed_impact_arrays(reader, pack, mesh)
    seg_arrs = tuple(a if prune else a[:3] for a in placed)
    bases = tuple(pack.bases)
    geom = mesh_geom(mesh)
    key = ("impact-mesh", pack.sig(), k_static, b_pad, t_pad,
           bool(prune), geom)
    qtids = _mesh_place(qtids, mesh, P("dp"), "mesh-query-consts")
    boosts_a, cs, cd = _mesh_place([boosts_a, cs, cd], mesh, P("dp"),
                                   "mesh-query-consts")
    scales = _mesh_place(pack.scales, mesh, P(), "mesh-scales")

    def compile_fn():
        def step_local(seg_in, qtids_in, scales_in, boosts_in, cs_in,
                       cd_in):
            sidx = jax.lax.axis_index("shard")
            if prune:
                def per_query(args):
                    qts, bo, c1, c2 = args
                    carry = blockmax_ops.pruned_carry_init(k_static)
                    for i, (ut, qi, lv, bmx) in enumerate(seg_in):
                        base = bases[i] + sidx * ut.shape[0]
                        carry = blockmax_ops.pruned_segment_topk_mesh(
                            carry, ut, qi, lv, bmx, qts[i],
                            scales_in[i] * bo, k_static, base, c1, c2)
                    return carry
                ts, td, n_scored, n_skipped, n_matched = jax.lax.map(
                    per_query,
                    (tuple(qtids_in), boosts_in, cs_in, cd_in))
                out = {"count": jax.lax.psum(n_matched, "shard"),
                       "blocks_scored": jax.lax.psum(n_scored, "shard"),
                       "blocks_skipped": jax.lax.psum(n_skipped,
                                                      "shard")}
            else:
                ts_list, td_list, base_list = [], [], []
                counts = None
                for i, (ut, qi, lv) in enumerate(seg_in):
                    base = bases[i] + sidx * ut.shape[0]

                    def one(qt, bo, c1, c2, ut=ut, qi=qi, lv=lv, i=i,
                            base=base):
                        return blockmax_ops.eager_segment_topk(
                            ut, qi, lv, qt, scales_in[i] * bo,
                            k_static, base, c1, c2)
                    s_i, d_i, cnt = jax.vmap(one)(qtids_in[i],
                                                  boosts_in, cs_in,
                                                  cd_in)
                    ts_list.append(s_i)
                    td_list.append(d_i)
                    base_list.append(base)
                    counts = cnt if counts is None else counts + cnt
                ts, td = topk_ops.merge_top_k_batch_body(
                    ts_list, td_list, k_static, tuple(base_list))
                out = {"count": jax.lax.psum(counts, "shard")}
            # cross-chip merge: gather every shard's candidate list
            # (GLOBAL doc ids) over ICI and re-select under the same
            # (score desc, doc asc) order — bit-identical to 1-chip
            # because a global-top-k doc is always in its own shard's
            # local top-k
            ag_s = jax.lax.all_gather(ts, "shard")
            ag_d = jax.lax.all_gather(td, "shard")
            bl = ts.shape[0]
            flat_s = jnp.moveaxis(ag_s, 0, 1).reshape(bl, -1)
            flat_d = jnp.moveaxis(ag_d, 0, 1).reshape(bl, -1)

            def refine(s_row, d_row):
                return blockmax_ops.topk_flat_by_doc(s_row, d_row,
                                                     k_static)
            out["top_scores"], out["top_docs"] = jax.vmap(refine)(
                flat_s, flat_d)
            return out

        seg_specs = tuple(tuple(P("shard") for _ in arrs)
                          for arrs in seg_arrs)
        out_specs = {"top_scores": P("dp"), "top_docs": P("dp"),
                     "count": P("dp")}
        if prune:
            out_specs["blocks_scored"] = P("dp")
            out_specs["blocks_skipped"] = P("dp")
        mapped = shard_map_compat(
            step_local, mesh=mesh,
            in_specs=(seg_specs, [P("dp")] * len(qtids), P(),
                      P("dp"), P("dp"), P("dp")),
            out_specs=out_specs)
        return jax.jit(mapped).lower(seg_arrs, qtids, scales,
                                     boosts_a, cs, cd)

    fn = _get_compiled(key, compile_fn, lane="impact-mesh",
                       owner=pack.engine_uuid)
    with device_span("impact-shard-dispatch",
                     cost=("impact-mesh", key,
                           n_real if n_real is not None else b, b_pad)):
        device_fault_point("impact-shard-dispatch")
        out = fn(seg_arrs, qtids, scales, boosts_a, cs, cd)
    if b_pad != b:
        out = {name: v[:b] for name, v in out.items()}
    return out


def _placed_vector_arrays(reader, pack: _VectorPack, mesh) -> list:
    """Per-segment placed (vecs, exists, live[, lens]) device arrays
    for the mesh knn lane — doc axis padded to the shard count (pad
    rows exists=False/live=False → never eligible) and pinned to owning
    devices through the placement-aware block cache. Aligned 1:1 with
    pack.segs (() entries for segments without the field)."""
    from elasticsearch_tpu.parallel.mesh_engine import fetch_placed_block
    s_axis = int(mesh.shape["shard"])
    engine_uuid = getattr(reader, "engine_uuid", None) or \
        f"reader:{id(reader)}"
    breaker_service = getattr(reader, "breaker_service", None)
    placed = []
    uploaded = reused = 0
    for s in pack.segs:
        if s is None:
            placed.append(())
            continue
        np_pad = -(-s["np_docs"] // s_axis) * s_axis

        def build(s=s, np_pad=np_pad):
            pad = np_pad - s["np_docs"]
            vecs = np.asarray(s["vecs"])
            out = [np.pad(vecs,
                          ((0, pad),) + ((0, 0),) * (vecs.ndim - 1)),
                   np.pad(np.asarray(s["exists"]), (0, pad)),
                   np.pad(np.asarray(s["live"]), (0, pad))]
            if s["lens"] is not None:
                out.append(np.pad(np.asarray(s["lens"]), (0, pad)))
            return out

        sig = ("knn-mesh", pack.field, pack.quant, pack.multi, np_pad)
        arrs, up, re = fetch_placed_block(
            mesh, engine_uuid, s["block_uid"], sig, build,
            breaker_service, component="vector")
        placed.append(tuple(arrs))
        uploaded += up
        reused += re
    note_data_blocks_placed(uploaded, reused)
    return placed


def run_knn_hybrid_mesh(reader, ctx, reqs, pack: _VectorPack,
                        cfg: KnnPlaneConfig, mesh, *, k: int,
                        num_candidates: int,
                        n_real: int | None = None):
    """The knn/hybrid lane served by the pod slice as ONE compiled
    shard_map dispatch: vector/token columns doc-axis sharded over
    ``shard`` (per-doc scoring is row-independent, so per-shard scores
    are bit-identical to the full-column pass), per-shard
    top-num_candidates, then an in-program cross-chip all_gather +
    re-top-k BEFORE fusion. A hybrid request's lexical side runs
    replicated on every shard (full segment columns — identical on all
    shards), so RRF / weighted fusion computes replicated from the
    merged global candidate lists and bit-matches run_knn_hybrid_batch.
    Returns the single-chip lane's contract, or None on mixed plan
    signatures (callers retry per-request)."""
    from jax.sharding import PartitionSpec as P
    from elasticsearch_tpu.ops import maxsim as maxsim_ops
    from elasticsearch_tpu.ops import vector as vector_ops
    from elasticsearch_tpu.parallel.mesh import shard_map_compat
    segments = reader.segments
    if not segments or not reqs:
        return None
    hybrid = reqs[0].knn.hybrid
    b = len(reqs)
    k_static = int(k)
    c_static = int(num_candidates)
    dp = int(mesh.shape["dp"])
    s_axis = int(mesh.shape["shard"])
    need_seg = hybrid or any(r.knn.filter is not None for r in reqs)
    plans = None
    if need_seg:
        plans = []
        for dseg in segments:
            plan = _plan_knn_segment(dseg, ctx, reqs)
            if plan is None:
                return None
            plans.append(plan)
    qv, qmask, boosts, b_pad = _knn_query_inputs(reqs, pack)
    if need_seg:
        for plan in plans:
            if plan["b_pad"] is not None and plan["b_pad"] != b_pad:
                return None
    packeds = [{dt: jnp.asarray(buf) for dt, buf in p["packed"].items()}
               for p in plans] if need_seg else []
    b_pad_m = -(-b_pad // dp) * dp
    if b_pad_m != b_pad:
        qv, boosts = _pad_batch_rows([qv, boosts], b_pad_m)
        if qmask is not None:
            (qmask,) = _pad_batch_rows([qmask], b_pad_m)
        packeds = [{dt: _pad_batch_rows([buf], b_pad_m)[0]
                    for dt, buf in pk.items()} for pk in packeds]
        b_pad = b_pad_m
    placed = _placed_vector_arrays(reader, pack, mesh)
    bases = tuple(int(s.doc_base) for s in segments)
    vec_bases = tuple(s["doc_base"] for s in pack.segs if s is not None)
    fusion_key = (cfg.fusion_mode, int(cfg.rank_constant),
                  float(cfg.lexical_weight)) if hybrid else None
    geom = mesh_geom(mesh)
    key = ("knn-mesh", pack.sig(), hybrid, need_seg, bases, k_static,
           c_static, b_pad,
           None if qmask is None else tuple(qmask.shape), fusion_key,
           tuple(p["key"] for p in plans) if need_seg else None,
           tuple(tuple(p["specs"]) for p in plans) if need_seg else None,
           geom)
    flats = [p["flat"] for p in plans] if need_seg else []
    # lexical columns serve REPLICATED (every shard scores the full
    # segment — the lexical candidate lists must be global); the vector
    # columns are the sharded half
    flats = _mesh_place(flats, mesh, P(), "mesh-lexical-replicate")
    packeds = _mesh_place(packeds, mesh, P("dp"), "mesh-query-consts")
    qv, boosts = _mesh_place([qv, boosts], mesh, P("dp"),
                             "mesh-query-consts")
    if qmask is not None:
        (qmask,) = _mesh_place([qmask], mesh, P("dp"),
                               "mesh-query-consts")
    scales, offsets = _mesh_place([pack.scales, pack.offsets], mesh,
                                  P(), "mesh-scales")

    def compile_fn():
        def step_local(flats_in, packeds_in, vec_in, scales_in,
                       offsets_in, qv_in, qmask_in, boosts_in):
            sidx = jax.lax.axis_index("shard")
            bl = qv_in.shape[0]
            # ---- lexical scores / filter masks (replicated) ---------
            lex_ts, lex_td = [], []
            fmasks = [None] * len(segments)
            if need_seg:
                for i, (plan, flat_in, packed_in) in enumerate(
                        zip(plans, flats_in, packeds_in)):
                    view = seg_rebuild(plan["seg"], flat_in,
                                       plan["pos"], plan["vecs"])

                    def lane(packed_one, plan=plan, view=view):
                        consts_one = [
                            packed_one[dt][off:off + size].reshape(shape)
                            for dt, off, shape, size in plan["specs"]]
                        em = EmitCtx(view, consts_one)
                        out = {}
                        if plan["emit_q"] is not None:
                            scores, mask = plan["emit_q"](em)
                            mask = mask & view.live
                            ts, td = topk_ops.top_k(
                                scores, mask,
                                min(c_static, view.padded_docs), 0)
                            out["ts"], out["td"] = ts, td
                        if plan["emit_f"] is not None:
                            out["fmask"] = plan["emit_f"](em)
                        return out

                    if plan["specs"]:
                        outs = jax.vmap(lane)(packed_in)
                    else:
                        one = lane({})
                        outs = {kk: jnp.broadcast_to(
                            v, (bl,) + v.shape)
                            for kk, v in one.items()}
                    if hybrid:
                        lex_ts.append(outs["ts"])
                        lex_td.append(outs["td"])
                    if "fmask" in outs:
                        fmasks[i] = outs["fmask"]
            # ---- per-shard knn candidates ---------------------------
            knn_ts, knn_td = [], []
            knn_counts = jnp.zeros(bl, jnp.int32)
            vi = 0
            for i, arrs in enumerate(vec_in):
                if not arrs:
                    continue
                if pack.multi:
                    vecs, exists, live, lens = arrs
                else:
                    vecs, exists, live = arrs
                n_loc = vecs.shape[0]
                if pack.multi and pack.quant == "int8":
                    scores = maxsim_ops.maxsim_scores_int8_batch_body(
                        vecs, scales_in[vi], offsets_in[vi], lens,
                        qv_in, qmask_in)
                elif pack.multi:
                    scores = maxsim_ops.maxsim_scores_batch_body(
                        vecs, lens, qv_in, qmask_in)
                elif pack.quant == "int8":
                    scores = vector_ops.cosine_scores_int8_batch(
                        vecs, scales_in[vi], offsets_in[vi], exists,
                        qv_in)
                else:
                    scores = jnp.where(exists[None, :],
                                       qv_in @ vecs.T, 0.0)
                if not hybrid:
                    scores = scores * boosts_in[:, None]
                elig = exists & live
                masks = jnp.broadcast_to(elig[None, :], (bl, n_loc))
                if fmasks[i] is not None:
                    # the replicated filter mask covers the full
                    # (lexical-padded) doc axis — pad to the vector
                    # lane's shard-divisible width, slice our rows
                    fm = fmasks[i]
                    np_pad_i = n_loc * s_axis
                    if fm.shape[1] < np_pad_i:
                        fm = jnp.pad(
                            fm, ((0, 0), (0, np_pad_i - fm.shape[1])))
                    masks = masks & jax.lax.dynamic_slice_in_dim(
                        fm, sidx * n_loc, n_loc, axis=1)
                ts, td = vector_ops.filtered_topk_batch(
                    scores, masks, min(c_static, n_loc),
                    sidx * n_loc)
                knn_ts.append(ts)
                knn_td.append(td)
                knn_counts = knn_counts + masks.sum(axis=1,
                                                    dtype=jnp.int32)
                vi += 1
            ds, dd = topk_ops.merge_top_k_batch_body(
                knn_ts, knn_td, c_static, vec_bases)
            # ---- cross-chip merge: gather per-shard candidates and
            # re-top-k BEFORE fusion, so the fused ranking sees the
            # same global candidate lists the single-chip lane builds
            ag_s = jax.lax.all_gather(ds, "shard")
            ag_d = jax.lax.all_gather(dd, "shard")
            flat_s = jnp.moveaxis(ag_s, 0, 1).reshape(bl, -1)
            flat_d = jnp.moveaxis(ag_d, 0, 1).reshape(bl, -1)

            def refine(s_row, d_row):
                return blockmax_ops.topk_flat_by_doc(s_row, d_row,
                                                     c_static)
            ds, dd = jax.vmap(refine)(flat_s, flat_d)
            knn_counts = jax.lax.psum(knn_counts, "shard")
            if not hybrid:
                return {"top_scores": ds[:, :k_static],
                        "top_docs": dd[:, :k_static],
                        "count": knn_counts}
            ls, ld = topk_ops.merge_top_k_batch_body(
                lex_ts, lex_td, c_static, bases)
            if cfg.fusion_mode == "weighted":
                ts, td, count = _weighted_fuse_body(
                    ls, ld, ds, dd, boosts_in,
                    float(cfg.lexical_weight), k_static)
            else:
                ts, td, count = _rrf_fuse_body(
                    ls, ld, ds, dd, boosts_in,
                    float(cfg.rank_constant), k_static)
            return {"top_scores": ts, "top_docs": td, "count": count}

        flat_specs = jax.tree.map(lambda _: P(), flats)
        packed_specs = jax.tree.map(lambda _: P("dp"), packeds)
        vec_specs = tuple(tuple(P("shard") for _ in arrs)
                          for arrs in placed)
        qmask_spec = P() if qmask is None else P("dp")
        out_specs = {"top_scores": P("dp"), "top_docs": P("dp"),
                     "count": P("dp")}
        mapped = shard_map_compat(
            step_local, mesh=mesh,
            in_specs=(flat_specs, packed_specs, vec_specs, P(), P(),
                      P("dp"), qmask_spec, P("dp")),
            out_specs=out_specs)

        def run_outer(*a):
            return mapped(a[0], a[1], a[2], a[3], a[4], a[5],
                          a[6] if qmask is not None else None, a[7])
        dummy = jnp.zeros(0, bool) if qmask is None else qmask
        return jax.jit(run_outer).lower(
            flats, packeds, tuple(placed), scales, offsets, qv,
            dummy, boosts)

    fn = _get_compiled(key, compile_fn, lane="knn-mesh",
                       owner=getattr(reader, "engine_uuid", None))
    dummy = jnp.zeros(0, bool) if qmask is None else qmask
    args = (flats, packeds, tuple(placed), scales, offsets, qv, dummy,
            boosts)
    with device_span("knn-mesh-merge",
                     cost=("knn-mesh", key,
                           n_real if n_real is not None else b, b_pad)):
        device_fault_point("knn-mesh-merge")
        out = fn(*args)
    if b_pad != b:
        out = {name: v[:b] for name, v in out.items()}
    return out
